"""Client-side observability: request-phase tracing, metrics, propagation.

The reference client can only *configure* server-side tracing
(``update_trace_settings``) — the client itself is a black box, which is
exactly where production debugging of a KServe v2 data plane happens (is
the latency in serialize, connect, TTFB, or deserialize?). This module is
the consumer for the structured events PR 1/PR 2 already emit (retry
callbacks, breaker transitions, ``PoolEvent``s) and the phase timers the
frontends already capture:

- :class:`Tracer` + :class:`RequestSpan` — a monotonic per-request phase
  timeline (queue → serialize → connect/acquire → send → first-byte →
  recv → deserialize, plus retry-attempt and hedge sub-spans) with
  ``always`` / ``ratio`` / ``slow``-only sampling and a ring buffer of
  recent traces dumpable as Chrome ``trace_event`` JSON
  (``chrome://tracing`` / Perfetto load it directly).
- :class:`MetricsRegistry` — counters, gauges and fixed-bucket latency
  histograms with lock-cheap hot-path increments, rendered as Prometheus
  text exposition (``prometheus_text``) or a JSON snapshot
  (``snapshot``).
- W3C trace context propagation — :func:`format_traceparent` /
  :func:`parse_traceparent`; every frontend injects a ``traceparent``
  header (HTTP) or metadata key (GRPC) when a telemetry object is
  configured, and the in-repo servers honor it by recording a
  server-side access record joined on the same trace id (see
  ``ServerCore.access_records`` and the servers' ``/metrics`` route).
- :class:`Telemetry` — the facade a client/pool/policy shares via
  ``InferenceServerClientBase.configure_telemetry``: pre-wired
  request/error/retry/breaker/ejection/hedge metrics fed by the existing
  resilience and pool event streams.
- :class:`StreamSpan` + :class:`WindowedSketch` + :class:`SLO` — the
  streaming layer: token-level stream tracing (open -> per-attempt TTFT
  -> per-chunk marks -> close/error/reconnect; the hot path is one
  timestamp append per chunk), sliding-window quantile sketches merged
  at scrape time into ``ttft_ms``/``itl_ms``/``stream_duration_ms``
  windowed gauges, and declared SLOs (burn rate + breach gauges). See
  docs/observability.md "Streaming & SLOs".

Pay-for-what-you-use: with no telemetry configured the frontends' hot
paths check one attribute and do nothing else (~0 overhead); with
telemetry enabled the per-call cost is bounded by a handful of
pre-resolved label lookups and one registry-lock critical section (the
committed ``BENCH_OBSERVE.json`` holds the measured numbers).
"""

from __future__ import annotations

import asyncio
import inspect
import itertools
import json
import re
import random
import threading
import time
import weakref
from bisect import bisect_left, bisect_right
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import flight as _flight

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_S",
    "DEFAULT_STREAM_MS_BUCKETS",
    "ENDPOINT_LOAD_FORMAT_HEADER",
    "ENDPOINT_LOAD_HEADER",
    "SHM_FAMILIES",
    "TRACEPARENT_HEADER",
    "Counter",
    "DataPlaneRecorder",
    "EndpointLoad",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RequestSpan",
    "SLO",
    "SLOSpec",
    "StatsCorrelator",
    "StreamSpan",
    "Telemetry",
    "Tracer",
    "WindowedSketch",
    "accepts_client_timeout",
    "dataplane",
    "enable_dataplane",
    "format_traceparent",
    "install_dataplane",
    "make_span_id",
    "make_trace_id",
    "parse_endpoint_load",
    "parse_slo_spec",
    "parse_traceparent",
]

# -- W3C trace context --------------------------------------------------------
TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

_id_rng = random.Random()  # module-level: ids must differ across Telemetry objects


def make_trace_id(rng: Optional[random.Random] = None) -> str:
    """A 16-byte lowercase-hex W3C trace id (never all-zero)."""
    r = rng or _id_rng
    return f"{r.getrandbits(128) or 1:032x}"


def make_span_id(rng: Optional[random.Random] = None) -> str:
    """An 8-byte lowercase-hex W3C span (parent) id (never all-zero)."""
    r = rng or _id_rng
    return f"{r.getrandbits(64) or 1:016x}"


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(value: Optional[str]):
    """``(trace_id, parent_span_id, sampled)`` or None when malformed.

    Per the W3C spec: version ``ff`` and all-zero trace/span ids are
    invalid; unknown flag bits are ignored beyond the sampled bit."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 1)


# -- metrics ------------------------------------------------------------------
# Fixed latency buckets (seconds): 100 µs .. 10 s, roughly 1-2.5-5 decades —
# wide enough for localhost shm round trips and cold-compile outliers alike.
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _percentile_row(values: Sequence[float],
                    percentiles: Sequence[float] = (0.5, 0.99),
                    ) -> Dict[str, float]:
    """count/avg/pN summary of exact samples — the one percentile-index
    convention every breakdown (phase, stream, span dump) shares."""
    from .utils import sorted_percentile

    s = sorted(values)
    row: Dict[str, float] = {"count": len(s)}
    if not s:
        return row
    row["avg"] = round(sum(s) / len(s), 4)
    for q in percentiles:
        row[f"p{int(q * 100)}"] = round(sorted_percentile(s, q), 4)
    return row


class _Series:
    """One labeled time series. Mutations take the registry's shared lock
    (one uncontended acquire per op — "lock-cheap"); the ``_``-prefixed
    unlocked primitives exist so :meth:`Telemetry.finish` can batch a whole
    request's updates under a single acquire."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def _inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def _set(self, value: float) -> None:
        self.value = value

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def get(self) -> float:
        return self.value  # single-slot read: no lock needed


class _HistogramSeries:
    """One labeled histogram: cumulative-on-render fixed buckets + sum/count.

    ``exemplars`` (allocated lazily, only when the owning registry opted
    in) holds the LAST ``(trace_id, value, unix_ts)`` observed per bucket
    — the OpenMetrics-exemplar link from a dashboard bucket straight to a
    retained flight timeline."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count", "exemplars")

    def __init__(self, lock: threading.Lock, buckets: Sequence[float]):
        self._lock = lock
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.exemplars: Optional[List[Optional[Tuple[str, float, float]]]] \
            = None

    def _exemplar(self, idx: int, trace_id: str, value: float) -> None:
        """Record one exemplar on bucket ``idx`` (caller holds the lock)."""
        if self.exemplars is None:
            self.exemplars = [None] * (len(self.buckets) + 1)
        self.exemplars[idx] = (trace_id, value, time.time())

    def observe(self, value: float) -> None:
        with self._lock:
            self._observe(value)

    def _observe(self, value: float) -> None:
        self.counts[bisect_right(self.buckets, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile by linear interpolation inside the owning
        bucket (the usual histogram_quantile estimate). Values beyond the
        last finite edge clamp to it."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        lower = 0.0
        for i, edge in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                frac = (rank - prev) / max(counts[i], 1)
                return lower + (edge - lower) * min(max(frac, 0.0), 1.0)
            lower = edge
        return self.buckets[-1] if self.buckets else lower


# label value the cardinality guard aggregates overflowing series into
OVERFLOW_LABEL = "other"


class _Metric:
    """Shared labeled-family machinery for the three instrument kinds."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for label in labelnames:
            if not _LABEL_RE.match(label):
                raise ValueError(f"invalid label name {label!r}")
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _new_series(self):
        raise NotImplementedError

    def labels(self, *values) -> Any:
        """The series for one label-value tuple (created on first use and
        cached — callers are expected to hold on to hot series).

        Cardinality guard: once this instrument holds the registry's
        ``max_series_per_metric`` distinct label-sets, NEW label-sets are
        not materialized — they aggregate into one ``other`` series (every
        label value :data:`OVERFLOW_LABEL`) and bump the registry's
        dropped-labelsets counter, so unbounded label sources (region
        names, URLs) can never blow up the scrape."""
        return self._resolve(values, fold_overflow=True)

    def try_labels(self, *values) -> Optional[Any]:
        """Like :meth:`labels`, but returns None (still counting the drop)
        when the cardinality cap would fold the label-set into the
        ``other`` series — for instruments where an aggregated value is
        meaningless (per-entity gauges like the ORCA load: a last-writer-
        wins mix of endpoints would also be unremovable by TTL expiry)."""
        return self._resolve(values, fold_overflow=False)

    def _resolve(self, values, fold_overflow: bool,
                 note_drop: bool = True) -> Optional[Any]:
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {key}")
        series = self._series.get(key)
        if series is None:
            dropped = False
            with self._registry._lock:
                series = self._series.get(key)
                if series is None:
                    limit = self._registry.max_series_per_metric
                    if (limit and self.labelnames
                            and len(self._series) >= limit):
                        dropped = True
                        if fold_overflow:
                            key = (OVERFLOW_LABEL,) * len(self.labelnames)
                            series = self._series.get(key)
                    if series is None and (fold_overflow or not dropped):
                        series = self._new_series()
                        self._series[key] = series
            if dropped and note_drop:
                # outside the registry lock: the dropped counter may need
                # to be created, which re-enters _instrument
                self._registry._note_dropped_labelset(self.name)
        return series

    def remove(self, *values) -> bool:
        """Drop one label-set's series (stale-endpoint gauge expiry);
        True when a series was actually removed."""
        key = tuple(str(v) for v in values)
        with self._registry._lock:
            return self._series.pop(key, None) is not None

    def _default(self):
        """The unlabeled series (metrics declared with no label names)."""
        return self.labels()


class Counter(_Metric):
    kind = "counter"

    def _new_series(self):
        return _Series(self._registry._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class Gauge(_Metric):
    kind = "gauge"

    def _new_series(self):
        return _Series(self._registry._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S):
        super().__init__(registry, name, help, labelnames)
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        if len(set(edges)) != len(edges):
            raise ValueError("histogram bucket edges must be distinct")
        self.buckets = edges

    def _new_series(self):
        return _HistogramSeries(self._registry._lock, self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)


class MetricsRegistry:
    """A process-local metric registry with Prometheus + JSON exporters.

    Instruments are created idempotently (asking for an existing name
    returns the existing instrument; a kind/label mismatch raises).
    ``add_collector`` registers a callback run before every export — the
    pool uses it to refresh per-endpoint gauges at scrape time instead of
    on the data path.

    ``max_series_per_metric`` caps the distinct label-sets any one
    instrument may hold (0 disables the cap): past it, new label-sets
    fold into a single ``other`` series and
    ``client_tpu_metrics_dropped_labelsets_total{metric}`` counts the
    overflow resolutions.

    ``exemplars=True`` opts in to OpenMetrics-style exemplars: histogram
    bucket lines grow a `` # {trace_id="..."} value ts`` suffix carrying
    the last trace id observed in that bucket (the request/TTFT
    histograms populate them from the active span), linking any
    dashboard bucket straight to a retained flight timeline
    (``FlightRecorder.find(trace_id)``). Off by default — the plain
    0.0.4 text exposition stays byte-compatible with strict parsers."""

    def __init__(self, max_series_per_metric: int = 512,
                 exemplars: bool = False):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []
        # scrape-drain hooks: called with the finished snapshot dict at
        # the end of every snapshot() — the watchtower's black box drains
        # metric state to disk through this. Empty list = one branch.
        self._drains: List[Callable[[Dict[str, Any]], None]] = []
        self.max_series_per_metric = max(0, int(max_series_per_metric))
        self.exemplars = bool(exemplars)
        self._dropped_labelsets: Optional[Counter] = None

    def _note_dropped_labelset(self, metric_name: str) -> None:
        # created lazily OUTSIDE the registry lock (counter creation
        # re-enters _instrument); races create it idempotently
        counter = self._dropped_labelsets
        if counter is None:
            counter = self._dropped_labelsets = self.counter(
                "client_tpu_metrics_dropped_labelsets_total",
                "Label-set resolutions folded into the 'other' overflow "
                "series by the cardinality guard", ("metric",))
        # note_drop=False: if this counter is itself at the cap, its own
        # overflow fold must not re-note the drop — that recursed forever
        counter._resolve((metric_name,), fold_overflow=True,
                         note_drop=False).inc()

    def _instrument(self, cls, name, help, labelnames, **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different kind or labels")
                return existing
        metric = cls(self, name, help, labelnames, **kwargs)
        with self._lock:
            return self._metrics.setdefault(name, metric)

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._instrument(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._instrument(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                  ) -> Histogram:
        return self._instrument(
            Histogram, name, help, labelnames, buckets=buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def add_drain(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Register a scrape-drain hook: called (outside the registry
        lock, exceptions swallowed) with the snapshot dict at the end of
        every :meth:`snapshot`. The watchtower's crash-safe black box
        subscribes here so metric state survives a ``kill -9``; with no
        drains registered the cost is one empty-list branch."""
        with self._lock:
            self._drains.append(fn)

    def remove_drain(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            try:
                self._drains.remove(fn)
            except ValueError:
                pass

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:  # outside the lock: collectors set gauges
            try:
                fn()
            except Exception:
                pass  # an exporter must never break on a sick collector

    # -- exporters -----------------------------------------------------------
    @staticmethod
    def _exemplar_text(exemplars, idx: int) -> str:
        """The OpenMetrics `` # {trace_id="..."} value ts`` bucket-line
        suffix (empty when exemplars are off or this bucket has none)."""
        if exemplars is None:
            return ""
        entry = exemplars[idx]
        if entry is None:
            return ""
        trace_id, value, ts = entry
        return (f' # {{trace_id="{_escape_label(trace_id)}"}} '
                f"{_fmt_value(value)} {ts:.3f}")

    @staticmethod
    def _labels_text(labelnames, key, extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4). Histogram buckets are
        cumulative and ``+Inf``-terminated, with ``_sum``/``_count``."""
        self._run_collectors()
        lines: List[str] = []
        with self._lock:
            for metric in self._metrics.values():
                if not metric._series:
                    continue
                if metric.help:
                    lines.append(f"# HELP {metric.name} {metric.help}")
                lines.append(f"# TYPE {metric.name} {metric.kind}")
                for key in sorted(metric._series):
                    series = metric._series[key]
                    if metric.kind == "histogram":
                        exemplars = (series.exemplars
                                     if self.exemplars else None)
                        cum = 0
                        for i, (edge, n) in enumerate(
                                zip(series.buckets, series.counts)):
                            cum += n
                            labels = self._labels_text(
                                metric.labelnames, key,
                                f'le="{_fmt_value(edge)}"')
                            lines.append(
                                f"{metric.name}_bucket{labels} {cum}"
                                + self._exemplar_text(exemplars, i))
                        labels = self._labels_text(
                            metric.labelnames, key, 'le="+Inf"')
                        lines.append(
                            f"{metric.name}_bucket{labels} {series.count}"
                            + self._exemplar_text(
                                exemplars, len(series.buckets)))
                        base = self._labels_text(metric.labelnames, key)
                        lines.append(
                            f"{metric.name}_sum{base} "
                            f"{_fmt_value(series.sum)}")
                        lines.append(f"{metric.name}_count{base} "
                                     f"{series.count}")
                    else:
                        labels = self._labels_text(metric.labelnames, key)
                        lines.append(
                            f"{metric.name}{labels} "
                            f"{_fmt_value(series.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """A JSON-ready snapshot (plain dict/list/str/number values only, so
        ``json.loads(json.dumps(snapshot)) == snapshot``)."""
        self._run_collectors()
        out: Dict[str, Any] = {}
        with self._lock:
            for metric in self._metrics.values():
                series_out = []
                for key in sorted(metric._series):
                    series = metric._series[key]
                    labels = dict(zip(metric.labelnames, key))
                    if metric.kind == "histogram":
                        cum = 0
                        buckets = []
                        for edge, n in zip(series.buckets, series.counts):
                            cum += n
                            buckets.append({"le": edge, "count": cum})
                        buckets.append({"le": "+Inf", "count": series.count})
                        row = {
                            "labels": labels,
                            "count": series.count,
                            "sum": series.sum,
                            "buckets": buckets,
                        }
                        if self.exemplars and series.exemplars:
                            edges = list(series.buckets) + ["+Inf"]
                            row["exemplars"] = [
                                {"le": edges[i], "trace_id": ex[0],
                                 "value": ex[1], "ts": ex[2]}
                                for i, ex in enumerate(series.exemplars)
                                if ex is not None
                            ]
                        series_out.append(row)
                    else:
                        series_out.append(
                            {"labels": labels, "value": series.value})
                out[metric.name] = {
                    "kind": metric.kind,
                    "help": metric.help,
                    "series": series_out,
                }
        if self._drains:
            for fn in list(self._drains):
                try:
                    fn(out)
                except Exception:
                    pass  # a sick drain must never break the scrape
        return out

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "MetricsRegistry":
        """Rebuild a registry from a :meth:`snapshot` dict so that
        ``MetricsRegistry.from_snapshot(s).snapshot() == s`` — the
        offline half of the black-box metrics drain: a postmortem (or
        ``doctor --blackbox``) reloads the last scraped state into real
        instruments and queries them as if the process were alive.
        Histogram bucket edges are recovered from the cumulative bucket
        rows (``+Inf`` excluded) and the per-bucket counts decumulated;
        exemplars restore when present. The restored registry has no
        cardinality cap (it holds exactly the series the snapshot did —
        a second fold would corrupt the parity contract)."""
        exemplars = any(
            "exemplars" in row
            for doc in snap.values() for row in doc.get("series", ()))
        reg = cls(max_series_per_metric=0, exemplars=exemplars)
        for name, doc in snap.items():
            kind = doc.get("kind", "untyped")
            help_text = doc.get("help", "")
            series = doc.get("series", [])
            labelnames = tuple(series[0]["labels"]) if series else ()
            if kind == "histogram":
                if series:
                    edges = tuple(float(b["le"])
                                  for b in series[0]["buckets"]
                                  if b["le"] != "+Inf")
                else:
                    edges = DEFAULT_LATENCY_BUCKETS_S
                metric = reg.histogram(name, help_text, labelnames,
                                       buckets=edges)
                for row in series:
                    s = metric.labels(*(row["labels"][n]
                                        for n in labelnames))
                    finite = [b for b in row["buckets"]
                              if b["le"] != "+Inf"]
                    counts = []
                    cum_prev = 0
                    for b in finite:
                        counts.append(int(b["count"]) - cum_prev)
                        cum_prev = int(b["count"])
                    counts.append(int(row["count"]) - cum_prev)
                    s.counts = counts
                    s.sum = float(row["sum"])
                    s.count = int(row["count"])
                    for ex in row.get("exemplars", ()):
                        if s.exemplars is None:
                            s.exemplars = [None] * (len(edges) + 1)
                        idx = (len(edges) if ex["le"] == "+Inf"
                               else list(edges).index(float(ex["le"])))
                        s.exemplars[idx] = (ex["trace_id"], ex["value"],
                                            ex["ts"])
            else:
                factory = reg.gauge if kind == "gauge" else reg.counter
                metric = factory(name, help_text, labelnames)
                for row in series:
                    s = metric.labels(*(row["labels"][n]
                                        for n in labelnames))
                    s.value = float(row["value"])
        return reg


# -- data-plane (shm lifecycle) accounting ------------------------------------
# The byte-level data plane: shared-memory regions created, attached,
# read/written and destroyed by utils.shared_memory / utils.tpu_shared_memory,
# plus the register/unregister RPCs the frontends issue against the server.
SHM_FAMILIES = ("system", "tpu", "cuda")


class _FamilyBinding:
    """Pre-resolved per-family series so one shm op is dict-lookup-free."""

    __slots__ = ("create", "attach", "map_read", "map_write", "destroy",
                 "regions", "bytes_resident", "bytes_peak")

    def __init__(self, rec: "DataPlaneRecorder", family: str):
        self.create = rec.ops.labels(family, "create")
        self.attach = rec.ops.labels(family, "attach")
        self.map_read = rec.ops.labels(family, "map_read")
        self.map_write = rec.ops.labels(family, "map_write")
        self.destroy = rec.ops.labels(family, "destroy")
        self.regions = rec.regions.labels(family)
        self.bytes_resident = rec.bytes_resident.labels(family)
        self.bytes_peak = rec.bytes_peak.labels(family)


class DataPlaneRecorder:
    """shm lifecycle accounting: region create/attach/map/destroy counters,
    bytes-resident/peak gauges, and register/unregister RPC latency.

    The shm utils are module-level (regions are process-global state, not
    client-bound), so the recorder is installed process-globally via
    :func:`install_dataplane` / :func:`enable_dataplane` /
    ``Telemetry.enable_dataplane``. The shm modules' hot paths check one
    module attribute against None and do nothing else when no recorder is
    installed (the same pay-for-what-you-use bar as request telemetry);
    with a recorder installed each op batches its counter/gauge updates
    under ONE registry-lock acquire.

    This is the measure-before-you-optimize baseline for pooled shm
    arenas (ROADMAP item 1): the per-use-site churn the arena will
    eliminate is a committed number, not a hunch."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        reg = registry or MetricsRegistry()
        self.registry = reg
        self._lock = reg._lock  # all series share it: one acquire per op
        self.ops = reg.counter(
            "client_tpu_shm_ops_total",
            "Shared-memory lifecycle operations "
            "(create/attach/map_read/map_write/destroy)",
            ("family", "op"))
        self.regions = reg.gauge(
            "client_tpu_shm_regions",
            "Shared-memory regions currently held by this process",
            ("family",))
        self.bytes_resident = reg.gauge(
            "client_tpu_shm_bytes_resident",
            "Bytes currently resident in held shared-memory regions",
            ("family",))
        self.bytes_peak = reg.gauge(
            "client_tpu_shm_bytes_peak",
            "High-water mark of resident shared-memory bytes", ("family",))
        self.rpc_seconds = reg.histogram(
            "client_tpu_shm_registration_seconds",
            "Client-observed latency of shm register/unregister RPCs",
            ("frontend", "family", "op"))
        self.rpcs = reg.counter(
            "client_tpu_shm_rpcs_total",
            "shm register/unregister RPCs by outcome",
            ("frontend", "family", "op", "outcome"))
        # arena accounting (client_tpu.arena): slab lease hit/miss, leased/
        # free bytes per size class, and registration-cache outcomes —
        # cached-vs-issued is THE number proving registration RPCs/req -> 0
        self.arena_leases = reg.counter(
            "client_tpu_arena_leases_total",
            "Arena slab leases (hit = served from a free slab; "
            "miss = a new region was carved)",
            ("family", "class", "outcome"))
        self.arena_bytes = reg.gauge(
            "client_tpu_arena_bytes",
            "Arena bytes by size class and state (leased/free)",
            ("family", "class", "state"))
        self.arena_registrations = reg.counter(
            "client_tpu_arena_registrations_total",
            "Arena registration-cache outcomes "
            "(issued = RPC sent; cached = served without network; "
            "invalidated = entry dropped on ejection/unregister)",
            ("outcome",))
        self._families = {f: _FamilyBinding(self, f) for f in SHM_FAMILIES}
        # (frontend, family, op, ok) -> (histogram series, counter series)
        self._rpc_cache: Dict[Tuple[str, str, str, bool], Tuple[Any, Any]] = {}
        # (family, class) -> (hit ctr, miss ctr, leased gauge, free gauge)
        self._arena_cache: Dict[Tuple[str, int], Tuple[Any, Any, Any, Any]] = {}
        self._arena_reg_cache: Dict[str, Any] = {}
        # handle identity -> recorded nbytes, for regions whose create/
        # attach THIS recorder saw (destroys of older regions skip the
        # residency decrement instead of stealing it from live ones)
        self._live: Dict[int, int] = {}
        self.started_monotonic = time.monotonic()

    # -- region ops (fed by the shm utils; one lock acquire each) ------------
    def on_create(self, family: str, nbytes: int,
                  key: Optional[int] = None) -> None:
        f = self._families[family]
        with self._lock:
            f.create.value += 1
            f.regions.value += 1
            f.bytes_resident.value += nbytes
            if f.bytes_resident.value > f.bytes_peak.value:
                f.bytes_peak.value = f.bytes_resident.value
            if key is not None:
                self._live[key] = nbytes

    def on_attach(self, family: str, nbytes: int,
                  key: Optional[int] = None) -> None:
        # an attach maps the region into THIS process too: it is resident
        # here until its handle is destroyed/detached
        f = self._families[family]
        with self._lock:
            f.attach.value += 1
            f.regions.value += 1
            f.bytes_resident.value += nbytes
            if f.bytes_resident.value > f.bytes_peak.value:
                f.bytes_peak.value = f.bytes_resident.value
            if key is not None:
                self._live[key] = nbytes

    def on_map(self, family: str, write: bool) -> None:
        f = self._families[family]
        with self._lock:
            (f.map_write if write else f.map_read).value += 1

    def on_destroy(self, family: str, nbytes: int,
                   key: Optional[int] = None) -> None:
        f = self._families[family]
        with self._lock:
            f.destroy.value += 1
            if key is not None:
                recorded = self._live.pop(key, None)
                if recorded is None:
                    # region predates this recorder (installed mid-process):
                    # its create was never counted, so its destroy must not
                    # shrink the residency other live regions account for
                    return
                nbytes = recorded
            # clamp at zero for key-less callers: a destroy with no
            # matching on_create must not drive the gauges negative
            f.regions.value = max(f.regions.value - 1, 0)
            f.bytes_resident.value = max(f.bytes_resident.value - nbytes, 0)

    # -- register/unregister RPCs (fed by the four frontends) ----------------
    def on_rpc(self, frontend: str, family: str, op: str, seconds: float,
               ok: bool = True) -> None:
        key = (frontend, family, op, ok)
        cached = self._rpc_cache.get(key)
        if cached is None:
            cached = (self.rpc_seconds.labels(frontend, family, op),
                      self.rpcs.labels(frontend, family, op,
                                       "ok" if ok else "error"))
            self._rpc_cache[key] = cached
        hist, counter = cached
        with self._lock:
            hist._observe(seconds)
            counter.value += 1

    # -- arena ops (fed by client_tpu.arena; one lock acquire each) ----------
    def _arena_series(self, family: str, class_bytes: int):
        key = (family, class_bytes)
        cached = self._arena_cache.get(key)
        if cached is None:
            label = str(class_bytes)
            made = (self.arena_leases.labels(family, label, "hit"),
                    self.arena_leases.labels(family, label, "miss"),
                    self.arena_bytes.labels(family, label, "leased"),
                    self.arena_bytes.labels(family, label, "free"))
            # insert under the registry lock: snapshot() iterates this dict
            # under the same lock, so a first lease of a new class must not
            # mutate it mid-iteration (labels() manages its own locking and
            # is called before the acquire — never nested)
            with self._lock:
                cached = self._arena_cache.setdefault(key, made)
        return cached

    def on_arena_lease(self, family: str, class_bytes: int, hit: bool) -> None:
        hit_c, miss_c, leased_g, free_g = self._arena_series(family, class_bytes)
        with self._lock:
            (hit_c if hit else miss_c).value += 1
            leased_g.value += class_bytes
            free_g.value = max(free_g.value - class_bytes, 0)

    def on_arena_release(self, family: str, class_bytes: int) -> None:
        _, _, leased_g, free_g = self._arena_series(family, class_bytes)
        with self._lock:
            leased_g.value = max(leased_g.value - class_bytes, 0)
            free_g.value += class_bytes

    def on_arena_carve(self, family: str, class_bytes: int,
                       slab_count: int) -> None:
        """A new region was carved into ``slab_count`` free slabs."""
        _, _, _, free_g = self._arena_series(family, class_bytes)
        with self._lock:
            free_g.value += class_bytes * slab_count

    def on_arena_trim(self, family: str, class_bytes: int,
                      slab_count: int) -> None:
        """A fully-free region was destroyed (its slabs leave the pool)."""
        _, _, _, free_g = self._arena_series(family, class_bytes)
        with self._lock:
            free_g.value = max(free_g.value - class_bytes * slab_count, 0)

    def on_arena_registration(self, outcome: str) -> None:
        series = self._arena_reg_cache.get(outcome)
        if series is None:
            made = self.arena_registrations.labels(outcome)
            with self._lock:
                series = self._arena_reg_cache.setdefault(outcome, made)
        with self._lock:
            series.value += 1

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready per-family accounting + RPC totals + churn rate."""
        elapsed = max(time.monotonic() - self.started_monotonic, 1e-9)
        out: Dict[str, Any] = {"elapsed_s": round(elapsed, 3)}
        families: Dict[str, Any] = {}
        total_ops = 0
        with self._lock:
            for name, f in self._families.items():
                ops = (f.create.value + f.attach.value + f.map_read.value
                       + f.map_write.value + f.destroy.value)
                total_ops += ops
                families[name] = {
                    "created": f.create.value,
                    "attached": f.attach.value,
                    "map_reads": f.map_read.value,
                    "map_writes": f.map_write.value,
                    "destroyed": f.destroy.value,
                    "regions": f.regions.value,
                    "bytes_resident": f.bytes_resident.value,
                    "bytes_peak": f.bytes_peak.value,
                }
            rpcs: Dict[str, float] = {}
            for key, series in self.rpcs._series.items():
                _, family, op, outcome = key
                label = f"{family}.{op}.{outcome}"
                rpcs[label] = rpcs.get(label, 0.0) + series.value
                total_ops += series.value
            arena: Dict[str, Any] = {
                "leases": {}, "bytes": {}, "registrations": {}}
            for (family, class_bytes), (hit_c, miss_c, leased_g, free_g) \
                    in self._arena_cache.items():
                arena["leases"][f"{family}.{class_bytes}"] = {
                    "hits": hit_c.value, "misses": miss_c.value}
                arena["bytes"][f"{family}.{class_bytes}"] = {
                    "leased": leased_g.value, "free": free_g.value}
            for outcome, series in self._arena_reg_cache.items():
                arena["registrations"][outcome] = series.value
        out["families"] = families
        out["rpcs"] = rpcs
        if arena["leases"] or arena["registrations"]:
            out["arena"] = arena
        out["churn_ops_per_s"] = round(total_ops / elapsed, 3)
        return out

    def registered_totals(self) -> Dict[str, float]:
        """Per-family successful register RPC counts (perf-row helper)."""
        totals: Dict[str, float] = {}
        with self._lock:
            for key, series in self.rpcs._series.items():
                _, family, op, outcome = key
                if op == "register" and outcome == "ok":
                    totals[family] = totals.get(family, 0.0) + series.value
        return totals


# the process-global recorder the shm utils and frontends consult; None
# keeps their hot paths at one attribute load + None check
_DATAPLANE: Optional[DataPlaneRecorder] = None


def dataplane() -> Optional[DataPlaneRecorder]:
    """The installed process-global data-plane recorder, if any."""
    return _DATAPLANE


def install_dataplane(
        recorder: Optional[DataPlaneRecorder]) -> Optional[DataPlaneRecorder]:
    """Install (or clear, with None) the process-global recorder; returns
    the previous one so scoped users (perf runs, tests) can restore it."""
    global _DATAPLANE
    previous = _DATAPLANE
    _DATAPLANE = recorder
    return previous


def enable_dataplane(
        registry: Optional[MetricsRegistry] = None) -> DataPlaneRecorder:
    """Create a :class:`DataPlaneRecorder` on ``registry`` (or a fresh
    one) and install it process-globally; returns the recorder."""
    recorder = DataPlaneRecorder(registry)
    install_dataplane(recorder)
    return recorder


# -- ORCA endpoint load ingestion ---------------------------------------------
# The server emits per-response load metrics in the ORCA ``endpoint-load-
# metrics`` response header (json or text form) when the client opts in via
# the ``endpoint-load-metrics-format`` request header; parsing them into a
# typed EndpointLoad is the observability half of load-aware routing
# (ROADMAP item 2 — routing on these stays there).
ENDPOINT_LOAD_HEADER = "endpoint-load-metrics"
ENDPOINT_LOAD_FORMAT_HEADER = "endpoint-load-metrics-format"

_ORCA_FORMATS = (None, "json", "text")


class EndpointLoad:
    """One parsed ORCA load report: a flat ``{metric: float}`` mapping
    (nested maps like ``named_metrics`` flatten to dotted keys)."""

    __slots__ = ("metrics", "format", "received_monotonic")

    def __init__(self, metrics: Dict[str, float], format: str):
        self.metrics = metrics
        self.format = format
        self.received_monotonic = time.monotonic()

    def get(self, name: str, default: Optional[float] = None):
        return self.metrics.get(name, default)

    def age_s(self) -> float:
        return max(time.monotonic() - self.received_monotonic, 0.0)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "metrics": dict(self.metrics),
            "format": self.format,
            "age_s": round(self.age_s(), 3),
        }

    def __repr__(self) -> str:
        return f"EndpointLoad({self.metrics!r}, format={self.format!r})"


def _load_value(value) -> Optional[float]:
    if isinstance(value, bool):
        return float(value)
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    # NaN / inf are not reportable load values
    if f != f or f in (float("inf"), float("-inf")):
        return None
    return f


def parse_endpoint_load(value: Optional[str],
                        fmt: Optional[str] = None) -> Optional[EndpointLoad]:
    """Parse an ORCA ``endpoint-load-metrics`` header value.

    ``fmt`` forces ``"json"`` or ``"text"``; None sniffs (a leading ``{``
    is json). Unknown keys are preserved verbatim; malformed values are
    skipped, never raised; a value with nothing parseable returns None
    (as does a missing header), so ingestion causes no gauge churn on
    garbage."""
    if not value or not isinstance(value, str):
        return None
    text = value.strip()
    metrics: Dict[str, float] = {}
    if fmt == "json" or (fmt is None and text.startswith("{")):
        try:
            obj = json.loads(text)
        except ValueError:
            return None
        if not isinstance(obj, dict):
            return None
        for key, val in obj.items():
            if isinstance(val, dict):  # named_metrics / utilization maps
                for sub, subval in val.items():
                    f = _load_value(subval)
                    if f is not None:
                        metrics[f"{key}.{sub}"] = f
            else:
                f = _load_value(val)
                if f is not None:
                    metrics[str(key)] = f
        return EndpointLoad(metrics, "json") if metrics else None
    for part in text.split(","):
        key, sep, val = part.partition("=")
        if not sep:
            continue
        key = key.strip()
        f = _load_value(val.strip())
        if key and f is not None:
            metrics[key] = f
    return EndpointLoad(metrics, "text") if metrics else None


# -- tracing ------------------------------------------------------------------
# Canonical phase vocabulary (what each transport can observe of it):
#   queue       time waiting for a worker/slot before the request is built
#   admission_queue  time parked in the pool's admission controller
#               (client_tpu.admission; acquire -> admit — stashed by the
#               pool and claimed by the endpoint client's span)
#   coalesce_queue  time parked in the micro-batching dispatcher's queue
#               before the coalesced wire request was issued
#               (client_tpu.batch; enqueue -> claim)
#   serialize   request body/tensor marshaling
#   connect     TCP/TLS/channel establishment (when separable)
#   send        request bytes on the wire (when separable from ttfb)
#   ttfb        request issued -> first response byte (HTTP: headers;
#               GRPC unary: the whole call, send+server+receive)
#   recv        response body read
#   deserialize response unmarshaling into InferResult
#   attempt     one resilient attempt (sub-span; repeated under retries —
#               and one per SHARD on a sharded logical request, so
#               phase_breakdown's attempt row is the slowest-shard leg)
#   shard_scatter  slicing + arena staging + dispatch of the per-shard
#               requests of one sharded logical infer (client_tpu.shard)
#   shard_gather   shard-response exactness checks + logical-result
#               assembly after the last shard landed
#   cache_lookup   response-cache/singleflight key probe (client_tpu.cache;
#               a hit's span is ONLY this phase — no wire leg at all)
REQUEST_PHASES = (
    "queue", "admission_queue", "coalesce_queue", "cache_lookup",
    "serialize", "connect", "send", "ttfb", "recv", "deserialize",
    "attempt", "shard_scatter", "shard_gather",
)


class RequestSpan:
    """One client request's span: ids, phase intervals, point events.

    ``phase(name, start_ns, end_ns)`` appends an interval (monotonic
    ``time.perf_counter_ns`` values); ``event(name, **attrs)`` appends a
    point annotation (retries, hedges, reconnects). Both are plain list
    appends — cheap enough for the hot path. ``events`` and ``tid`` are
    populated lazily (most requests have no point events, and the thread
    id is only needed when the span is retained for a trace dump)."""

    __slots__ = ("trace_id", "span_id", "frontend", "model", "op",
                 "start_ns", "end_ns", "phases", "events", "sampled",
                 "error", "tid", "flight")

    def __init__(self, trace_id: str, span_id: str, frontend: str,
                 model: str, op: str, sampled: bool):
        # end_ns / events / error / tid / flight are set lazily off the
        # hot path (finish, event(), trace retention, flight-recorder
        # ownership); readers use getattr defaults
        self.trace_id = trace_id
        self.span_id = span_id
        self.frontend = frontend
        self.model = model
        self.op = op
        self.start_ns = time.perf_counter_ns()
        self.phases: List[Tuple[str, int, int]] = []
        self.sampled = sampled

    def phase(self, name: str, start_ns: int, end_ns: int) -> None:
        self.phases.append((name, start_ns, end_ns))

    def event(self, name: str, **attrs) -> None:
        events = getattr(self, "events", None)
        if events is None:
            events = self.events = []
        events.append((name, time.perf_counter_ns(), attrs or None))

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, self.sampled)

    def duration_s(self) -> float:
        end = getattr(self, "end_ns", 0) or time.perf_counter_ns()
        return (end - self.start_ns) * 1e-9

    def as_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "frontend": self.frontend,
            "model": self.model,
            "op": self.op,
            "start_ns": self.start_ns,
            "end_ns": getattr(self, "end_ns", 0),
            "duration_ms": round(self.duration_s() * 1e3, 6),
            "error": getattr(self, "error", None),
            "phases": [
                {"name": n, "start_ns": s, "end_ns": e,
                 "duration_ms": round((e - s) / 1e6, 6)}
                for n, s, e in self.phases
            ],
            "events": [
                {"name": n, "ns": ts, **(attrs or {})}
                for n, ts, attrs in (getattr(self, "events", None) or ())
            ],
        }


# -- streaming spans ----------------------------------------------------------
class _StreamAttempt:
    """One transport attempt of a stream (the initial open, or one
    reconnect): its open timestamp plus the raw chunk-arrival marks."""

    __slots__ = ("start_ns", "marks")

    def __init__(self, start_ns: int):
        self.start_ns = start_ns
        self.marks: List[int] = []


class StreamSpan:
    """One client stream's span: open -> first-chunk (TTFT) -> per-chunk
    marks -> close/error/reconnect.

    The hot path is :meth:`mark` — one ``perf_counter_ns`` plus one list
    append on the CURRENT attempt (the bound-method indirection is rebound
    by :meth:`reconnect`, so marking never branches on attempt state).
    Everything derived — TTFT, inter-chunk latencies, per-attempt splits —
    is computed at fold/scrape time, never per chunk.

    Reconnects open a new sub-attempt: TTFT and inter-chunk gaps are
    always computed WITHIN one attempt, so a retried stream never folds
    reconnect backoff into TTFT and the gap across a reconnect never
    poisons the inter-chunk distribution."""

    __slots__ = ("trace_id", "span_id", "frontend", "model", "op",
                 "start_ns", "end_ns", "attempts", "events", "sampled",
                 "error", "abandoned", "tid", "_mark")

    def __init__(self, trace_id: str, span_id: str, frontend: str,
                 model: str, op: str, sampled: bool):
        # end_ns / events / error / abandoned / tid set lazily off the hot
        # path; readers use getattr defaults (same pattern as RequestSpan)
        self.trace_id = trace_id
        self.span_id = span_id
        self.frontend = frontend
        self.model = model
        self.op = op
        self.start_ns = time.perf_counter_ns()
        first = _StreamAttempt(self.start_ns)
        self.attempts: List[_StreamAttempt] = [first]
        self.sampled = sampled
        self._mark = first.marks.append

    def mark(self) -> None:
        """Record one chunk/token arrival (the ≤2 µs/mark hot path)."""
        self._mark(time.perf_counter_ns())

    def reconnect(self, abandoned: int = 0, resent: int = 0) -> None:
        """Open a reconnect sub-attempt; subsequent marks land in it."""
        attempt = _StreamAttempt(time.perf_counter_ns())
        self.attempts.append(attempt)
        self._mark = attempt.marks.append
        self.event("reconnect", attempt=len(self.attempts) - 1,
                   abandoned=abandoned, resent=resent)

    def event(self, name: str, **attrs) -> None:
        events = getattr(self, "events", None)
        if events is None:
            events = self.events = []
        events.append((name, time.perf_counter_ns(), attrs or None))

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, self.sampled)

    # -- derived views (fold/scrape side, never the chunk path) --------------
    @property
    def chunk_count(self) -> int:
        return sum(len(a.marks) for a in self.attempts)

    def marks_ns(self) -> List[int]:
        """All chunk marks in arrival order (attempts concatenated)."""
        out: List[int] = []
        for attempt in self.attempts:
            out.extend(attempt.marks)
        return out

    def ttft_ms_per_attempt(self) -> List[float]:
        """Open->first-chunk per attempt that saw a chunk — recorded per
        reconnect attempt so retries never inflate TTFT."""
        return [(a.marks[0] - a.start_ns) / 1e6
                for a in self.attempts if a.marks]

    def itl_values_ms(self) -> List[float]:
        """Inter-chunk gaps, computed within each attempt only (a gap that
        spans a reconnect is transport recovery, not token latency)."""
        out: List[float] = []
        for attempt in self.attempts:
            marks = attempt.marks
            for i in range(1, len(marks)):
                out.append((marks[i] - marks[i - 1]) / 1e6)
        return out

    def duration_s(self) -> float:
        end = getattr(self, "end_ns", 0) or time.perf_counter_ns()
        return (end - self.start_ns) * 1e-9

    @property
    def phases(self) -> List[Tuple[str, int, int]]:
        """Tracer-compatible interval view: one ``attempt`` interval per
        transport attempt plus its ``ttft`` window."""
        end_ns = getattr(self, "end_ns", 0)
        out: List[Tuple[str, int, int]] = []
        for i, attempt in enumerate(self.attempts):
            nxt = (self.attempts[i + 1].start_ns
                   if i + 1 < len(self.attempts) else end_ns)
            last = attempt.marks[-1] if attempt.marks else (
                nxt or attempt.start_ns)
            out.append(("attempt", attempt.start_ns, last))
            if attempt.marks:
                out.append(("ttft", attempt.start_ns, attempt.marks[0]))
        return out

    def as_dict(self) -> Dict[str, Any]:
        itl = self.itl_values_ms()
        itl_summary: Dict[str, Any] = _percentile_row(itl)
        if itl:
            itl_summary["max"] = round(max(itl), 4)
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "frontend": self.frontend,
            "model": self.model,
            "op": self.op,
            "start_ns": self.start_ns,
            "end_ns": getattr(self, "end_ns", 0),
            "duration_ms": round(self.duration_s() * 1e3, 6),
            "error": getattr(self, "error", None),
            "abandoned": bool(getattr(self, "abandoned", False)),
            "chunks": self.chunk_count,
            "reconnects": len(self.attempts) - 1,
            "ttft_ms": [round(v, 4) for v in self.ttft_ms_per_attempt()],
            "itl_ms": itl_summary,
            "attempts": [
                {"start_ns": a.start_ns, "chunks": len(a.marks)}
                for a in self.attempts
            ],
            "phases": [
                {"name": n, "start_ns": s, "end_ns": e,
                 "duration_ms": round((e - s) / 1e6, 6)}
                for n, s, e in self.phases
            ],
            "events": [
                {"name": n, "ns": ts, **(attrs or {})}
                for n, ts, attrs in (getattr(self, "events", None) or ())
            ],
        }


# -- sliding-window quantile sketch -------------------------------------------
# Fixed millisecond bucket edges for the windowed stream metrics: 50 µs ..
# 30 s — SSE token gaps on localhost sit at the bottom, cold-compile first
# tokens at the top.
DEFAULT_STREAM_MS_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class WindowedSketch:
    """A sliding-window quantile sketch: a ring of fixed-bucket
    sub-windows, merged at read time.

    ``observe`` lands one value in the current sub-window (a bisect plus
    an increment under the sketch lock — this runs on the FOLD/scrape
    side, never the per-chunk path). Readers merge the live sub-windows
    and interpolate quantiles; values older than ``window_s`` age out as
    their sub-window is recycled. Rotation is lazy on both paths under
    the same lock, so a scrape concurrent with a rotation sees either the
    pre- or post-rotation window — never a torn one.
    """

    __slots__ = ("buckets", "window_s", "subwindows", "_sub_s", "_counts",
                 "_sums", "_ns", "_period", "_lock", "_clock")

    def __init__(self, window_s: float = 300.0, subwindows: int = 6,
                 buckets: Sequence[float] = DEFAULT_STREAM_MS_BUCKETS,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        if subwindows < 1:
            raise ValueError("subwindows must be >= 1")
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges or len(set(edges)) != len(edges):
            raise ValueError("buckets must be non-empty and distinct")
        self.buckets = edges
        self.window_s = float(window_s)
        self.subwindows = int(subwindows)
        self._sub_s = self.window_s / self.subwindows
        self._counts = [[0] * (len(edges) + 1) for _ in range(subwindows)]
        self._sums = [0.0] * subwindows
        self._ns = [0] * subwindows
        self._period: Optional[int] = None
        self._lock = threading.Lock()
        self._clock = clock

    def _rotate_locked(self) -> int:
        """Advance to the current period, recycling expired sub-windows;
        returns the live slot index. Caller holds the lock."""
        period = int(self._clock() / self._sub_s)
        if self._period is None:
            self._period = period
        elif period > self._period:
            empty = len(self.buckets) + 1
            for i in range(1, min(period - self._period, self.subwindows) + 1):
                slot = (self._period + i) % self.subwindows
                self._counts[slot] = [0] * empty
                self._sums[slot] = 0.0
                self._ns[slot] = 0
            self._period = period
        return self._period % self.subwindows

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            slot = self._rotate_locked()
            # bisect_left: a value EQUAL to an edge lands in that edge's
            # ≤-bucket (Prometheus ``le`` semantics) — fraction_le(edge)
            # is then exact, which the SLO good/bad split relies on (its
            # single bucket edge IS the threshold)
            self._counts[slot][bisect_left(self.buckets, value)] += 1
            self._sums[slot] += value
            self._ns[slot] += 1

    def merged(self) -> Tuple[List[int], int, float]:
        """(per-bucket counts, total count, sum) over the live window."""
        with self._lock:
            self._rotate_locked()
            counts = [0] * (len(self.buckets) + 1)
            for sub in self._counts:
                for i, n in enumerate(sub):
                    counts[i] += n
            return counts, sum(self._ns), sum(self._sums)

    def count(self) -> int:
        return self.merged()[1]

    def quantile(self, q: float) -> float:
        """Windowed quantile via linear interpolation inside the owning
        bucket (same estimate as ``_HistogramSeries.quantile``)."""
        counts, total, _ = self.merged()
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        lower = 0.0
        for i, edge in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                frac = (rank - prev) / max(counts[i], 1)
                return lower + (edge - lower) * min(max(frac, 0.0), 1.0)
            lower = edge
        return self.buckets[-1]

    def fraction_le(self, edge: float) -> float:
        """The windowed fraction of values <= ``edge`` (exact when
        ``edge`` is a bucket edge — the SLO good/bad split)."""
        counts, total, _ = self.merged()
        if total == 0:
            return 1.0
        idx = bisect_right(self.buckets, float(edge))
        return sum(counts[:idx]) / total

    def merged_recent(self, window_s: float) -> Tuple[List[int], int, float]:
        """(per-bucket counts, total count, sum) over only the NEWEST
        sub-windows covering the last ``window_s`` seconds — the fast-
        window tap behind multi-window burn-rate alerting
        (``client_tpu.watch``): one sketch answers both the slow (full-
        window) and fast (recent sub-windows) burn question without a
        second ingest path. ``window_s`` rounds UP to whole sub-windows
        (never narrower than asked), clamped to the full window."""
        with self._lock:
            self._rotate_locked()
            k = min(self.subwindows,
                    max(1, int(-(-float(window_s) // self._sub_s))))
            counts = [0] * (len(self.buckets) + 1)
            total = 0
            total_sum = 0.0
            period = self._period or 0
            for i in range(k):
                slot = (period - i) % self.subwindows
                for j, n in enumerate(self._counts[slot]):
                    counts[j] += n
                total += self._ns[slot]
                total_sum += self._sums[slot]
            return counts, total, total_sum

    def quantile_recent(self, q: float, window_s: float) -> float:
        """:meth:`quantile` over only the last ``window_s`` seconds (the
        changepoint watchdog's per-tick sample)."""
        counts, total, _ = self.merged_recent(window_s)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        lower = 0.0
        for i, edge in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                frac = (rank - prev) / max(counts[i], 1)
                return lower + (edge - lower) * min(max(frac, 0.0), 1.0)
            lower = edge
        return self.buckets[-1]

    def fraction_le_recent(self, edge: float, window_s: float) -> float:
        """:meth:`fraction_le` over only the last ``window_s`` seconds
        (the FAST half of a multi-window burn evaluation)."""
        counts, total, _ = self.merged_recent(window_s)
        if total == 0:
            return 1.0
        idx = bisect_right(self.buckets, float(edge))
        return sum(counts[:idx]) / total

    def snapshot(self) -> Dict[str, Any]:
        """A JSON-pure snapshot (``json.loads(json.dumps(s)) == s``) that
        :meth:`from_snapshot` restores bit-for-bit."""
        with self._lock:
            self._rotate_locked()
            return {
                "window_s": self.window_s,
                "subwindows": self.subwindows,
                "buckets_ms": list(self.buckets),
                "counts": [list(sub) for sub in self._counts],
                "sums": list(self._sums),
                "ns": list(self._ns),
                "period": self._period,
            }

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any],
                      clock: Callable[[], float] = time.monotonic,
                      ) -> "WindowedSketch":
        sketch = cls(snap["window_s"], snap["subwindows"],
                     snap["buckets_ms"], clock=clock)
        sketch._counts = [list(sub) for sub in snap["counts"]]
        sketch._sums = list(snap["sums"])
        sketch._ns = list(snap["ns"])
        sketch._period = snap["period"]
        return sketch


class SLO:
    """One declared latency objective, e.g. ``ttft_p95 < 200ms over 5m``.

    ``objective`` is the target good fraction (0.95 means 95% of events
    must land under ``threshold_ms``). Stream metrics (``ttft_ms``,
    ``itl_ms``, ``stream_duration_ms``) are fed from finished
    :class:`StreamSpan`\\ s; ``request_ms`` is fed from finished unary
    :class:`RequestSpan`\\ s (an errored request always counts bad — see
    :meth:`observe_failure`). The tracker counts every observed
    event good/bad (cumulative counters), keeps a windowed good/bad split
    (a :class:`WindowedSketch` whose single bucket edge IS the
    threshold), and exports at scrape time:

    - ``client_tpu_slo_events_total{slo,outcome}`` — cumulative counters;
    - ``client_tpu_slo_burn_rate{slo}`` — windowed bad fraction over the
      error budget (``1 - objective``); burning exactly the budget is 1.0;
    - ``client_tpu_slo_breached{slo}`` — 1 when the windowed burn rate
      exceeds 1 (the declared quantile currently misses the threshold).
    """

    __slots__ = ("name", "metric", "threshold_ms", "objective", "window_s",
                 "frontend", "window", "good", "bad")

    def __init__(self, name: str, metric: str = "ttft_ms",
                 threshold_ms: float = 200.0, objective: float = 0.95,
                 window_s: float = 300.0, frontend: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if metric not in ("ttft_ms", "itl_ms", "stream_duration_ms",
                          "request_ms"):
            raise ValueError(f"unknown SLO metric {metric!r}")
        if threshold_ms <= 0:
            raise ValueError("threshold_ms must be > 0")
        self.name = name
        self.metric = metric
        self.threshold_ms = float(threshold_ms)
        self.objective = float(objective)
        self.window_s = float(window_s)
        self.frontend = frontend
        # single bucket edge == threshold: counts[0] is good, counts[1] bad
        self.window = WindowedSketch(
            window_s, buckets=(self.threshold_ms,), clock=clock)
        self.good = None  # counters bound by the owning Telemetry
        self.bad = None

    def observe(self, value_ms: float) -> None:
        self.window.observe(value_ms)
        if value_ms <= self.threshold_ms:
            if self.good is not None:
                self.good.inc()
        elif self.bad is not None:
            self.bad.inc()

    def observe_failure(self) -> None:
        """Count one errored request as a bad event: an error violates a
        latency objective whatever its measured duration (a fast 500 is
        not 'within SLO'). The window sees a finite beyond-threshold
        value so sums/snapshots stay JSON-pure."""
        self.window.observe(self.threshold_ms * 2.0)
        if self.bad is not None:
            self.bad.inc()

    def burn_rate(self, window_s: Optional[float] = None) -> float:
        """Windowed bad fraction over the error budget. ``window_s``
        restricts the read to the newest sub-windows of the same sketch
        (the FAST window of multi-window burn alerting — see
        ``client_tpu.watch``); None reads the full declared window."""
        if window_s is None:
            bad_fraction = 1.0 - self.window.fraction_le(self.threshold_ms)
        else:
            bad_fraction = 1.0 - self.window.fraction_le_recent(
                self.threshold_ms, window_s)
        return bad_fraction / (1.0 - self.objective)

    def breached(self) -> bool:
        return self.burn_rate() > 1.0

    def report(self) -> Dict[str, Any]:
        """Good/bad accounting as one JSON-pure row. Counts come from the
        cumulative counters when bound (exact over a bounded replay run
        on a fresh Telemetry — the capacity harness's contract), else
        from the live window. ``attained`` is the bounded-window verdict:
        the bad fraction fits inside the error budget — and requires at
        least one event: a declared objective that was never measured is
        NOT met (certifying an unmeasured SLO is the dishonest option)."""
        if self.good is not None and self.bad is not None:
            good = int(self.good.get())
            bad = int(self.bad.get())
        else:
            counts, total, _ = self.window.merged()
            good = int(counts[0])
            bad = int(total - counts[0])
        total = good + bad
        bad_fraction = (bad / total) if total else 0.0
        return {
            "slo": self.name,
            "metric": self.metric,
            "threshold_ms": self.threshold_ms,
            "objective": self.objective,
            "good": good,
            "bad": bad,
            "events": total,
            "bad_fraction": round(bad_fraction, 6),
            "attained": total > 0
            and bad_fraction <= (1.0 - self.objective) + 1e-12,
            "burn_rate": round(self.burn_rate(), 4),
            "breached": self.breached(),
        }


@dataclass
class SLOSpec:
    """A parsed capacity-SLO declaration (see :func:`parse_slo_spec`).

    ``kind`` is ``"latency"`` (declare via :meth:`Telemetry.track_slo`
    with ``metric``/``threshold_ms``/``objective``) or ``"error_rate"``
    (``limit`` is the max tolerated error fraction; evaluated by the
    replay harness from its shed/error accounting, not a latency window).
    """

    spec: str
    kind: str
    metric: Optional[str] = None
    threshold_ms: Optional[float] = None
    objective: Optional[float] = None
    limit: Optional[float] = None

    @property
    def name(self) -> str:
        return self.spec


_SLO_ERROR_RATE_RE = re.compile(
    r"^\s*error_rate\s*<\s*(?P<value>\d+(?:\.\d+)?)\s*(?P<pct>%)?\s*$")
_SLO_LATENCY_RE = re.compile(
    r"^\s*(?:(?P<name>[a-z_]+?)_?)?p(?P<pct>\d{2,4})\s*<\s*"
    r"(?P<value>\d+(?:\.\d+)?)\s*(?P<unit>ms|s)\s*$")

_SLO_METRICS = {
    "ttft": "ttft_ms",
    "itl": "itl_ms",
    "stream_duration": "stream_duration_ms",
    "duration": "stream_duration_ms",
    "latency": "request_ms",
    "request": "request_ms",
}


def parse_slo_spec(spec: str) -> SLOSpec:
    """Parse one declared SLO, e.g. ``ttft_p95<200ms``, ``p99<50ms``,
    ``itl_p99<20ms``, ``error_rate<0.1%``. Latency specs name a metric
    (``ttft``/``itl``/``duration``/``latency``; bare ``pNN`` means
    end-to-end request latency), a percentile, and a threshold in ``ms``
    or ``s``; ``error_rate`` takes ``%`` or a bare fraction."""
    m = _SLO_ERROR_RATE_RE.match(spec)
    if m is not None:
        limit = float(m.group("value"))
        if m.group("pct"):
            limit /= 100.0
        if not 0.0 <= limit < 1.0:
            raise ValueError(f"error_rate limit out of range: {spec!r}")
        return SLOSpec(spec=spec.strip(), kind="error_rate", limit=limit)
    m = _SLO_LATENCY_RE.match(spec)
    if m is None:
        raise ValueError(
            f"malformed SLO spec {spec!r} (want e.g. ttft_p95<200ms, "
            f"p99<50ms, error_rate<0.1%)")
    name, pct, value, unit = (m.group("name"), m.group("pct"),
                              float(m.group("value")), m.group("unit"))
    metric = _SLO_METRICS.get(name) if name else "request_ms"
    if metric is None:
        raise ValueError(
            f"unknown SLO metric {name!r} in {spec!r} "
            f"(one of {sorted(_SLO_METRICS)} or error_rate)")
    # p95 -> 0.95, p999 -> 0.999. The digit count IS the precision, so a
    # trailing-zero form like p100 would misparse to 0.10 — requiring the
    # objective to land in [0.5, 1) rejects p100/p05 instead of silently
    # certifying a 10%-good "SLO"
    objective = int(pct) / (10.0 ** len(pct))
    if not 0.5 <= objective < 1.0:
        raise ValueError(
            f"percentile out of range in {spec!r} (want p50..p99...)")
    threshold_ms = value * 1000.0 if unit == "s" else value
    if threshold_ms <= 0:
        raise ValueError(f"threshold must be > 0: {spec!r}")
    return SLOSpec(spec=spec.strip(), kind="latency", metric=metric,
                   threshold_ms=threshold_ms, objective=objective)


class Tracer:
    """Ring buffer of recently finished request spans + dump formats."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, capacity))
        self.dropped = 0

    def keep(self, span: RequestSpan) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)

    def recent(self, count: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._ring)
        if count is not None:
            spans = spans[-count:]
        return [s.as_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome ``trace_event`` JSON (load in chrome://tracing/Perfetto):
        one complete ("X") event per request span, nested complete events
        per phase, instant ("i") events for retries/hedges.

        The ring is snapshotted under ONE lock acquire (``list(deque)``)
        so a dump racing the hot path's ``keep`` never sees a torn deque,
        and the emitted events are sorted by start timestamp — two
        concurrent scrapes produce the same, time-ordered stream instead
        of an interleaving that depends on finish order."""
        with self._lock:
            spans = list(self._ring)
        events: List[Dict[str, Any]] = []
        for span in spans:
            name = f"{span.op} {span.model}".strip()
            end_ns = getattr(span, "end_ns", 0) or span.start_ns
            tid = getattr(span, "tid", 0)
            error = getattr(span, "error", None)
            args: Dict[str, Any] = {
                "trace_id": span.trace_id, "span_id": span.span_id,
            }
            if error:
                args["error"] = error
            events.append({
                "name": name, "cat": span.frontend, "ph": "X",
                "ts": span.start_ns / 1e3,
                "dur": max(end_ns - span.start_ns, 0) / 1e3,
                "pid": 1, "tid": tid, "args": args,
            })
            for pname, s, e in span.phases:
                events.append({
                    "name": pname, "cat": "phase", "ph": "X",
                    "ts": s / 1e3, "dur": max(e - s, 0) / 1e3,
                    "pid": 1, "tid": tid,
                })
            for ename, ts, attrs in (getattr(span, "events", None) or ()):
                events.append({
                    "name": ename, "cat": "event", "ph": "i",
                    "ts": ts / 1e3, "s": "t", "pid": 1, "tid": tid,
                    "args": attrs or {},
                })
        # stable time-order: spans land in the ring in FINISH order, so an
        # early-started-late-finished span would otherwise appear after
        # requests it preceded (and a dump concurrent with another scrape
        # would interleave differently per call)
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_json(self) -> str:
        return json.dumps(self.chrome_trace(), separators=(",", ":"))


# -- the facade ---------------------------------------------------------------
_SAMPLE_MODES = ("always", "ratio", "slow", "off")


class _FrontendBinding:
    """Pre-resolved hot-path series for one frontend label value, so
    ``Telemetry.finish`` does dict lookups instead of label resolution."""

    __slots__ = ("requests", "request_seconds", "phase_series")

    def __init__(self, tel: "Telemetry", frontend: str):
        self.requests = tel.requests_total.labels(frontend)
        self.request_seconds = tel.request_seconds.labels(frontend)
        self.phase_series: Dict[str, _HistogramSeries] = {
            name: tel.phase_seconds.labels(frontend, name)
            for name in REQUEST_PHASES
        }


class Telemetry:
    """One telemetry object shared by frontends, pools and policies.

    ``sample``: which finished spans the tracer ring retains — ``always``,
    ``ratio`` (keep ``sample_ratio`` of requests, decided at span start so
    the traceparent sampled flag matches), ``slow`` (keep only requests
    slower than ``slow_threshold_s``), or ``off`` (metrics only). Metrics
    are always recorded; sampling gates only trace retention.

    ``orca_format``: ``"json"`` or ``"text"`` makes every frontend this
    telemetry is configured on opt in to ORCA per-response load metrics
    (the ``endpoint-load-metrics-format`` request header); parsed reports
    export as ``client_tpu_endpoint_load{url,metric}`` gauges and surface
    in ``PoolClient.endpoint_stats()``. Endpoints silent for longer than
    ``orca_ttl_s`` have their load gauges expired at scrape time.

    ``flight``: a :class:`~client_tpu.flight.FlightRecorder` (or ``True``
    for one with defaults) arms the flight recorder: every layer records
    a per-request causal event timeline, and a tail-based verdict at
    completion retains the requests worth explaining (errors, sheds, SLO
    breaches, the rolling slow tail, a baseline sample) in a bounded
    ring — see docs/observability.md "Flight recorder & postmortems".
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        sample: str = "always",
        sample_ratio: float = 0.01,
        slow_threshold_s: float = 0.25,
        trace_capacity: int = 256,
        rng: Optional[random.Random] = None,
        stream_window_s: float = 300.0,
        orca_format: Optional[str] = None,
        orca_ttl_s: float = 60.0,
        flight: Any = None,
    ):
        if sample not in _SAMPLE_MODES:
            raise ValueError(
                f"unknown sample mode {sample!r} (one of {_SAMPLE_MODES})")
        if orca_format not in _ORCA_FORMATS:
            raise ValueError(
                f"unknown orca_format {orca_format!r} (one of json|text)")
        self.registry = registry or MetricsRegistry()
        self.tracer = Tracer(trace_capacity)
        if flight is True:
            flight = _flight.FlightRecorder()
        self.flight = flight
        if flight is not None:
            flight.bind(self)
        self.sample = sample
        self.sample_ratio = sample_ratio
        self.slow_threshold_s = slow_threshold_s
        self._rng = rng or random.Random()
        reg = self.registry
        # -- pre-wired client instruments ------------------------------------
        self.requests_total = reg.counter(
            "client_tpu_requests_total",
            "Requests finished (success or error) per frontend",
            ("frontend",))
        self.request_errors_total = reg.counter(
            "client_tpu_request_errors_total",
            "Requests finished with an error, by fault domain",
            ("frontend", "domain"))
        self.request_seconds = reg.histogram(
            "client_tpu_request_seconds",
            "End-to-end client request latency", ("frontend",))
        self.phase_seconds = reg.histogram(
            "client_tpu_phase_seconds",
            "Per-phase client latency (serialize/ttfb/recv/deserialize/...)",
            ("frontend", "phase"))
        self.retries_total = reg.counter(
            "client_tpu_retries_total",
            "Resilient re-attempts across all policies")
        self.fast_fails_total = reg.counter(
            "client_tpu_breaker_fast_fails_total",
            "Requests shed by an open circuit breaker")
        self.breaker_transitions_total = reg.counter(
            "client_tpu_breaker_transitions_total",
            "Circuit breaker state transitions", ("state",))
        self.stream_reconnects_total = reg.counter(
            "client_tpu_stream_reconnects_total",
            "GRPC bidi stream auto-reconnects")
        self.stream_abandoned_sequences_total = reg.counter(
            "client_tpu_stream_abandoned_sequences_total",
            "Sequence requests abandoned by a stream reconnect "
            "(never re-sent)")
        self.streams_total = reg.counter(
            "client_tpu_streams_total",
            "Streams finished (success, error or abandoned) per frontend",
            ("frontend",))
        self.stream_errors_total = reg.counter(
            "client_tpu_stream_errors_total",
            "Streams finished with an error, by fault domain",
            ("frontend", "domain"))
        self.stream_abandoned_total = reg.counter(
            "client_tpu_stream_abandoned_total",
            "Streams abandoned by the consumer before exhaustion",
            ("frontend",))
        self.stream_chunks_total = reg.counter(
            "client_tpu_stream_chunks_total",
            "Chunks/tokens received across all streams", ("frontend",))
        self.pool_ejections_total = reg.counter(
            "client_tpu_pool_ejections_total",
            "Passive outlier ejections per endpoint", ("url",))
        self.pool_readmissions_total = reg.counter(
            "client_tpu_pool_readmissions_total",
            "Ejection-window expiries / proven-healthy readmissions",
            ("url",))
        self.pool_health_changes_total = reg.counter(
            "client_tpu_pool_health_changes_total",
            "Active ready-probe health flips per endpoint", ("url",))
        self.pool_sequence_abandoned_total = reg.counter(
            "client_tpu_pool_sequence_abandoned_total",
            "Sequence requests abandoned mid-flight (never re-sent)",
            ("url",))
        # -- response integrity (client_tpu.integrity) ------------------------
        self.integrity_checks_total = reg.counter(
            "client_tpu_integrity_checks_total",
            "Individual contract checks performed on responses",
            ("kind", "url"))
        self.integrity_violations_total = reg.counter(
            "client_tpu_integrity_violations_total",
            "Responses failing contract validation, by violated check",
            ("kind", "url"))
        self.pool_quarantines_total = reg.counter(
            "client_tpu_pool_quarantines_total",
            "Byzantine-replica quarantines (repeated INVALID responses)",
            ("url",))
        self.hedges_fired_total = reg.counter(
            "client_tpu_hedges_fired_total",
            "Hedge copies issued to a second replica")
        self.hedge_wins_total = reg.counter(
            "client_tpu_hedge_wins_total",
            "Requests won by a hedge copy (not the primary)")
        self.hedge_losses_total = reg.counter(
            "client_tpu_hedge_losses_total",
            "Requests where the primary beat an in-flight hedge")
        # -- sharded scatter-gather (client_tpu.shard) ------------------------
        self.shard_requests_total = reg.counter(
            "client_tpu_shard_requests_total",
            "Sharded LOGICAL requests finished (success or error) per "
            "frontend", ("frontend",))
        self.shard_subrequests_total = reg.counter(
            "client_tpu_shard_subrequests_total",
            "Per-shard requests issued by the scatter-gather layer, by "
            "pinned endpoint", ("url",))
        self.shard_failed_total = reg.counter(
            "client_tpu_shard_failed_total",
            "Logical requests failed by a shard (the whole request fails "
            "— never a partial gather), by the failing pinned endpoint",
            ("url",))
        self.shard_skew_seconds = reg.histogram(
            "client_tpu_shard_skew_seconds",
            "Slowest-minus-fastest shard completion skew per successful "
            "logical request (the scatter-gather straggler cost)")
        # -- admission control (client_tpu.admission) -------------------------
        self.admission_shed_total = reg.counter(
            "client_tpu_admission_shed_total",
            "Requests shed by admission control, by priority lane and "
            "shed reason (saturated/deadline/queue_full/queue_timeout/"
            "endpoint_saturated)", ("lane", "reason"))
        self.admission_admitted_total = reg.counter(
            "client_tpu_admission_admitted_total",
            "Requests admitted by admission control, by priority lane",
            ("lane",))
        self._admission_limit_gauge = reg.gauge(
            "client_tpu_admission_limit",
            "Live adaptive concurrency limit per attached controller",
            ("scope",))
        self._admission_inflight_gauge = reg.gauge(
            "client_tpu_admission_inflight",
            "In-flight requests holding an admission slot", ("scope",))
        self._admission_queue_depth_gauge = reg.gauge(
            "client_tpu_admission_queue_depth",
            "Waiters parked in each priority lane's LIFO admission queue",
            ("scope", "lane"))
        self._admission_ctrls: List[Any] = []  # (weakref, scope) pairs
        self._admission_collector_installed = False
        # -- multi-cell federation (client_tpu.federation) --------------------
        self.federation_spill_total = reg.counter(
            "client_tpu_federation_spill_total",
            "Requests the home cell could not serve that transparently "
            "landed on another cell, by home cell, target cell and spill "
            "reason (saturated/down/error)", ("cell", "target", "reason"))
        self.federation_shadow_total = reg.counter(
            "client_tpu_federation_shadow_total",
            "Shadow-mirrored requests by outcome (matched/diverged/"
            "errors are compared responses; skipped = mirror dropped at "
            "the pending bound)", ("outcome",))
        self.federation_canary_total = reg.counter(
            "client_tpu_federation_canary_total",
            "Canary-split outcomes (routed/fallback/rollback)",
            ("outcome",))
        self._federations: List[Any] = []  # (weakref, scope) pairs
        self._federation_collector_installed = False
        self._federation_gauges: Optional[Dict[str, Gauge]] = None
        self._bindings: Dict[str, _FrontendBinding] = {}
        self._pools: List[Any] = []
        self._pools_lock = threading.Lock()
        self._pool_gauges: Optional[Dict[str, Gauge]] = None
        # -- hot-path fast lanes ---------------------------------------------
        # mode flags instead of string compares; cheap unique ids: span ids
        # are a random 64-bit base xor a GIL-atomic counter, trace ids a
        # random 64-bit hex prefix + the counter (W3C needs uniqueness and
        # non-zero; the per-object random prefix keeps ids distinct across
        # processes without paying getrandbits(128)+format per request)
        self._sample_ratio_mode = sample == "ratio"
        self._sample_slow_mode = sample == "slow"
        self._sample_off = sample == "off"
        self._trace_prefix = f"{self._rng.getrandbits(64) or 1:016x}"
        # itertools.count.__next__ is a single C call: each concurrent
        # caller receives a DISTINCT value (a python `seq += 1; read seq`
        # pair could hand two threads the same id)
        self._next_seq = itertools.count(1).__next__
        # finished spans queue here (lock-free GIL-atomic deque appends) and
        # fold into the counters/histograms on the SCRAPER's thread (via
        # the collector below) — the request path never pays the histogram
        # math. _FOLD_BACKLOG bounds memory when nothing scrapes: past it,
        # the unlucky request folds the backlog inline (amortized, rare).
        self._pending: deque = deque()
        self.registry.add_collector(self._fold_pending)
        # -- streaming: windowed sketches + SLOs ------------------------------
        # finished stream spans queue exactly like request spans (lock-free
        # deque, folded on the scraper's thread); the windowed ttft/itl/
        # duration sketches and any declared SLOs are fed AT FOLD TIME —
        # the per-chunk hot path is only StreamSpan.mark()
        self.stream_window_s = stream_window_s
        self._pending_streams: deque = deque()
        self._stream_windows: Dict[Tuple[str, str], WindowedSketch] = {}
        self._endpoint_ttft: Dict[str, WindowedSketch] = {}
        self._windows_lock = threading.Lock()
        self._slos: List[SLO] = []
        # request_ms SLOs resolved once: _fold_pending pays one truthiness
        # check when none are declared
        self._request_slos: List[SLO] = []
        self._window_quantile_gauge = reg.gauge(
            "client_tpu_stream_window_ms",
            f"Windowed stream latency quantiles (last "
            f"{stream_window_s:g}s, merged at scrape time)",
            ("metric", "frontend", "quantile"))
        self._window_count_gauge = reg.gauge(
            "client_tpu_stream_window_count",
            "Samples in the live window per windowed stream metric",
            ("metric", "frontend"))
        self._endpoint_ttft_gauge = reg.gauge(
            "client_tpu_pool_endpoint_ttft_ms",
            "Windowed per-endpoint generate_stream TTFT quantiles "
            "(fed by the pool, merged at scrape time)",
            ("url", "quantile"))
        self._slo_events = reg.counter(
            "client_tpu_slo_events_total",
            "SLO events by outcome", ("slo", "outcome"))
        self._slo_burn_gauge = reg.gauge(
            "client_tpu_slo_burn_rate",
            "Windowed bad fraction over the error budget (1.0 = burning "
            "exactly the budget)", ("slo",))
        self._slo_breached_gauge = reg.gauge(
            "client_tpu_slo_breached",
            "1 when the declared objective currently misses its threshold "
            "over the window", ("slo",))
        self.registry.add_collector(self._fold_stream_pending)
        self.registry.add_collector(self._collect_stream_windows)
        # -- ORCA endpoint load ----------------------------------------------
        # frontends read orca_format to decide whether to request the
        # header; ingestion works regardless (a caller may opt in manually
        # via per-request headers)
        self.orca_format = orca_format
        self.orca_ttl_s = float(orca_ttl_s)
        self._orca_lock = threading.Lock()
        self._orca_loads: Dict[str, EndpointLoad] = {}
        self._orca_gauge = reg.gauge(
            "client_tpu_endpoint_load",
            "Latest ORCA per-response load report per endpoint "
            f"(expired after {orca_ttl_s:g}s of silence)",
            ("url", "metric"))
        self._orca_reports = reg.counter(
            "client_tpu_endpoint_load_reports_total",
            "ORCA load reports ingested per endpoint", ("url",))
        self._orca_parse_errors = reg.counter(
            "client_tpu_endpoint_load_parse_errors_total",
            "ORCA headers that failed to parse", ("url",))
        self.registry.add_collector(self._expire_orca)

    _FOLD_BACKLOG = 32768
    _WINDOW_QUANTILES = ((0.5, "p50"), (0.9, "p90"), (0.95, "p95"),
                         (0.99, "p99"))

    # -- span lifecycle ------------------------------------------------------
    def begin(self, frontend: str, model: str = "",
              op: str = "infer") -> RequestSpan:
        """Open a request span. The sampled flag reflects ``ratio`` mode at
        start time (``slow`` keeps the flag set: the decision happens at
        finish, and servers record access on any traceparent)."""
        sampled = True
        if self._sample_ratio_mode:
            sampled = self._rng.random() < self.sample_ratio
        elif self._sample_off:
            sampled = False
        suffix = f"{self._next_seq():016x}"
        # one client span per trace, so the span id can reuse the trace
        # suffix: unique within the trace (trivially) and across this
        # object's traces (the counter), never all-zero (seq starts at 1)
        return RequestSpan(
            self._trace_prefix + suffix, suffix,
            frontend, model, op, sampled)

    def _binding(self, frontend: str) -> _FrontendBinding:
        binding = self._bindings.get(frontend)
        if binding is None:
            binding = _FrontendBinding(self, frontend)
            self._bindings[frontend] = binding
        return binding

    def finish(self, span: Optional[RequestSpan],
               error: Optional[BaseException] = None) -> None:
        """Close the span. The hot path is one timestamp, the trace-ring
        decision, and a lock-free deque append; the counter/histogram fold
        is deferred to scrape time (or amortized once the backlog passes
        ``_FOLD_BACKLOG``). This is the per-request overhead
        BENCH_OBSERVE.json measures."""
        if span is None:
            return
        end_ns = span.end_ns = time.perf_counter_ns()
        total_s = (end_ns - span.start_ns) * 1e-9
        if error is not None:
            from .resilience import classify_fault  # no import cycle: lazy

            span.error = f"{type(error).__name__}: {error}"[:256]
            pending = (span, total_s, classify_fault(error))
        else:
            pending = (span, total_s, None)
        self._pending.append(pending)
        if self._sample_slow_mode:
            if total_s >= self.slow_threshold_s:
                span.tid = threading.get_ident()
                self.tracer.keep(span)
        elif span.sampled:
            span.tid = threading.get_ident()
            self.tracer.keep(span)
        if self.flight is not None:
            # the wire span's completion lands on the flight timeline it
            # was BOUND to at _obs_begin (failover/hedge outers see each
            # attempt's end) — membership-gated, because finish() is not
            # always called on the originating thread: the batch
            # dispatcher settles EVERY coalesced caller's span on the
            # leader's thread, and fanning those foreign completions onto
            # the leader's active scratch would corrupt its timeline
            active = _flight._SCRATCH.get()
            if (active is not None and not active.committed
                    and span.trace_id in active.trace_ids):
                if error is not None:
                    active.append("span", "finish",
                                  ms=round(total_s * 1e3, 3),
                                  error=type(error).__name__)
                else:
                    active.append("span", "finish",
                                  ms=round(total_s * 1e3, 3))
            scratch = getattr(span, "flight", None)
            if scratch is not None:
                self.flight.commit(scratch, error=error)
        if len(self._pending) >= self._FOLD_BACKLOG:
            self._fold_pending()

    def _fold_pending(self) -> None:
        """Drain finished spans into the metric series. Runs at scrape time
        (registry collector), at the amortization threshold, or on demand;
        concurrent folders are safe — ``popleft`` hands each record to
        exactly one of them."""
        pending = self._pending
        if not pending:
            return
        lock = self.registry._lock
        while True:
            try:
                span, total_s, domain = pending.popleft()
            except IndexError:
                return
            binding = self._binding(span.frontend)
            err_series = None
            if domain is not None:
                err_series = self.request_errors_total.labels(
                    span.frontend, domain)
            phases = span.phases
            phase_series = binding.phase_series
            for name, _, _ in phases:  # rare: non-canonical phase name
                if name not in phase_series:
                    phase_series[name] = self.phase_seconds.labels(
                        span.frontend, name)
            req_hist = binding.request_seconds
            exemplars_on = self.registry.exemplars
            with lock:
                binding.requests.value += 1
                bucket = bisect_right(req_hist.buckets, total_s)
                req_hist.counts[bucket] += 1
                req_hist.sum += total_s
                req_hist.count += 1
                if exemplars_on:
                    req_hist._exemplar(bucket, span.trace_id, total_s)
                if err_series is not None:
                    err_series.value += 1
                for name, s, e in phases:
                    seconds = (e - s) * 1e-9
                    if seconds < 0.0:
                        seconds = 0.0
                    h = phase_series[name]
                    bucket = bisect_right(h.buckets, seconds)
                    h.counts[bucket] += 1
                    h.sum += seconds
                    h.count += 1
                    if exemplars_on:
                        h._exemplar(bucket, span.trace_id, seconds)
            if self._request_slos:
                for slo in self._request_slos:
                    if (slo.frontend is not None
                            and slo.frontend != span.frontend):
                        continue
                    if domain is not None:
                        slo.observe_failure()
                    else:
                        slo.observe(total_s * 1e3)
            # windowed request-latency tap: the same sliding-sketch family
            # the stream metrics use, keyed ``request_ms`` — the
            # watchtower's changepoint stream and the fast-window burn
            # evaluation read it (fold-side: never the per-request path)
            self._stream_window("request_ms", span.frontend).observe(
                total_s * 1e3)

    # -- stream span lifecycle ----------------------------------------------
    def begin_stream(self, frontend: str, model: str = "",
                     op: str = "generate_stream") -> StreamSpan:
        """Open a stream span (same id scheme and sampling decision as
        :meth:`begin`)."""
        sampled = True
        if self._sample_ratio_mode:
            sampled = self._rng.random() < self.sample_ratio
        elif self._sample_off:
            sampled = False
        suffix = f"{self._next_seq():016x}"
        return StreamSpan(
            self._trace_prefix + suffix, suffix, frontend, model, op, sampled)

    def finish_stream(self, span: Optional[StreamSpan],
                      error: Optional[BaseException] = None,
                      abandoned: bool = False) -> None:
        """Close a stream span (idempotent: a span can be finished by a
        terminal stream error and again by ``stop_stream``/``close`` — the
        first close wins). Counter/sketch folding is deferred to scrape
        time exactly like :meth:`finish`."""
        if span is None or getattr(span, "end_ns", 0):
            return
        end_ns = span.end_ns = time.perf_counter_ns()
        total_s = (end_ns - span.start_ns) * 1e-9
        domain = None
        if error is not None:
            from .resilience import classify_fault  # no import cycle: lazy

            span.error = f"{type(error).__name__}: {error}"[:256]
            domain = classify_fault(error)
        if abandoned:
            span.abandoned = True
        self._pending_streams.append((span, domain))
        if self._sample_slow_mode:
            if total_s >= self.slow_threshold_s:
                span.tid = threading.get_ident()
                self.tracer.keep(span)
        elif span.sampled:
            span.tid = threading.get_ident()
            self.tracer.keep(span)
        if self.flight is not None:
            # streams never hold a scratch open across the generator's
            # life; the recorder synthesizes the timeline (attempts +
            # reconnect events) from the finished span and verdicts it
            self.flight.commit_stream(span, error=error,
                                      abandoned=abandoned)
        if len(self._pending_streams) >= self._FOLD_BACKLOG:
            self._fold_stream_pending()

    def _stream_window(self, metric: str, frontend: str) -> WindowedSketch:
        key = (metric, frontend)
        window = self._stream_windows.get(key)
        if window is None:
            with self._windows_lock:
                window = self._stream_windows.setdefault(
                    key, WindowedSketch(self.stream_window_s))
        return window

    def _fold_stream_pending(self) -> None:
        """Drain finished stream spans into counters, windowed sketches
        and SLOs. Runs at scrape time (registry collector) or at the
        backlog threshold; ``popleft`` keeps concurrent folders safe."""
        pending = self._pending_streams
        while True:
            try:
                span, domain = pending.popleft()
            except IndexError:
                return
            frontend = span.frontend
            self.streams_total.labels(frontend).inc()
            chunks = span.chunk_count
            if chunks:
                self.stream_chunks_total.labels(frontend).inc(chunks)
            if domain is not None:
                self.stream_errors_total.labels(frontend, domain).inc()
            if getattr(span, "abandoned", False):
                self.stream_abandoned_total.labels(frontend).inc()
            ttfts = span.ttft_ms_per_attempt()
            itls = span.itl_values_ms()
            duration_ms = span.duration_s() * 1e3
            samples = (("ttft_ms", ttfts), ("itl_ms", itls),
                       ("stream_duration_ms", (duration_ms,)))
            for metric, values in samples:
                if not values:
                    continue
                window = self._stream_window(metric, frontend)
                for value in values:
                    if value >= 0.0:  # clock skew guard: never a negative
                        window.observe(value)
            for slo in self._slos:
                if slo.frontend is not None and slo.frontend != frontend:
                    continue
                for metric, values in samples:
                    if metric != slo.metric:
                        continue
                    if metric == "stream_duration_ms" and domain is not None:
                        # an errored stream's duration is short BECAUSE it
                        # was truncated — feeding it would count a failed
                        # session as a fast (good) one. The session did
                        # not complete inside the objective: bad.
                        slo.observe_failure()
                        continue
                    if metric == "ttft_ms" and domain is not None \
                            and not values:
                        # a stream that DIED before its first chunk has no
                        # TTFT sample, but it did not meet the objective —
                        # same rule as an errored unary request: an error
                        # always counts bad, never nothing. (Measured
                        # ttft/itl samples from partially-failed streams
                        # stay valid token-timing observations and feed
                        # normally.)
                        slo.observe_failure()
                        continue
                    for value in values:
                        if value >= 0.0:
                            slo.observe(value)

    def _collect_stream_windows(self) -> None:
        """Scrape-time collector: merge the windowed sketches into
        quantile gauges (no hot-path percentile math anywhere)."""
        with self._windows_lock:
            windows = list(self._stream_windows.items())
            endpoints = list(self._endpoint_ttft.items())
        for (metric, frontend), window in windows:
            self._window_count_gauge.labels(metric, frontend).set(
                window.count())
            for q, label in self._WINDOW_QUANTILES:
                self._window_quantile_gauge.labels(
                    metric, frontend, label).set(round(window.quantile(q), 4))
        for url, window in endpoints:
            for q, label in self._WINDOW_QUANTILES:
                self._endpoint_ttft_gauge.labels(url, label).set(
                    round(window.quantile(q), 4))
        for slo in self._slos:
            burn = slo.burn_rate()
            self._slo_burn_gauge.labels(slo.name).set(round(burn, 4))
            self._slo_breached_gauge.labels(slo.name).set(
                1.0 if burn > 1.0 else 0.0)

    # -- SLOs ----------------------------------------------------------------
    def track_slo(self, name: str, metric: str = "ttft_ms",
                  threshold_ms: float = 200.0, objective: float = 0.95,
                  window_s: Optional[float] = None,
                  frontend: Optional[str] = None) -> SLO:
        """Declare a streaming SLO (e.g. ``ttft_p95 < 200ms over 5m`` is
        ``track_slo("ttft_p95", "ttft_ms", 200, objective=0.95,
        window_s=300)``). Returns the tracker; its good/bad counters,
        burn rate and breach gauge export on every scrape."""
        slo = SLO(name, metric, threshold_ms, objective,
                  window_s if window_s is not None else self.stream_window_s,
                  frontend)
        slo.good = self._slo_events.labels(name, "good")
        slo.bad = self._slo_events.labels(name, "bad")
        self._slos.append(slo)
        if metric == "request_ms":
            self._request_slos.append(slo)
        return slo

    def slos(self) -> List[SLO]:
        return list(self._slos)

    def stream_windows(self) -> Dict[Tuple[str, str], WindowedSketch]:
        """The live windowed sketches keyed ``(metric, frontend)``,
        including the ``request_ms`` tap — the watchtower's changepoint
        detectors sample these per tick."""
        with self._windows_lock:
            return dict(self._stream_windows)

    def slo_report(self) -> List[Dict[str, Any]]:
        """One :meth:`SLO.report` row per declared SLO, after folding any
        pending spans — so a bounded replay run (fresh Telemetry, read
        once at the end) gets exact good/bad counts over exactly that
        run, without requiring a scrape."""
        self._fold_pending()
        self._fold_stream_pending()
        return [slo.report() for slo in self._slos]

    # -- pool TTFT feed -------------------------------------------------------
    def observe_endpoint_ttft(self, url: str, ttft_ms: float) -> None:
        """Record one stream's TTFT against the endpoint that served it
        (fed by ``PoolClient.generate_stream`` once per stream) so
        ejection decisions have a latency signal per replica."""
        if ttft_ms < 0.0:
            return
        window = self._endpoint_ttft.get(url)
        if window is None:
            with self._windows_lock:
                window = self._endpoint_ttft.setdefault(
                    url, WindowedSketch(self.stream_window_s))
        window.observe(ttft_ms)

    # -- ORCA endpoint load ---------------------------------------------------
    def ingest_endpoint_load(self, url: str, header_value: Optional[str],
                             fmt: Optional[str] = None,
                             ) -> Optional[EndpointLoad]:
        """Ingest one response's ORCA header for ``url``. A missing header
        (None) touches nothing — no gauge churn; a malformed one counts a
        parse error. Returns the parsed :class:`EndpointLoad`, if any."""
        if header_value is None:
            return None
        load = parse_endpoint_load(header_value, fmt or self.orca_format)
        if load is None:
            self._orca_parse_errors.labels(url).inc()
            return None
        gauge = self._orca_gauge
        reports = self._orca_reports.labels(url)
        with self._orca_lock:
            # gauge writes stay under the lock: two concurrent reports for
            # one url must not interleave (the loser could resurrect a
            # series the winner just removed, orphaning it forever).
            # try_labels: a load folded into the cardinality-overflow
            # series would be a meaningless endpoint mix AND unremovable
            # by the TTL expiry — drop it (counted) instead
            previous = self._orca_loads.get(url)
            self._orca_loads[url] = load
            # resolve series first (lock-free once cached), then write the
            # whole report under ONE registry-lock acquire — per-metric
            # series.set() would take it once per metric per response
            writes = [(series, value)
                      for name, value in load.metrics.items()
                      if (series := gauge.try_labels(url, name)) is not None]
            vanished = ([name for name in previous.metrics
                         if name not in load.metrics]
                        if previous is not None else [])
            with self.registry._lock:
                for series, value in writes:
                    series._set(value)
                reports._inc()
                for name in vanished:  # metric left the report
                    gauge._series.pop((url, name), None)
        return load

    def endpoint_loads(self) -> Dict[str, EndpointLoad]:
        """The un-expired latest load report per endpoint url."""
        now = time.monotonic()
        with self._orca_lock:
            return {url: load for url, load in self._orca_loads.items()
                    if now - load.received_monotonic <= self.orca_ttl_s}

    def _expire_orca(self) -> None:
        """Scrape-time collector: drop load gauges for endpoints that have
        not reported within ``orca_ttl_s`` (a stale load number is worse
        than no number — it looks current). Removal happens under
        ``_orca_lock``, the same invariant ``ingest_endpoint_load`` keeps:
        an ingest racing the expiry must not have its fresh gauges
        deleted."""
        now = time.monotonic()
        with self._orca_lock:
            for url, load in list(self._orca_loads.items()):
                if now - load.received_monotonic > self.orca_ttl_s:
                    del self._orca_loads[url]
                    for name in load.metrics:
                        self._orca_gauge.remove(url, name)

    # -- data plane -----------------------------------------------------------
    def enable_dataplane(self) -> DataPlaneRecorder:
        """Install a process-global :class:`DataPlaneRecorder` on THIS
        telemetry's registry (shm accounting shows up in its scrapes);
        returns the recorder. See :func:`install_dataplane` to restore a
        previous one."""
        return enable_dataplane(self.registry)

    # -- resilience observer protocol (duck-typed from resilience.py) --------
    def on_retry(self, attempt: int, exc: BaseException,
                 delay_s: float) -> None:
        self.retries_total.inc()

    def on_fast_fail(self) -> None:
        self.fast_fails_total.inc()

    def on_breaker_transition(self, state: str) -> None:
        self.breaker_transitions_total.labels(state).inc()

    def on_stream_reconnect(self, event=None) -> None:
        """Exactly-once bridge for ``resilience.StreamReconnected``: the
        reconnecting stream calls this (with the event) BEFORE the user
        callback sees it, so the counters move once per reconnect and the
        abandoned-sequence count is never lost even when the application
        swallows the event."""
        self.stream_reconnects_total.inc()
        abandoned = getattr(event, "abandoned_request_ids", None)
        if abandoned:
            self.stream_abandoned_sequences_total.inc(len(abandoned))

    def on_shard_subrequest(self, url: str) -> None:
        self.shard_subrequests_total.labels(url).inc()

    def on_shard_result(self, frontend: str,
                        skew_s: Optional[float] = None) -> None:
        """One sharded logical request finished (either way); ``skew_s``
        (successes only) is the slowest-minus-fastest shard interval."""
        self.shard_requests_total.labels(frontend).inc()
        if skew_s is not None:
            self.shard_skew_seconds.observe(max(0.0, skew_s))

    def on_shard_failed(self, url: str) -> None:
        self.shard_failed_total.labels(url).inc()

    def on_hedge_fired(self) -> None:
        self.hedges_fired_total.inc()

    def on_hedge_result(self, hedge_won: bool) -> None:
        (self.hedge_wins_total if hedge_won
         else self.hedge_losses_total).inc()

    def attach(self, policy) -> Any:
        """Wire a ``resilience.ResiliencePolicy`` (and its breaker) into
        this telemetry object; returns the policy for chaining."""
        policy.observer = self
        breaker = getattr(policy, "breaker", None)
        if breaker is not None:
            breaker.on_transition = self.on_breaker_transition
        return policy

    # -- admission bridge -----------------------------------------------------
    def on_admission_admit(self, lane: str, waited_s: float) -> None:
        self.admission_admitted_total.labels(lane).inc()

    def on_admission_shed(self, lane: str, reason: str) -> None:
        self.admission_shed_total.labels(lane, reason).inc()

    def attach_admission(self, controller, scope: str = "pool") -> Any:
        """Wire an ``admission.AdmissionController`` into this telemetry:
        its sheds/admits feed ``client_tpu_admission_shed_total{lane,
        reason}`` / ``..._admitted_total{lane}``, and the live limit,
        in-flight count and per-lane queue depths export as gauges at
        scrape time (held by weak reference, like pools). Returns the
        controller for chaining."""
        controller.observer = self
        with self._pools_lock:
            # disambiguate: two pools sharing one Telemetry must not
            # export colliding {scope=...} gauges where the last-collected
            # controller silently stands in for both
            taken = {s for ref, s in self._admission_ctrls
                     if ref() is not None}
            if scope in taken:
                n = 2
                while f"{scope}#{n}" in taken:
                    n += 1
                scope = f"{scope}#{n}"
            self._admission_ctrls.append((weakref.ref(controller), scope))
            if not self._admission_collector_installed:
                self._admission_collector_installed = True
                self.registry.add_collector(self._collect_admission)
        return controller

    def pools(self) -> List[Any]:
        """The live registered pools (dead weakrefs skipped) — the
        watchtower's breaker/quarantine watermark gauges read their
        ``watch_gauges()``/health summaries."""
        with self._pools_lock:
            refs = list(self._pools)
        return [pool for pool in (ref() for ref in refs)
                if pool is not None]

    def admission_controllers(self) -> List[Any]:
        """The live attached controllers (dead weakrefs skipped) —
        doctor's admission section reads their snapshots."""
        with self._pools_lock:
            refs = list(self._admission_ctrls)
        out = []
        for ref, scope in refs:
            ctrl = ref()
            if ctrl is not None:
                out.append((ctrl, scope))
        return out

    def _collect_admission(self) -> None:
        dead = []
        with self._pools_lock:
            refs = list(self._admission_ctrls)
        for entry in refs:
            ref, scope = entry
            ctrl = ref()
            if ctrl is None:
                dead.append(entry)
                continue
            try:
                snap = ctrl.snapshot()
            except Exception:
                continue  # one sick controller must not break the scrape
            self._admission_limit_gauge.labels(scope).set(snap["limit"])
            self._admission_inflight_gauge.labels(scope).set(
                snap["inflight"])
            for lane, row in snap["lanes"].items():
                self._admission_queue_depth_gauge.labels(scope, lane).set(
                    row["depth"])
        if dead:
            with self._pools_lock:
                for entry in dead:
                    try:
                        self._admission_ctrls.remove(entry)
                    except ValueError:
                        pass

    # -- federation bridge ----------------------------------------------------
    def on_cell_spill(self, cell: str, target: str, reason: str) -> None:
        self.federation_spill_total.labels(cell, target, reason).inc()

    def on_shadow_result(self, outcome: str) -> None:
        self.federation_shadow_total.labels(outcome).inc()

    def on_canary(self, outcome: str) -> None:
        self.federation_canary_total.labels(outcome).inc()

    def attach_federation(self, fed, scope: str = "federation") -> Any:
        """Wire a ``federation.FederatedClient`` into this telemetry:
        spills/shadow verdicts/canary transitions feed the
        ``client_tpu_federation_*`` counters (the federation calls the
        ``on_*`` hooks above directly, exactly once per event), and the
        per-cell health/spill-state/canary-weight gauges export at
        scrape time from ``federation_stats()`` (held by weak reference,
        like pools). Called by the federation constructor; returns the
        federation for chaining."""
        with self._pools_lock:
            if self._federation_gauges is None:
                reg = self.registry
                self._federation_gauges = {
                    "healthy": reg.gauge(
                        "client_tpu_federation_cell_healthy",
                        "Healthy (routable) endpoints per cell", ("cell",)),
                    "spill_active": reg.gauge(
                        "client_tpu_federation_cell_spill_active",
                        "1 while the cell's shed-rate hysteresis keeps "
                        "new traffic spilling past it", ("cell",)),
                    "shed_rate": reg.gauge(
                        "client_tpu_federation_cell_shed_rate",
                        "Windowed home-attempt shed rate per cell",
                        ("cell",)),
                    "breaker_state": reg.gauge(
                        "client_tpu_federation_cell_breaker_state",
                        "Cell breaker state (0 closed, 1 half-open, "
                        "2 open)", ("cell",)),
                    "canary_weight": reg.gauge(
                        "client_tpu_federation_canary_weight",
                        "Live canary traffic weight (0 after rollback)",
                        ("cell",)),
                }
            self._federations.append((weakref.ref(fed), scope))
            if not self._federation_collector_installed:
                self._federation_collector_installed = True
                self.registry.add_collector(self._collect_federations)
        return fed

    def federations(self) -> List[Any]:
        """The live attached federations as ``(fed, scope)`` pairs —
        doctor's ``cells`` section reads their ``federation_stats()``."""
        with self._pools_lock:
            refs = list(self._federations)
        out = []
        for ref, scope in refs:
            fed = ref()
            if fed is not None:
                out.append((fed, scope))
        return out

    def _collect_federations(self) -> None:
        _BREAKER_STATE = {"closed": 0, "half_open": 1, "open": 2}
        with self._pools_lock:
            refs = list(self._federations)
            gauges = self._federation_gauges
        if gauges is None:
            return
        dead = []
        for entry in refs:
            ref, _scope = entry
            fed = ref()
            if fed is None:
                dead.append(entry)
                continue
            try:
                stats = fed.federation_stats()
            except Exception:
                continue  # one sick federation must not break the scrape
            for name, row in stats.get("cells", {}).items():
                pool = row.get("pool") or {}
                gauges["healthy"].labels(name).set(pool.get("healthy", 0))
                gauges["spill_active"].labels(name).set(
                    1.0 if row.get("spill_active") else 0.0)
                rate = row.get("shed_rate")
                if rate is not None:
                    gauges["shed_rate"].labels(name).set(rate)
                state = row.get("breaker_state")
                if state is not None:
                    gauges["breaker_state"].labels(name).set(
                        _BREAKER_STATE.get(state, -1))
            canary = stats.get("canary")
            if canary:
                gauges["canary_weight"].labels(canary["cell"]).set(
                    canary.get("weight", 0.0))
        if dead:
            with self._pools_lock:
                for entry in dead:
                    try:
                        self._federations.remove(entry)
                    except ValueError:
                        pass

    # -- response integrity ---------------------------------------------------
    def integrity_checked(self, kind: str, url: str, checks: int = 1) -> None:
        """Count the contract checks one validated response passed."""
        self.integrity_checks_total.labels(kind, url or "").inc(checks)

    def integrity_violation(self, kind: str, url: str) -> None:
        """Count one response that failed contract validation."""
        self.integrity_violations_total.labels(kind, url or "").inc()

    # -- pool bridge ---------------------------------------------------------
    def pool_observer(self, chain: Optional[Callable[[Any], None]] = None,
                      ) -> Callable[[Any], None]:
        """An ``on_event`` callback for ``client_tpu.pool`` that counts
        each typed pool event exactly once, then forwards to ``chain``.
        Matches on type name so this module never imports the pool."""
        counters = {
            "EndpointEjected": self.pool_ejections_total,
            "EndpointQuarantined": self.pool_quarantines_total,
            "EndpointReadmitted": self.pool_readmissions_total,
            "EndpointHealthChanged": self.pool_health_changes_total,
            "SequenceAbandoned": self.pool_sequence_abandoned_total,
        }

        def observe(event) -> None:
            try:
                counter = counters.get(type(event).__name__)
                if counter is not None:
                    counter.labels(event.url).inc()
            finally:
                if chain is not None:
                    chain(event)

        return observe

    def register_pool(self, pool) -> None:
        """Expose a pool's per-endpoint stats (health, ejection, breaker
        state, outstanding, resilience counters) as gauges refreshed at
        scrape time via a registry collector — one Prometheus scrape shows
        ejections, half-open probes and hedge win/loss together.

        Pools are held by weak reference: a long-lived Telemetry shared
        across PoolClient create/close cycles must not pin dead pools (and
        their endpoint clients) in memory or keep scraping them."""
        with self._pools_lock:
            first = self._pool_gauges is None
            if first:
                reg = self.registry
                self._pool_gauges = {
                    "healthy": reg.gauge(
                        "client_tpu_pool_endpoint_healthy",
                        "Active ready-probe verdict (1 healthy)", ("url",)),
                    "ejected": reg.gauge(
                        "client_tpu_pool_endpoint_ejected",
                        "Outlier-ejection state (1 ejected)", ("url",)),
                    "outstanding": reg.gauge(
                        "client_tpu_pool_endpoint_outstanding",
                        "In-flight requests per endpoint", ("url",)),
                    "consecutive_failures": reg.gauge(
                        "client_tpu_pool_endpoint_consecutive_failures",
                        "Consecutive transport failures", ("url",)),
                    "ejection_count": reg.gauge(
                        "client_tpu_pool_endpoint_ejection_count",
                        "Lifetime ejections per endpoint", ("url",)),
                    "breaker_state": reg.gauge(
                        "client_tpu_pool_endpoint_breaker_state",
                        "Breaker state (0 closed, 1 half-open, 2 open)",
                        ("url",)),
                    "resilience": reg.gauge(
                        "client_tpu_pool_endpoint_resilience",
                        "Per-endpoint ResilienceStats counters",
                        ("url", "counter")),
                    "affinity": reg.gauge(
                        "client_tpu_pool_endpoint_affinity",
                        "Affinity-routing counters per endpoint: picks "
                        "landed as home (routed), after deterministic "
                        "re-homing (rehomed), after a bounded-load spill "
                        "(spilled), and the capped distinct-key count "
                        "(keys)", ("url", "counter")),
                }
            self._pools.append(weakref.ref(pool))
            if first:
                self.registry.add_collector(self._collect_pools)

    def _collect_pools(self) -> None:
        _BREAKER_STATE = {"closed": 0, "half_open": 1, "open": 2}
        with self._pools_lock:
            refs = list(self._pools)
            gauges = self._pool_gauges
        if gauges is None:
            return
        dead = []
        for ref in refs:
            pool = ref()
            if pool is None:
                dead.append(ref)
                continue
            try:
                snapshot = pool.snapshot()
            except Exception:
                continue  # one sick pool must not break the whole scrape
            for url, stats in snapshot.items():
                gauges["healthy"].labels(url).set(
                    1.0 if stats["healthy"] else 0.0)
                gauges["ejected"].labels(url).set(
                    1.0 if stats["ejected"] else 0.0)
                gauges["outstanding"].labels(url).set(stats["outstanding"])
                gauges["consecutive_failures"].labels(url).set(
                    stats["consecutive_failures"])
                gauges["ejection_count"].labels(url).set(
                    stats["ejection_count"])
                state = stats.get("breaker_state")
                if state is not None:
                    gauges["breaker_state"].labels(url).set(
                        _BREAKER_STATE.get(state, -1))
                for name, value in stats.get("resilience", {}).items():
                    gauges["resilience"].labels(url, name).set(value)
                for name, value in (stats.get("affinity") or {}).items():
                    gauges["affinity"].labels(url, name).set(value)
        if dead:
            with self._pools_lock:
                for ref in dead:
                    try:
                        self._pools.remove(ref)
                    except ValueError:
                        pass

    # -- introspection -------------------------------------------------------
    def flush(self) -> None:
        """Fold any pending finished spans into the metric series now.
        Exporters (``prometheus_text``/``snapshot``) do this implicitly;
        call it before reading instrument objects directly."""
        self._fold_pending()

    def recent_traces(self, count: Optional[int] = None) -> List[Dict[str, Any]]:
        return self.tracer.recent(count)

    def chrome_trace(self) -> Dict[str, Any]:
        return self.tracer.chrome_trace()

    def dump_json(self) -> str:
        return self.tracer.dump_json()

    def phase_breakdown(self, percentiles: Sequence[float] = (0.5, 0.99),
                        ) -> Dict[str, Dict[str, float]]:
        """Per-phase latency percentiles (ms) computed from the EXACT
        samples in the trace ring (not histogram-interpolated) — the
        perf harness emits this under ``--observe``. Stream spans share
        the ring but have their own vocabulary (their ``attempt``/``ttft``
        intervals are whole-stream-scale): they report via
        :meth:`stream_breakdown`, never here."""
        samples: Dict[str, List[float]] = {}
        for trace in self.tracer.recent():
            if "chunks" in trace:  # a StreamSpan, not a request span
                continue
            for phase in trace["phases"]:
                samples.setdefault(phase["name"], []).append(
                    phase["duration_ms"])
        return {name: _percentile_row(values, percentiles)
                for name, values in sorted(samples.items())}

    def stream_breakdown(self, percentiles: Sequence[float] = (0.5, 0.99),
                         ) -> Dict[str, Dict[str, float]]:
        """TTFT / inter-chunk / duration percentiles (ms) from the EXACT
        stream samples retained in the trace ring — the perf harness emits
        this under ``--observe`` for streaming runs. Empty when no stream
        finished in the ring."""
        samples: Dict[str, List[float]] = {}
        with self.tracer._lock:
            spans = list(self.tracer._ring)
        for span in spans:
            if not isinstance(span, StreamSpan):
                continue
            samples.setdefault("ttft_ms", []).extend(
                span.ttft_ms_per_attempt())
            samples.setdefault("itl_ms", []).extend(span.itl_values_ms())
            samples.setdefault("stream_duration_ms", []).append(
                span.duration_s() * 1e3)
        return {name: _percentile_row(values, percentiles)
                for name, values in sorted(samples.items()) if values}


# -- client <-> server stats correlation --------------------------------------
def accepts_client_timeout(fn: Callable) -> bool:
    """Whether a transport method takes a per-call ``client_timeout=``
    (gRPC surfaces do; HTTP surfaces bound calls at the connection-pool
    level instead)."""
    try:
        return "client_timeout" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


class StatsCorrelator:
    """Optional poller that merges SERVER-side timings into the client
    registry and renders a "where did the milliseconds go" decomposition.

    Each poll calls every endpoint's ``get_inference_statistics()`` (the
    KServe v2 statistics extension both in-repo servers expose) and — on
    transports that serve one — scrapes the server's ``/metrics`` text.
    Server queue/compute/batch-execution timings land in the client
    registry as ``client_tpu_server_stat_seconds{url,model,stat}`` et al,
    so ONE client scrape shows both halves of every request.

    :meth:`decomposition` compares the deltas between the first and the
    most recent poll against the client's own request latency over the
    same window: per (endpoint, model) it reports server queue ms, server
    compute ms, and the remainder (network + client overhead) — the
    framework-comparison methodology of the inference-benchmark literature
    (client-side totals decomposed against server-side accounting).

    ``endpoints``: a ``{url: client}`` mapping, an iterable of
    ``(url, client)`` pairs, or a ``PoolClient`` (its per-endpoint sync
    clients are used). Clients must be synchronous — run the poller
    beside an aio app with sync clients pointed at the same fleet."""

    def __init__(self, telemetry: Telemetry, endpoints,
                 interval_s: float = 5.0,
                 call_timeout_s: Optional[float] = None):
        self._telemetry = telemetry
        self.call_timeout_s = call_timeout_s
        pool = getattr(endpoints, "pool", None)
        if pool is not None and hasattr(pool, "endpoints"):
            self._endpoints = [(ep.url, ep.client) for ep in pool.endpoints]
        elif isinstance(endpoints, dict):
            self._endpoints = list(endpoints.items())
        else:
            self._endpoints = [(url, client) for url, client in endpoints]
        if not self._endpoints:
            raise ValueError("StatsCorrelator needs at least one endpoint")
        self._timeout_kw: Dict[str, bool] = {}
        for url, client in self._endpoints:
            stats_fn = getattr(client, "get_inference_statistics", None)
            if stats_fn is None or asyncio.iscoroutinefunction(stats_fn):
                # fail at construction, not as a counted error every poll
                # (an aio client would hand back un-awaited coroutines)
                raise TypeError(
                    "StatsCorrelator needs synchronous clients; endpoint "
                    f"{url!r} is async or lacks get_inference_statistics — "
                    "run the poller beside an aio app with sync clients "
                    "pointed at the same fleet")
            self._timeout_kw[url] = accepts_client_timeout(stats_fn)
        self.interval_s = interval_s
        reg = telemetry.registry
        self._stat_seconds = reg.gauge(
            "client_tpu_server_stat_seconds",
            "Cumulative server-side per-model timings mirrored from "
            "get_inference_statistics", ("url", "model", "stat"))
        self._stat_requests = reg.gauge(
            "client_tpu_server_requests",
            "Cumulative server-side request counts by outcome",
            ("url", "model", "outcome"))
        self._batch_seconds = reg.gauge(
            "client_tpu_server_batch_compute_seconds",
            "Cumulative server compute per executed batch size",
            ("url", "model", "batch_size"))
        self._batch_count = reg.gauge(
            "client_tpu_server_batch_executions",
            "Server executions per batch size",
            ("url", "model", "batch_size"))
        self._up = reg.gauge(
            "client_tpu_server_statistics_up",
            "1 when the last statistics poll of the endpoint succeeded",
            ("url",))
        self._poll_errors = reg.counter(
            "client_tpu_server_statistics_poll_errors_total",
            "Statistics polls that failed", ("url",))
        self._lock = threading.Lock()
        # (url, model) -> cumulative server counters at first/last poll
        self._baseline: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._latest: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._client_base: Optional[Tuple[float, float]] = None
        self._server_metrics: Dict[str, Dict[str, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @staticmethod
    def _server_row(row: Dict[str, Any]) -> Dict[str, float]:
        stats = row.get("inference_stats", {})

        def ns(stat: str) -> float:
            return float(stats.get(stat, {}).get("ns", 0))

        return {
            "requests": float(stats.get("success", {}).get("count", 0)),
            "fail": float(stats.get("fail", {}).get("count", 0)),
            "cancel": float(stats.get("cancel", {}).get("count", 0)),
            "queue_ns": ns("queue"),
            "compute_ns": (ns("compute_input") + ns("compute_infer")
                           + ns("compute_output")),
            "executions": float(row.get("execution_count", 0)),
            "inferences": float(row.get("inference_count", 0)),
        }

    def _client_totals(self) -> Tuple[float, float]:
        """(sum_s, count) across every frontend's request histogram."""
        self._telemetry.flush()
        hist = self._telemetry.request_seconds
        total_s = 0.0
        count = 0.0
        with self._telemetry.registry._lock:
            for series in hist._series.values():
                total_s += series.sum
                count += series.count
        return total_s, count

    @staticmethod
    def _parse_prometheus(text: str) -> Dict[str, float]:
        """Minimal Prometheus text parse: ``{series_string: value}``.

        Handles label values containing spaces (split after the closing
        ``}``) and the optional trailing timestamp field (ignored, never
        mistaken for the value)."""
        out: Dict[str, float] = {}
        for line in text.splitlines():
            if not line.strip() or line.startswith("#"):
                continue
            brace = line.rfind("}")
            if brace != -1:
                name = line[:brace + 1]
                fields = line[brace + 1:].split()
            else:
                parts = line.split()
                name, fields = parts[0], parts[1:]
            if not fields:
                continue
            try:
                out[name] = float(fields[0])
            except ValueError:
                continue
        return out

    def _scrape_server_metrics(self, url: str, client) -> None:
        """Best-effort GET /metrics (sync HTTP transports only)."""
        get = getattr(client, "_get", None)
        if get is None:
            return
        try:
            resp = get("metrics")
            if resp.status != 200:
                return
            parsed = self._parse_prometheus(resp.data.decode("utf-8"))
        except Exception:
            return
        with self._lock:
            self._server_metrics[url] = parsed

    def server_metrics(self, url: str) -> Dict[str, float]:
        """The last parsed /metrics scrape for ``url`` (may be empty)."""
        with self._lock:
            return dict(self._server_metrics.get(url, {}))

    def poll_once(self) -> None:
        """One poll of every endpoint: refresh the mirrored gauges and the
        delta bookkeeping ``decomposition()`` reads."""
        if self._client_base is None:
            self._client_base = self._client_totals()
        for url, client in self._endpoints:
            try:
                # per-call deadline where the transport takes one (gRPC);
                # HTTP transports are bounded by their constructor timeouts
                if self.call_timeout_s is not None and self._timeout_kw[url]:
                    stats = client.get_inference_statistics(
                        client_timeout=self.call_timeout_s)
                else:
                    stats = client.get_inference_statistics()
            except Exception:
                self._poll_errors.labels(url).inc()
                self._up.labels(url).set(0.0)
                continue
            self._up.labels(url).set(1.0)
            for row in stats.get("model_stats", []):
                model = row.get("name", "")
                parsed = self._server_row(row)
                self._stat_seconds.labels(url, model, "queue").set(
                    parsed["queue_ns"] / 1e9)
                self._stat_seconds.labels(url, model, "compute").set(
                    parsed["compute_ns"] / 1e9)
                self._stat_requests.labels(url, model, "success").set(
                    parsed["requests"])
                self._stat_requests.labels(url, model, "fail").set(
                    parsed["fail"])
                self._stat_requests.labels(url, model, "cancel").set(
                    parsed["cancel"])
                for batch in row.get("batch_stats", []):
                    size = batch.get("batch_size", 0)
                    ci = batch.get("compute_infer", {})
                    self._batch_seconds.labels(url, model, size).set(
                        float(ci.get("ns", 0)) / 1e9)
                    self._batch_count.labels(url, model, size).set(
                        float(ci.get("count", 0)))
                with self._lock:
                    key = (url, model)
                    self._baseline.setdefault(key, parsed)
                    self._latest[key] = parsed
            self._scrape_server_metrics(url, client)

    def decomposition(
        self,
        client_ms_by_url: Optional[Dict[str, float]] = None,
    ) -> List[Dict[str, Any]]:
        """Per (endpoint, model) latency decomposition over the polled
        window: server queue / server compute / the network+client
        remainder, all per request.

        ``client_ms_by_url`` supplies a per-endpoint client request
        latency (the doctor passes its probe averages) so the remainder
        is attributed to the endpoint that actually paid it. Without it,
        client latency falls back to the telemetry-wide request average
        over the window (the client histograms are per-frontend, not
        per-endpoint) — fine for a single endpoint, a misattribution on
        mixed fleets. Needs at least two polls with traffic in between."""
        client_ms = None
        if self._client_base is not None:
            base_s, base_n = self._client_base
            now_s, now_n = self._client_totals()
            if now_n > base_n:
                client_ms = (now_s - base_s) / (now_n - base_n) * 1e3
        rows: List[Dict[str, Any]] = []
        with self._lock:
            pairs = [(key, self._baseline.get(key), latest)
                     for key, latest in self._latest.items()]
        for (url, model), base, latest in sorted(pairs, key=lambda p: p[0]):
            if base is None:
                continue
            n = latest["requests"] - base["requests"]
            if n <= 0:
                continue
            queue_ms = (latest["queue_ns"] - base["queue_ns"]) / n / 1e6
            compute_ms = (latest["compute_ns"] - base["compute_ns"]) / n / 1e6
            row: Dict[str, Any] = {
                "url": url,
                "model": model,
                "requests": int(n),
                "server_queue_ms": round(queue_ms, 4),
                "server_compute_ms": round(compute_ms, 4),
                "server_total_ms": round(queue_ms + compute_ms, 4),
            }
            url_ms = (client_ms_by_url or {}).get(url, client_ms)
            if url_ms is not None:
                row["client_request_ms"] = round(url_ms, 4)
                row["network_client_overhead_ms"] = round(
                    max(url_ms - (queue_ms + compute_ms), 0.0), 4)
            rows.append(row)
        return rows

    # -- background polling ---------------------------------------------------
    def start(self) -> "StatsCorrelator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:
                    pass  # a sick endpoint must not kill the poller

        self._thread = threading.Thread(
            target=loop, name="client_tpu_stats_correlator", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
