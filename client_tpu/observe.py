"""Client-side observability: request-phase tracing, metrics, propagation.

The reference client can only *configure* server-side tracing
(``update_trace_settings``) — the client itself is a black box, which is
exactly where production debugging of a KServe v2 data plane happens (is
the latency in serialize, connect, TTFB, or deserialize?). This module is
the consumer for the structured events PR 1/PR 2 already emit (retry
callbacks, breaker transitions, ``PoolEvent``s) and the phase timers the
frontends already capture:

- :class:`Tracer` + :class:`RequestSpan` — a monotonic per-request phase
  timeline (queue → serialize → connect/acquire → send → first-byte →
  recv → deserialize, plus retry-attempt and hedge sub-spans) with
  ``always`` / ``ratio`` / ``slow``-only sampling and a ring buffer of
  recent traces dumpable as Chrome ``trace_event`` JSON
  (``chrome://tracing`` / Perfetto load it directly).
- :class:`MetricsRegistry` — counters, gauges and fixed-bucket latency
  histograms with lock-cheap hot-path increments, rendered as Prometheus
  text exposition (``prometheus_text``) or a JSON snapshot
  (``snapshot``).
- W3C trace context propagation — :func:`format_traceparent` /
  :func:`parse_traceparent`; every frontend injects a ``traceparent``
  header (HTTP) or metadata key (GRPC) when a telemetry object is
  configured, and the in-repo servers honor it by recording a
  server-side access record joined on the same trace id (see
  ``ServerCore.access_records`` and the servers' ``/metrics`` route).
- :class:`Telemetry` — the facade a client/pool/policy shares via
  ``InferenceServerClientBase.configure_telemetry``: pre-wired
  request/error/retry/breaker/ejection/hedge metrics fed by the existing
  resilience and pool event streams.

Pay-for-what-you-use: with no telemetry configured the frontends' hot
paths check one attribute and do nothing else (~0 overhead); with
telemetry enabled the per-call cost is bounded by a handful of
pre-resolved label lookups and one registry-lock critical section (the
committed ``BENCH_OBSERVE.json`` holds the measured numbers).
"""

from __future__ import annotations

import itertools
import json
import re
import random
import threading
import time
import weakref
from bisect import bisect_right
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_S",
    "TRACEPARENT_HEADER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RequestSpan",
    "Telemetry",
    "Tracer",
    "format_traceparent",
    "make_span_id",
    "make_trace_id",
    "parse_traceparent",
]

# -- W3C trace context --------------------------------------------------------
TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

_id_rng = random.Random()  # module-level: ids must differ across Telemetry objects


def make_trace_id(rng: Optional[random.Random] = None) -> str:
    """A 16-byte lowercase-hex W3C trace id (never all-zero)."""
    r = rng or _id_rng
    return f"{r.getrandbits(128) or 1:032x}"


def make_span_id(rng: Optional[random.Random] = None) -> str:
    """An 8-byte lowercase-hex W3C span (parent) id (never all-zero)."""
    r = rng or _id_rng
    return f"{r.getrandbits(64) or 1:016x}"


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(value: Optional[str]):
    """``(trace_id, parent_span_id, sampled)`` or None when malformed.

    Per the W3C spec: version ``ff`` and all-zero trace/span ids are
    invalid; unknown flag bits are ignored beyond the sampled bit."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 1)


# -- metrics ------------------------------------------------------------------
# Fixed latency buckets (seconds): 100 µs .. 10 s, roughly 1-2.5-5 decades —
# wide enough for localhost shm round trips and cold-compile outliers alike.
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Series:
    """One labeled time series. Mutations take the registry's shared lock
    (one uncontended acquire per op — "lock-cheap"); the ``_``-prefixed
    unlocked primitives exist so :meth:`Telemetry.finish` can batch a whole
    request's updates under a single acquire."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def _inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def _set(self, value: float) -> None:
        self.value = value

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def get(self) -> float:
        return self.value  # single-slot read: no lock needed


class _HistogramSeries:
    """One labeled histogram: cumulative-on-render fixed buckets + sum/count."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, buckets: Sequence[float]):
        self._lock = lock
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._observe(value)

    def _observe(self, value: float) -> None:
        self.counts[bisect_right(self.buckets, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile by linear interpolation inside the owning
        bucket (the usual histogram_quantile estimate). Values beyond the
        last finite edge clamp to it."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        lower = 0.0
        for i, edge in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                frac = (rank - prev) / max(counts[i], 1)
                return lower + (edge - lower) * min(max(frac, 0.0), 1.0)
            lower = edge
        return self.buckets[-1] if self.buckets else lower


class _Metric:
    """Shared labeled-family machinery for the three instrument kinds."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for label in labelnames:
            if not _LABEL_RE.match(label):
                raise ValueError(f"invalid label name {label!r}")
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _new_series(self):
        raise NotImplementedError

    def labels(self, *values) -> Any:
        """The series for one label-value tuple (created on first use and
        cached — callers are expected to hold on to hot series)."""
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {key}")
        series = self._series.get(key)
        if series is None:
            with self._registry._lock:
                series = self._series.get(key)
                if series is None:
                    series = self._new_series()
                    self._series[key] = series
        return series

    def _default(self):
        """The unlabeled series (metrics declared with no label names)."""
        return self.labels()


class Counter(_Metric):
    kind = "counter"

    def _new_series(self):
        return _Series(self._registry._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class Gauge(_Metric):
    kind = "gauge"

    def _new_series(self):
        return _Series(self._registry._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S):
        super().__init__(registry, name, help, labelnames)
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        if len(set(edges)) != len(edges):
            raise ValueError("histogram bucket edges must be distinct")
        self.buckets = edges

    def _new_series(self):
        return _HistogramSeries(self._registry._lock, self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)


class MetricsRegistry:
    """A process-local metric registry with Prometheus + JSON exporters.

    Instruments are created idempotently (asking for an existing name
    returns the existing instrument; a kind/label mismatch raises).
    ``add_collector`` registers a callback run before every export — the
    pool uses it to refresh per-endpoint gauges at scrape time instead of
    on the data path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []

    def _instrument(self, cls, name, help, labelnames, **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different kind or labels")
                return existing
        metric = cls(self, name, help, labelnames, **kwargs)
        with self._lock:
            return self._metrics.setdefault(name, metric)

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._instrument(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._instrument(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                  ) -> Histogram:
        return self._instrument(
            Histogram, name, help, labelnames, buckets=buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:  # outside the lock: collectors set gauges
            try:
                fn()
            except Exception:
                pass  # an exporter must never break on a sick collector

    # -- exporters -----------------------------------------------------------
    @staticmethod
    def _labels_text(labelnames, key, extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4). Histogram buckets are
        cumulative and ``+Inf``-terminated, with ``_sum``/``_count``."""
        self._run_collectors()
        lines: List[str] = []
        with self._lock:
            for metric in self._metrics.values():
                if not metric._series:
                    continue
                if metric.help:
                    lines.append(f"# HELP {metric.name} {metric.help}")
                lines.append(f"# TYPE {metric.name} {metric.kind}")
                for key in sorted(metric._series):
                    series = metric._series[key]
                    if metric.kind == "histogram":
                        cum = 0
                        for edge, n in zip(series.buckets, series.counts):
                            cum += n
                            labels = self._labels_text(
                                metric.labelnames, key,
                                f'le="{_fmt_value(edge)}"')
                            lines.append(
                                f"{metric.name}_bucket{labels} {cum}")
                        labels = self._labels_text(
                            metric.labelnames, key, 'le="+Inf"')
                        lines.append(
                            f"{metric.name}_bucket{labels} {series.count}")
                        base = self._labels_text(metric.labelnames, key)
                        lines.append(
                            f"{metric.name}_sum{base} "
                            f"{_fmt_value(series.sum)}")
                        lines.append(f"{metric.name}_count{base} "
                                     f"{series.count}")
                    else:
                        labels = self._labels_text(metric.labelnames, key)
                        lines.append(
                            f"{metric.name}{labels} "
                            f"{_fmt_value(series.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """A JSON-ready snapshot (plain dict/list/str/number values only, so
        ``json.loads(json.dumps(snapshot)) == snapshot``)."""
        self._run_collectors()
        out: Dict[str, Any] = {}
        with self._lock:
            for metric in self._metrics.values():
                series_out = []
                for key in sorted(metric._series):
                    series = metric._series[key]
                    labels = dict(zip(metric.labelnames, key))
                    if metric.kind == "histogram":
                        cum = 0
                        buckets = []
                        for edge, n in zip(series.buckets, series.counts):
                            cum += n
                            buckets.append({"le": edge, "count": cum})
                        buckets.append({"le": "+Inf", "count": series.count})
                        series_out.append({
                            "labels": labels,
                            "count": series.count,
                            "sum": series.sum,
                            "buckets": buckets,
                        })
                    else:
                        series_out.append(
                            {"labels": labels, "value": series.value})
                out[metric.name] = {
                    "kind": metric.kind,
                    "help": metric.help,
                    "series": series_out,
                }
        return out


# -- tracing ------------------------------------------------------------------
# Canonical phase vocabulary (what each transport can observe of it):
#   queue       time waiting for a worker/slot before the request is built
#   serialize   request body/tensor marshaling
#   connect     TCP/TLS/channel establishment (when separable)
#   send        request bytes on the wire (when separable from ttfb)
#   ttfb        request issued -> first response byte (HTTP: headers;
#               GRPC unary: the whole call, send+server+receive)
#   recv        response body read
#   deserialize response unmarshaling into InferResult
#   attempt     one resilient attempt (sub-span; repeated under retries)
REQUEST_PHASES = (
    "queue", "serialize", "connect", "send", "ttfb", "recv", "deserialize",
    "attempt",
)


class RequestSpan:
    """One client request's span: ids, phase intervals, point events.

    ``phase(name, start_ns, end_ns)`` appends an interval (monotonic
    ``time.perf_counter_ns`` values); ``event(name, **attrs)`` appends a
    point annotation (retries, hedges, reconnects). Both are plain list
    appends — cheap enough for the hot path. ``events`` and ``tid`` are
    populated lazily (most requests have no point events, and the thread
    id is only needed when the span is retained for a trace dump)."""

    __slots__ = ("trace_id", "span_id", "frontend", "model", "op",
                 "start_ns", "end_ns", "phases", "events", "sampled",
                 "error", "tid")

    def __init__(self, trace_id: str, span_id: str, frontend: str,
                 model: str, op: str, sampled: bool):
        # end_ns / events / error / tid are set lazily off the hot path
        # (finish, event(), trace retention); readers use getattr defaults
        self.trace_id = trace_id
        self.span_id = span_id
        self.frontend = frontend
        self.model = model
        self.op = op
        self.start_ns = time.perf_counter_ns()
        self.phases: List[Tuple[str, int, int]] = []
        self.sampled = sampled

    def phase(self, name: str, start_ns: int, end_ns: int) -> None:
        self.phases.append((name, start_ns, end_ns))

    def event(self, name: str, **attrs) -> None:
        events = getattr(self, "events", None)
        if events is None:
            events = self.events = []
        events.append((name, time.perf_counter_ns(), attrs or None))

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, self.sampled)

    def duration_s(self) -> float:
        end = getattr(self, "end_ns", 0) or time.perf_counter_ns()
        return (end - self.start_ns) * 1e-9

    def as_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "frontend": self.frontend,
            "model": self.model,
            "op": self.op,
            "start_ns": self.start_ns,
            "end_ns": getattr(self, "end_ns", 0),
            "duration_ms": round(self.duration_s() * 1e3, 6),
            "error": getattr(self, "error", None),
            "phases": [
                {"name": n, "start_ns": s, "end_ns": e,
                 "duration_ms": round((e - s) / 1e6, 6)}
                for n, s, e in self.phases
            ],
            "events": [
                {"name": n, "ns": ts, **(attrs or {})}
                for n, ts, attrs in (getattr(self, "events", None) or ())
            ],
        }


class Tracer:
    """Ring buffer of recently finished request spans + dump formats."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, capacity))
        self.dropped = 0

    def keep(self, span: RequestSpan) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)

    def recent(self, count: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._ring)
        if count is not None:
            spans = spans[-count:]
        return [s.as_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome ``trace_event`` JSON (load in chrome://tracing/Perfetto):
        one complete ("X") event per request span, nested complete events
        per phase, instant ("i") events for retries/hedges."""
        with self._lock:
            spans = list(self._ring)
        events: List[Dict[str, Any]] = []
        for span in spans:
            name = f"{span.op} {span.model}".strip()
            end_ns = getattr(span, "end_ns", 0) or span.start_ns
            tid = getattr(span, "tid", 0)
            error = getattr(span, "error", None)
            args: Dict[str, Any] = {
                "trace_id": span.trace_id, "span_id": span.span_id,
            }
            if error:
                args["error"] = error
            events.append({
                "name": name, "cat": span.frontend, "ph": "X",
                "ts": span.start_ns / 1e3,
                "dur": max(end_ns - span.start_ns, 0) / 1e3,
                "pid": 1, "tid": tid, "args": args,
            })
            for pname, s, e in span.phases:
                events.append({
                    "name": pname, "cat": "phase", "ph": "X",
                    "ts": s / 1e3, "dur": max(e - s, 0) / 1e3,
                    "pid": 1, "tid": tid,
                })
            for ename, ts, attrs in (getattr(span, "events", None) or ()):
                events.append({
                    "name": ename, "cat": "event", "ph": "i",
                    "ts": ts / 1e3, "s": "t", "pid": 1, "tid": tid,
                    "args": attrs or {},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_json(self) -> str:
        return json.dumps(self.chrome_trace(), separators=(",", ":"))


# -- the facade ---------------------------------------------------------------
_SAMPLE_MODES = ("always", "ratio", "slow", "off")


class _FrontendBinding:
    """Pre-resolved hot-path series for one frontend label value, so
    ``Telemetry.finish`` does dict lookups instead of label resolution."""

    __slots__ = ("requests", "request_seconds", "phase_series")

    def __init__(self, tel: "Telemetry", frontend: str):
        self.requests = tel.requests_total.labels(frontend)
        self.request_seconds = tel.request_seconds.labels(frontend)
        self.phase_series: Dict[str, _HistogramSeries] = {
            name: tel.phase_seconds.labels(frontend, name)
            for name in REQUEST_PHASES
        }


class Telemetry:
    """One telemetry object shared by frontends, pools and policies.

    ``sample``: which finished spans the tracer ring retains — ``always``,
    ``ratio`` (keep ``sample_ratio`` of requests, decided at span start so
    the traceparent sampled flag matches), ``slow`` (keep only requests
    slower than ``slow_threshold_s``), or ``off`` (metrics only). Metrics
    are always recorded; sampling gates only trace retention.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        sample: str = "always",
        sample_ratio: float = 0.01,
        slow_threshold_s: float = 0.25,
        trace_capacity: int = 256,
        rng: Optional[random.Random] = None,
    ):
        if sample not in _SAMPLE_MODES:
            raise ValueError(
                f"unknown sample mode {sample!r} (one of {_SAMPLE_MODES})")
        self.registry = registry or MetricsRegistry()
        self.tracer = Tracer(trace_capacity)
        self.sample = sample
        self.sample_ratio = sample_ratio
        self.slow_threshold_s = slow_threshold_s
        self._rng = rng or random.Random()
        reg = self.registry
        # -- pre-wired client instruments ------------------------------------
        self.requests_total = reg.counter(
            "client_tpu_requests_total",
            "Requests finished (success or error) per frontend",
            ("frontend",))
        self.request_errors_total = reg.counter(
            "client_tpu_request_errors_total",
            "Requests finished with an error, by fault domain",
            ("frontend", "domain"))
        self.request_seconds = reg.histogram(
            "client_tpu_request_seconds",
            "End-to-end client request latency", ("frontend",))
        self.phase_seconds = reg.histogram(
            "client_tpu_phase_seconds",
            "Per-phase client latency (serialize/ttfb/recv/deserialize/...)",
            ("frontend", "phase"))
        self.retries_total = reg.counter(
            "client_tpu_retries_total",
            "Resilient re-attempts across all policies")
        self.fast_fails_total = reg.counter(
            "client_tpu_breaker_fast_fails_total",
            "Requests shed by an open circuit breaker")
        self.breaker_transitions_total = reg.counter(
            "client_tpu_breaker_transitions_total",
            "Circuit breaker state transitions", ("state",))
        self.stream_reconnects_total = reg.counter(
            "client_tpu_stream_reconnects_total",
            "GRPC bidi stream auto-reconnects")
        self.pool_ejections_total = reg.counter(
            "client_tpu_pool_ejections_total",
            "Passive outlier ejections per endpoint", ("url",))
        self.pool_readmissions_total = reg.counter(
            "client_tpu_pool_readmissions_total",
            "Ejection-window expiries / proven-healthy readmissions",
            ("url",))
        self.pool_health_changes_total = reg.counter(
            "client_tpu_pool_health_changes_total",
            "Active ready-probe health flips per endpoint", ("url",))
        self.pool_sequence_abandoned_total = reg.counter(
            "client_tpu_pool_sequence_abandoned_total",
            "Sequence requests abandoned mid-flight (never re-sent)",
            ("url",))
        self.hedges_fired_total = reg.counter(
            "client_tpu_hedges_fired_total",
            "Hedge copies issued to a second replica")
        self.hedge_wins_total = reg.counter(
            "client_tpu_hedge_wins_total",
            "Requests won by a hedge copy (not the primary)")
        self.hedge_losses_total = reg.counter(
            "client_tpu_hedge_losses_total",
            "Requests where the primary beat an in-flight hedge")
        self._bindings: Dict[str, _FrontendBinding] = {}
        self._pools: List[Any] = []
        self._pools_lock = threading.Lock()
        self._pool_gauges: Optional[Dict[str, Gauge]] = None
        # -- hot-path fast lanes ---------------------------------------------
        # mode flags instead of string compares; cheap unique ids: span ids
        # are a random 64-bit base xor a GIL-atomic counter, trace ids a
        # random 64-bit hex prefix + the counter (W3C needs uniqueness and
        # non-zero; the per-object random prefix keeps ids distinct across
        # processes without paying getrandbits(128)+format per request)
        self._sample_ratio_mode = sample == "ratio"
        self._sample_slow_mode = sample == "slow"
        self._sample_off = sample == "off"
        self._trace_prefix = f"{self._rng.getrandbits(64) or 1:016x}"
        # itertools.count.__next__ is a single C call: each concurrent
        # caller receives a DISTINCT value (a python `seq += 1; read seq`
        # pair could hand two threads the same id)
        self._next_seq = itertools.count(1).__next__
        # finished spans queue here (lock-free GIL-atomic deque appends) and
        # fold into the counters/histograms on the SCRAPER's thread (via
        # the collector below) — the request path never pays the histogram
        # math. _FOLD_BACKLOG bounds memory when nothing scrapes: past it,
        # the unlucky request folds the backlog inline (amortized, rare).
        self._pending: deque = deque()
        self.registry.add_collector(self._fold_pending)

    _FOLD_BACKLOG = 32768

    # -- span lifecycle ------------------------------------------------------
    def begin(self, frontend: str, model: str = "",
              op: str = "infer") -> RequestSpan:
        """Open a request span. The sampled flag reflects ``ratio`` mode at
        start time (``slow`` keeps the flag set: the decision happens at
        finish, and servers record access on any traceparent)."""
        sampled = True
        if self._sample_ratio_mode:
            sampled = self._rng.random() < self.sample_ratio
        elif self._sample_off:
            sampled = False
        suffix = f"{self._next_seq():016x}"
        # one client span per trace, so the span id can reuse the trace
        # suffix: unique within the trace (trivially) and across this
        # object's traces (the counter), never all-zero (seq starts at 1)
        return RequestSpan(
            self._trace_prefix + suffix, suffix,
            frontend, model, op, sampled)

    def _binding(self, frontend: str) -> _FrontendBinding:
        binding = self._bindings.get(frontend)
        if binding is None:
            binding = _FrontendBinding(self, frontend)
            self._bindings[frontend] = binding
        return binding

    def finish(self, span: Optional[RequestSpan],
               error: Optional[BaseException] = None) -> None:
        """Close the span. The hot path is one timestamp, the trace-ring
        decision, and a lock-free deque append; the counter/histogram fold
        is deferred to scrape time (or amortized once the backlog passes
        ``_FOLD_BACKLOG``). This is the per-request overhead
        BENCH_OBSERVE.json measures."""
        if span is None:
            return
        end_ns = span.end_ns = time.perf_counter_ns()
        total_s = (end_ns - span.start_ns) * 1e-9
        if error is not None:
            from .resilience import classify_fault  # no import cycle: lazy

            span.error = f"{type(error).__name__}: {error}"[:256]
            pending = (span, total_s, classify_fault(error))
        else:
            pending = (span, total_s, None)
        self._pending.append(pending)
        if self._sample_slow_mode:
            if total_s >= self.slow_threshold_s:
                span.tid = threading.get_ident()
                self.tracer.keep(span)
        elif span.sampled:
            span.tid = threading.get_ident()
            self.tracer.keep(span)
        if len(self._pending) >= self._FOLD_BACKLOG:
            self._fold_pending()

    def _fold_pending(self) -> None:
        """Drain finished spans into the metric series. Runs at scrape time
        (registry collector), at the amortization threshold, or on demand;
        concurrent folders are safe — ``popleft`` hands each record to
        exactly one of them."""
        pending = self._pending
        if not pending:
            return
        lock = self.registry._lock
        while True:
            try:
                span, total_s, domain = pending.popleft()
            except IndexError:
                return
            binding = self._binding(span.frontend)
            err_series = None
            if domain is not None:
                err_series = self.request_errors_total.labels(
                    span.frontend, domain)
            phases = span.phases
            phase_series = binding.phase_series
            for name, _, _ in phases:  # rare: non-canonical phase name
                if name not in phase_series:
                    phase_series[name] = self.phase_seconds.labels(
                        span.frontend, name)
            req_hist = binding.request_seconds
            with lock:
                binding.requests.value += 1
                req_hist.counts[
                    bisect_right(req_hist.buckets, total_s)] += 1
                req_hist.sum += total_s
                req_hist.count += 1
                if err_series is not None:
                    err_series.value += 1
                for name, s, e in phases:
                    seconds = (e - s) * 1e-9
                    if seconds < 0.0:
                        seconds = 0.0
                    h = phase_series[name]
                    h.counts[bisect_right(h.buckets, seconds)] += 1
                    h.sum += seconds
                    h.count += 1

    # -- resilience observer protocol (duck-typed from resilience.py) --------
    def on_retry(self, attempt: int, exc: BaseException,
                 delay_s: float) -> None:
        self.retries_total.inc()

    def on_fast_fail(self) -> None:
        self.fast_fails_total.inc()

    def on_breaker_transition(self, state: str) -> None:
        self.breaker_transitions_total.labels(state).inc()

    def on_stream_reconnect(self) -> None:
        self.stream_reconnects_total.inc()

    def on_hedge_fired(self) -> None:
        self.hedges_fired_total.inc()

    def on_hedge_result(self, hedge_won: bool) -> None:
        (self.hedge_wins_total if hedge_won
         else self.hedge_losses_total).inc()

    def attach(self, policy) -> Any:
        """Wire a ``resilience.ResiliencePolicy`` (and its breaker) into
        this telemetry object; returns the policy for chaining."""
        policy.observer = self
        breaker = getattr(policy, "breaker", None)
        if breaker is not None:
            breaker.on_transition = self.on_breaker_transition
        return policy

    # -- pool bridge ---------------------------------------------------------
    def pool_observer(self, chain: Optional[Callable[[Any], None]] = None,
                      ) -> Callable[[Any], None]:
        """An ``on_event`` callback for ``client_tpu.pool`` that counts
        each typed pool event exactly once, then forwards to ``chain``.
        Matches on type name so this module never imports the pool."""
        counters = {
            "EndpointEjected": self.pool_ejections_total,
            "EndpointReadmitted": self.pool_readmissions_total,
            "EndpointHealthChanged": self.pool_health_changes_total,
            "SequenceAbandoned": self.pool_sequence_abandoned_total,
        }

        def observe(event) -> None:
            try:
                counter = counters.get(type(event).__name__)
                if counter is not None:
                    counter.labels(event.url).inc()
            finally:
                if chain is not None:
                    chain(event)

        return observe

    def register_pool(self, pool) -> None:
        """Expose a pool's per-endpoint stats (health, ejection, breaker
        state, outstanding, resilience counters) as gauges refreshed at
        scrape time via a registry collector — one Prometheus scrape shows
        ejections, half-open probes and hedge win/loss together.

        Pools are held by weak reference: a long-lived Telemetry shared
        across PoolClient create/close cycles must not pin dead pools (and
        their endpoint clients) in memory or keep scraping them."""
        with self._pools_lock:
            first = self._pool_gauges is None
            if first:
                reg = self.registry
                self._pool_gauges = {
                    "healthy": reg.gauge(
                        "client_tpu_pool_endpoint_healthy",
                        "Active ready-probe verdict (1 healthy)", ("url",)),
                    "ejected": reg.gauge(
                        "client_tpu_pool_endpoint_ejected",
                        "Outlier-ejection state (1 ejected)", ("url",)),
                    "outstanding": reg.gauge(
                        "client_tpu_pool_endpoint_outstanding",
                        "In-flight requests per endpoint", ("url",)),
                    "consecutive_failures": reg.gauge(
                        "client_tpu_pool_endpoint_consecutive_failures",
                        "Consecutive transport failures", ("url",)),
                    "ejection_count": reg.gauge(
                        "client_tpu_pool_endpoint_ejection_count",
                        "Lifetime ejections per endpoint", ("url",)),
                    "breaker_state": reg.gauge(
                        "client_tpu_pool_endpoint_breaker_state",
                        "Breaker state (0 closed, 1 half-open, 2 open)",
                        ("url",)),
                    "resilience": reg.gauge(
                        "client_tpu_pool_endpoint_resilience",
                        "Per-endpoint ResilienceStats counters",
                        ("url", "counter")),
                }
            self._pools.append(weakref.ref(pool))
            if first:
                self.registry.add_collector(self._collect_pools)

    def _collect_pools(self) -> None:
        _BREAKER_STATE = {"closed": 0, "half_open": 1, "open": 2}
        with self._pools_lock:
            refs = list(self._pools)
            gauges = self._pool_gauges
        if gauges is None:
            return
        dead = []
        for ref in refs:
            pool = ref()
            if pool is None:
                dead.append(ref)
                continue
            try:
                snapshot = pool.snapshot()
            except Exception:
                continue  # one sick pool must not break the whole scrape
            for url, stats in snapshot.items():
                gauges["healthy"].labels(url).set(
                    1.0 if stats["healthy"] else 0.0)
                gauges["ejected"].labels(url).set(
                    1.0 if stats["ejected"] else 0.0)
                gauges["outstanding"].labels(url).set(stats["outstanding"])
                gauges["consecutive_failures"].labels(url).set(
                    stats["consecutive_failures"])
                gauges["ejection_count"].labels(url).set(
                    stats["ejection_count"])
                state = stats.get("breaker_state")
                if state is not None:
                    gauges["breaker_state"].labels(url).set(
                        _BREAKER_STATE.get(state, -1))
                for name, value in stats.get("resilience", {}).items():
                    gauges["resilience"].labels(url, name).set(value)
        if dead:
            with self._pools_lock:
                for ref in dead:
                    try:
                        self._pools.remove(ref)
                    except ValueError:
                        pass

    # -- introspection -------------------------------------------------------
    def flush(self) -> None:
        """Fold any pending finished spans into the metric series now.
        Exporters (``prometheus_text``/``snapshot``) do this implicitly;
        call it before reading instrument objects directly."""
        self._fold_pending()

    def recent_traces(self, count: Optional[int] = None) -> List[Dict[str, Any]]:
        return self.tracer.recent(count)

    def chrome_trace(self) -> Dict[str, Any]:
        return self.tracer.chrome_trace()

    def dump_json(self) -> str:
        return self.tracer.dump_json()

    def phase_breakdown(self, percentiles: Sequence[float] = (0.5, 0.99),
                        ) -> Dict[str, Dict[str, float]]:
        """Per-phase latency percentiles (ms) computed from the EXACT
        samples in the trace ring (not histogram-interpolated) — the
        perf harness emits this under ``--observe``."""
        samples: Dict[str, List[float]] = {}
        for trace in self.tracer.recent():
            for phase in trace["phases"]:
                samples.setdefault(phase["name"], []).append(
                    phase["duration_ms"])
        out: Dict[str, Dict[str, float]] = {}
        for name, values in sorted(samples.items()):
            values.sort()
            row = {"count": len(values),
                   "avg": round(sum(values) / len(values), 4)}
            for q in percentiles:
                idx = min(int(len(values) * q), len(values) - 1)
                row[f"p{int(q * 100)}"] = round(values[idx], 4)
            out[name] = row
        return out
