"""Flash attention as a Pallas TPU kernel.

The single-device hot-loop counterpart of the distributed schemes in
``parallel/`` (ring rotates K/V across chips; Ulysses re-partitions heads;
THIS kernel is what each device should run on its local blocks): blocked
online-softmax attention that never materializes the [seq, seq] score
matrix. VMEM holds one Q block plus running (max, sum, accumulator) state
while K/V blocks stream through; the K-block grid axis is sequential on
TPU ("arbitrary" dimension semantics), which is exactly what the carried
scratch state needs.

Runs in interpret mode off-TPU (CI exactness tests vs dense attention);
compiled to Mosaic on the chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _on_tpu


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, block_q, block_k, nk, kv_len,
):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: K blocks strictly above the diagonal contribute nothing — and
    # with sequential K iteration the whole block body can be skipped
    run_block = jnp.logical_or(
        jnp.logical_not(causal), ik * block_k <= iq * block_q + block_q - 1
    )

    @pl.when(run_block)
    def _body():
        # dots take the operands in their NATIVE dtype with fp32
        # accumulation: bf16×bf16→f32 is the MXU's full-rate mode, while
        # pre-casting to f32 (the round-3 kernel) dropped every matmul to
        # the ~4x-slower fp32 MXU path — the bulk of the 4.9%-MFU finding
        q = q_ref[0]                                 # [bq, d]
        k = k_ref[0]                                 # [bk, d]
        v = v_ref[0]                                 # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [bq, bk] f32
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if kv_len is not None:
            # padded tail keys (sequence rounded up to the block size)
            # contribute nothing
            s = jnp.where(k_pos < kv_len, s, -jnp.inf)
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_prev = m_scr[...]                          # [bq, 128] broadcast lanes
        l_prev = l_scr[...]
        m_cur = s.max(-1)                            # [bq]
        m_new = jnp.maximum(m_prev, m_cur[:, None])
        p = jnp.exp(s - m_new[:, :1])                # [bq, bk]
        correction = jnp.exp(m_prev - m_new)         # [bq, 128]
        l_scr[...] = l_prev * correction + p.sum(-1)[:, None]
        acc_scr[...] = (
            acc_scr[...] * correction[:, :1]
            + jax.lax.dot_general(
                # probabilities rounded to the value dtype so the PV dot
                # also rides the full-rate MXU path (f32 accumulate keeps
                # the running sum exact); for f32 inputs this is a no-op
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...][:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q, k, v, causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool | None = None,
):
    """Blocked attention. q,k,v: [batch, seq, heads, dim] -> same shape.

    Sequences that don't divide by the block sizes are zero-padded up to
    the next multiple and the padded keys masked in-kernel (exact results,
    full-size blocks — never degrade the block to tiny grids). Blocks
    default to the MXU-native 128.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, real_seq, heads, dim = q.shape
    block_q = min(block_q, real_seq)
    block_k = min(block_k, real_seq)
    block = max(block_q, block_k)
    seq = -(-real_seq // block) * block  # ceil to a common block multiple
    kv_len = real_seq if seq != real_seq else None
    if kv_len is not None:
        pad = [(0, 0), (0, seq - real_seq), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    if seq % block_q or seq % block_k:
        raise ValueError(f"seq {seq} must divide by blocks {block_q}/{block_k}")
    nq = seq // block_q
    nk = seq // block_k
    scale = dim ** -0.5

    # [batch, seq, heads, dim] -> [batch*heads, seq, dim] kernel layout
    def to_bh(t):
        return jnp.transpose(t, (0, 2, 1, 3)).reshape(batch * heads, seq, dim)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, kv_len=kv_len,
    )
    out = pl.pallas_call(
        kernel,
        grid=(batch * heads, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dim), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, seq, dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (lanes bcast)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, dim), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=not _on_tpu() if interpret is None else interpret,
    )(qb, kb, vb)

    result = jnp.transpose(out.reshape(batch, heads, seq, dim), (0, 2, 1, 3))
    return result[:, :real_seq] if kv_len is not None else result
