"""Single-query KV-cache attention (flash decoding) as a Pallas TPU kernel.

The LLM decode hot op: one query vector per sequence attends over its whole
KV cache. ``flash_attention`` (the prefill kernel) streams K/V blocks
against a *block* of queries; at decode there is exactly one live query
position, so the kernel keeps the running online-softmax state for a single
row while K/V blocks stream through VMEM — the op is HBM-bandwidth-bound
(every decode step re-reads the cache), which is why padding the lone query
row up to the 8-sublane tile costs ~nothing: the MXU work is noise next to
the cache traffic.

Layout: the query row is padded to an [8, d] tile (row 0 live — Mosaic's
minimum f32 sublane tile); the grid is (batch*heads, nk) with the K axis
sequential ("arbitrary") so the (m, l, acc) scratch carries across K
blocks. The per-sequence valid length arrives as a scalar in SMEM; K slots
above it (unwritten cache tail) are masked in-kernel, so the same compiled
kernel serves every decode position — no shape-polymorphic retraces, the
same property the decoder's dense path has (models/decoder.py).

Runs in interpret mode off-TPU (CI exactness vs dense attention); compiled
to Mosaic on the chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _on_tpu

_SUBLANES = 8  # f32 min sublane tile; the padded query-row block height


def _decode_kernel(pos_ref, k_ref, v_ref, q_ref, o_ref, m_scr, l_scr, acc_scr,
                   *, scale, block_k, nk):
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = pos_ref[0]  # last valid cache slot for this sequence/head

    # K blocks wholly above pos contribute nothing — skip the whole body
    @pl.when(ik * block_k <= pos)
    def _body():
        # native-dtype operands + f32 accumulation: bf16 caches ride the
        # full-rate MXU path instead of the pre-cast fp32 one (same change
        # as flash_attention.py — decode is bandwidth-bound so the win is
        # smaller, but the halved VMEM footprint of bf16 blocks also helps)
        q = q_ref[0]                                 # [8, d] (row 0 live)
        k = k_ref[0]                                 # [bk, d]
        v = v_ref[0]                                 # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [8, bk] f32
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (_SUBLANES, block_k), 1
        )
        s = jnp.where(k_pos <= pos, s, -jnp.inf)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, s.max(-1)[:, None])
        p = jnp.exp(s - m_new[:, :1])
        correction = jnp.exp(m_prev - m_new)
        l_scr[...] = l_prev * correction + p.sum(-1)[:, None]
        acc_scr[...] = (
            acc_scr[...] * correction[:, :1]
            + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...][:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def decode_attention(q, k, v, pos, block_k: int = 128,
                     interpret: bool | None = None):
    """One-step decode attention. q: [batch, heads, dim]; k, v:
    [batch, heads, max_len, dim]; pos: [batch] int32 — cache slots
    ``<= pos[b]`` attend (the decoder's position-based mask,
    models/decoder.py). Returns [batch, heads, dim] in q's dtype."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, dim = q.shape
    max_len = k.shape[2]
    block_k = min(block_k, max_len)
    padded = -(-max_len // block_k) * block_k
    if padded != max_len:
        pad = [(0, 0), (0, 0), (0, padded - max_len), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)  # tail is masked by the pos comparison
    nk = padded // block_k
    scale = dim ** -0.5

    bh = batch * heads
    # query row padded to the sublane tile; K/V flattened to [bh, M, d]
    qb = jnp.zeros((bh, _SUBLANES, dim), q.dtype).at[:, 0, :].set(
        q.reshape(bh, dim))
    kb = k.reshape(bh, padded, dim)
    vb = v.reshape(bh, padded, dim)
    pos_b = jnp.repeat(pos.astype(jnp.int32), heads)  # [bh]

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nk),
        in_specs=[
            pl.BlockSpec((1,), lambda b, j: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_k, dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, _SUBLANES, dim), lambda b, j: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _SUBLANES, dim), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, _SUBLANES, dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((_SUBLANES, 128), jnp.float32),  # running max
            pltpu.VMEM((_SUBLANES, 128), jnp.float32),  # running sum
            pltpu.VMEM((_SUBLANES, dim), jnp.float32),  # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=not _on_tpu() if interpret is None else interpret,
    )(pos_b, kb, vb, qb)

    return out[:, 0, :].reshape(batch, heads, dim)


def decode_attention_reference(q, k, v, pos):
    """Dense fp32 reference (the decoder's einsum path, batched)."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhd,bhmd->bhm", qf, kf) * scale
    mask = jnp.arange(k.shape[2])[None, :] <= pos[:, None]  # [b, m]
    s = jnp.where(mask[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhm,bhmd->bhd", p, vf).astype(q.dtype)
