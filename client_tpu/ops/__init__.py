"""Jitted data-plane ops and Pallas kernels for the hot client/server paths.

The reference client's compute is numpy on the CUDA host (dtype conversion,
image preprocessing in examples). Here those run through XLA/Pallas so the
data plane stays on-device:

- ``normalize_image``: fused scale/shift/cast preprocessing (the
  image_client NONE/INCEPTION/VGG scaling modes) as a Pallas VPU kernel on
  TPU, interpret-mode on CPU.
- ``to_bf16`` / ``from_bf16``: BF16 wire conversion as jitted casts (the
  serializers' device-side twin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _normalize_kernel(x_ref, o_ref, *, scale, shift):
    o_ref[...] = (x_ref[...] * scale + shift).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "shift", "out_dtype"))
def normalize_image(x, scale: float = 1.0, shift: float = 0.0, out_dtype=jnp.bfloat16):
    """Fused ``x * scale + shift`` cast to ``out_dtype``.

    image_client scaling modes map directly: INCEPTION => scale=2/255,
    shift=-1; VGG => per-channel shift (applied before this call); NONE =>
    scale=1, shift=0 (pure cast).
    """
    from jax.experimental import pallas as pl

    kernel = functools.partial(_normalize_kernel, scale=scale, shift=shift)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, out_dtype),
        interpret=not _on_tpu(),
    )(x)


@jax.jit
def to_bf16(x):
    """Device-side BF16 downcast (round-to-nearest-even on the VPU)."""
    return x.astype(jnp.bfloat16)


@jax.jit
def from_bf16(x):
    """Device-side BF16 -> float32 upcast."""
    return x.astype(jnp.float32)


def stage_to_device(host_array, device=None):
    """Async host->HBM staging (returns immediately; fence at use)."""
    return jax.device_put(host_array, device)
