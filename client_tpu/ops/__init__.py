"""Jitted data-plane ops and Pallas kernels for the hot client/server paths.

The reference client's compute is numpy on the CUDA host (dtype conversion,
image preprocessing in examples). Here those run through XLA/Pallas so the
data plane stays on-device:

- ``normalize_image``: fused scale/shift/cast preprocessing (the
  image_client NONE/INCEPTION/VGG scaling modes) as a Pallas VPU kernel on
  TPU, interpret-mode on CPU.
- ``to_bf16`` / ``from_bf16``: BF16 wire conversion as jitted casts (the
  serializers' device-side twin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _normalize_kernel(x_ref, o_ref, *, scale, shift):
    o_ref[...] = (x_ref[...] * scale + shift).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "shift", "out_dtype"))
def normalize_image(x, scale: float = 1.0, shift: float = 0.0, out_dtype=jnp.bfloat16):
    """Fused ``x * scale + shift`` cast to ``out_dtype``.

    image_client scaling modes map directly: INCEPTION => scale=2/255,
    shift=-1; VGG => per-channel shift (applied before this call); NONE =>
    scale=1, shift=0 (pure cast).
    """
    from jax.experimental import pallas as pl

    kernel = functools.partial(_normalize_kernel, scale=scale, shift=shift)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, out_dtype),
        interpret=not _on_tpu(),
    )(x)


@jax.jit
def to_bf16(x):
    """Device-side BF16 downcast (round-to-nearest-even on the VPU)."""
    return x.astype(jnp.bfloat16)


@jax.jit
def from_bf16(x):
    """Device-side BF16 -> float32 upcast."""
    return x.astype(jnp.float32)


def stage_to_device(host_array, device=None):
    """Async host->HBM staging (returns immediately; fence at use)."""
    return jax.device_put(host_array, device)


# ---------------------------------------------------------------------------
# image preprocessing (resize + normalize fused under one jit)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("out_h", "out_w"))
def resize_nearest(img, out_h: int = 224, out_w: int = 224):
    """Nearest-neighbor resize of an HWC image via XLA gathers.

    The device-side twin of image_client's PIL resize (reference
    image_client.py preprocess :154): two index gathers XLA fuses with
    whatever follows.
    """
    h, w = img.shape[0], img.shape[1]
    ys = jnp.clip(
        (jnp.arange(out_h) * (h / out_h) + 0.5).astype(jnp.int32), 0, h - 1
    )
    xs = jnp.clip(
        (jnp.arange(out_w) * (w / out_w) + 0.5).astype(jnp.int32), 0, w - 1
    )
    return img[ys][:, xs]


@functools.partial(
    jax.jit, static_argnames=("out_h", "out_w", "scale", "shift", "out_dtype")
)
def preprocess_image(
    img, out_h: int = 224, out_w: int = 224, scale: float = 2.0 / 255.0,
    shift: float = -1.0, out_dtype=jnp.float32,
):
    """resize -> normalize -> HWC->CHW, one compiled program.

    The whole ensemble front stage (ImagePreprocessModel) as a single XLA
    computation: gathers fuse into the normalize elementwise, and the
    transpose is a layout assignment rather than a copy.
    """
    x = resize_nearest(img.astype(jnp.float32), out_h, out_w)
    x = x * scale + shift
    return jnp.transpose(x, (2, 0, 1)).astype(out_dtype)


# ---------------------------------------------------------------------------
# classification postprocess
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("k",))
def topk_classification(logits, k: int):
    """(values, indices) of the top-k logits along the last axis.

    ``jax.lax.top_k`` lowers to the TPU's sort unit; the server's
    classification extension ranks with this instead of a host argsort.
    """
    return jax.lax.top_k(logits, k)


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


@jax.jit
def softmax_probabilities(logits):
    """Numerically-stable softmax over the last axis as a Pallas VPU kernel
    (max-subtract, exp, normalize fused in one pass over VMEM)."""
    from jax.experimental import pallas as pl

    shaped = logits if logits.ndim > 1 else logits[None, :]
    out = pl.pallas_call(
        _softmax_kernel,
        out_shape=jax.ShapeDtypeStruct(shaped.shape, jnp.float32),
        interpret=not _on_tpu(),
    )(shaped)
    return out if logits.ndim > 1 else out[0]


# ---------------------------------------------------------------------------
# int8 wire quantization (bandwidth-limited transports)
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, o_ref, *, inv_scale):
    x = x_ref[...].astype(jnp.float32) * inv_scale
    o_ref[...] = jnp.clip(jnp.round(x), -127.0, 127.0).astype(jnp.int8)


def _dequantize_kernel(q_ref, o_ref, *, scale):
    o_ref[...] = (q_ref[...].astype(jnp.float32) * scale).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale",))
def quantize_int8(x, scale: float):
    """Symmetric int8 quantization ``round(x/scale)`` clipped to [-127,127].

    Shrinks wire tensors 4x for bandwidth-limited hops; pair with
    ``dequantize_int8`` on the receiving side. Pallas VPU kernel on TPU.
    """
    from jax.experimental import pallas as pl

    kernel = functools.partial(_quantize_kernel, inv_scale=1.0 / scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.int8),
        interpret=not _on_tpu(),
    )(x)


@functools.partial(jax.jit, static_argnames=("scale", "out_dtype"))
def dequantize_int8(q, scale: float, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_int8`."""
    from jax.experimental import pallas as pl

    kernel = functools.partial(_dequantize_kernel, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, out_dtype),
        interpret=not _on_tpu(),
    )(q)


def flash_attention(q, k, v, causal: bool = False, **kwargs):
    """Blocked online-softmax attention (Pallas kernel; see
    ops/flash_attention.py)."""
    from .flash_attention import flash_attention as impl

    return impl(q, k, v, causal=causal, **kwargs)
