"""Client base: plugin hook, auth, request bag, cumulative client statistics.

Parity with the reference's ``tritonclient/_client.py`` (:35-85),
``_plugin.py`` (:31-48), ``_request.py`` (:29-39), ``_auth.py`` (:33-45) and
the C++ ``RequestTimers``/``InferStat`` pair (src/c++/library/common.h:93-114,
:568-648) — extended with device-transfer timestamps for the TPU data path.
"""

from __future__ import annotations

import abc
import base64
import contextvars
import threading
import time
from typing import Dict, Optional, Tuple

from . import observe as _observe

# wire family segment -> data-plane accounting family
SHM_FAMILY_OF = {
    "systemsharedmemory": "system",
    "cudasharedmemory": "cuda",
    "tpusharedmemory": "tpu",
}

# the four frontends' infer() signatures share this positional prefix;
# folding positionals into kwargs lets the wrapper layers (pool, batch)
# stay drop-in replacements for code that calls e.g. client.infer("m",
# inputs, "2")
INFER_POSITIONAL_PREFIX = (
    "model_version", "outputs", "request_id", "sequence_id",
    "sequence_start", "sequence_end", "priority", "timeout",
    "client_timeout", "headers",
)


def _any_arena_lease(inputs, outputs) -> bool:
    """Does any tensor of this request carry an arena lease? (The no-arena
    hot path pays one class-attribute check per tensor and nothing else.)"""
    for inp in inputs:
        if getattr(inp, "_arena_lease", None) is not None:
            return True
    for out in outputs or ():
        if getattr(out, "_arena_lease", None) is not None:
            return True
    return False


# admission-queue phase handoff: the pool's admission gate runs BEFORE a
# frontend's request span exists, so it stashes the wait interval in a
# contextvar (thread- and task-local) and the next span begun on the same
# thread/task claims it as an ``admission_queue`` phase. Consume-once, so
# an admitted-then-errored call can never donate its wait to a later
# request. (Hedged attempts run on executor threads that don't inherit
# the caller's context — their spans simply skip the phase.)
_ADMISSION_PHASE: contextvars.ContextVar = contextvars.ContextVar(
    "client_tpu_admission_phase", default=None)


def stash_admission_phase(start_ns: int, end_ns: int) -> None:
    """Record an admission-queue wait for the next span on this context."""
    _ADMISSION_PHASE.set((start_ns, end_ns))


def consume_admission_phase() -> Optional[Tuple[int, int]]:
    value = _ADMISSION_PHASE.get()
    if value is not None:
        _ADMISSION_PHASE.set(None)
    return value


def fold_infer_args(args, kwargs):
    """Fold ``infer``'s shared positional prefix into ``kwargs``."""
    if len(args) > len(INFER_POSITIONAL_PREFIX):
        raise TypeError(
            "too many positional arguments to wrapped infer(); the "
            f"frontends diverge after {INFER_POSITIONAL_PREFIX[-1]!r} — "
            "pass the rest by keyword")
    for name, value in zip(INFER_POSITIONAL_PREFIX, args):
        if name in kwargs:
            raise TypeError(f"infer() got multiple values for argument {name!r}")
        kwargs[name] = value
    return kwargs


class Request:
    """A mutable view of an outgoing request handed to plugins (headers bag)."""

    def __init__(self, headers: Dict[str, str]):
        self.headers = headers


class InferenceServerClientPlugin(abc.ABC):
    """A plugin is invoked with the Request before every network operation.

    Subclass and implement ``__call__`` to mutate headers (auth tokens,
    tracing ids, ...).
    """

    @abc.abstractmethod
    def __call__(self, request: Request) -> None:
        ...


class BasicAuth(InferenceServerClientPlugin):
    """HTTP basic auth plugin: sets the ``authorization`` header."""

    def __init__(self, username: str, password: str):
        creds = f"{username}:{password}".encode("utf-8")
        self._auth_header = "Basic " + base64.b64encode(creds).decode("ascii")

    def __call__(self, request: Request) -> None:
        request.headers["authorization"] = self._auth_header


class InferenceServerClientBase:
    """Holds the (single) registered plugin and applies it before network ops,
    plus the shared resilience hook every frontend routes its transport
    through (see ``client_tpu.resilience``)."""

    # telemetry frontend label ("http", "grpc", "http_aio", "grpc_aio");
    # wrapper layers derive theirs from it (e.g. batch -> "http+batch")
    _FRONTEND = "client"
    # which batching wrapper coalescing() builds (aio frontends flip this)
    _BATCH_AIO = False

    def __init__(self):
        self._plugin: Optional[InferenceServerClientPlugin] = None
        self._resilience = None  # Optional[resilience.ResiliencePolicy]
        self._telemetry = None  # Optional[observe.Telemetry]
        self._shm_arena = None  # Optional[arena.ShmArena]
        # None = process-default integrity policy; False = disabled;
        # else an integrity.IntegrityPolicy
        self._integrity = None

    def _call_plugin(self, request: Request) -> None:
        if self._plugin is not None:
            self._plugin(request)

    # -- observability -------------------------------------------------------
    def configure_telemetry(self, telemetry) -> "InferenceServerClientBase":
        """Install an ``observe.Telemetry`` (or None to clear) that every
        inference of this client reports into: request-phase spans, a
        ``traceparent`` header/metadata key on the wire, and the pre-wired
        metrics. Pay-for-what-you-use: with no telemetry configured the
        transport paths check one attribute and do nothing else."""
        self._telemetry = telemetry
        return self

    def telemetry(self):
        return self._telemetry

    def _obs_begin(self, frontend: str, model: str):
        """A request span when telemetry is configured, else None — the
        single hot-path gate all four frontends share. A pending
        admission-queue wait stashed by the pool's admission gate is
        claimed onto the new span as its first phase. With a flight
        recorder armed, the span's trace id is bound onto the active
        flight scratch (or a span-owned scratch opens — this frontend is
        the outermost layer — which ``Telemetry.finish`` settles)."""
        tel = self._telemetry
        if tel is None:
            return None
        span = tel.begin(frontend, model)
        flight = getattr(tel, "flight", None)
        if flight is not None:
            flight.span_begin(span, getattr(self, "_url", None))
        pending = consume_admission_phase()
        if pending is not None:
            span.phase("admission_queue", pending[0], pending[1])
        return span

    def _obs_begin_stream(self, frontend: str, model: str,
                          op: str = "generate_stream"):
        """A stream span when telemetry is configured, else None — the
        streaming twin of ``_obs_begin`` (SSE generate streams and GRPC
        bidi streams)."""
        tel = self._telemetry
        if tel is None:
            return None
        return tel.begin_stream(frontend, model, op)

    # -- data plane ----------------------------------------------------------
    def configure_arena(self, arena) -> "InferenceServerClientBase":
        """Install a ``client_tpu.arena.ShmArena`` (``True`` = the process
        default arena; ``None`` to clear) as this client's zero-copy data
        plane: binary-staged inputs are transparently promoted into leased
        slabs at ``infer()`` time, arena-leased inputs/outputs get their
        region registrations ensured (an RPC only on first use per
        endpoint), and ``InferResult.as_numpy`` serves zero-copy views
        over leased output slabs."""
        if arena is True:
            from .arena import default_arena

            arena = default_arena()
        self._shm_arena = arena
        return self

    def arena(self):
        return self._shm_arena

    def _arena_bind(self, inputs, outputs, promote: bool = True):
        """Per-request arena binding for the sync frontends: None when the
        request touches no arena state (the common no-arena hot path costs
        one attribute check per tensor)."""
        arena = self._shm_arena
        if arena is None and not _any_arena_lease(inputs, outputs):
            return None
        from . import arena as _arena_mod

        return _arena_mod.bind_request(self, arena, inputs, outputs,
                                       promote=promote)

    async def _arena_bind_async(self, inputs, outputs, promote: bool = True):
        """Asyncio twin of :meth:`_arena_bind`."""
        arena = self._shm_arena
        if arena is None and not _any_arena_lease(inputs, outputs):
            return None
        from . import arena as _arena_mod

        return await _arena_mod.bind_request_async(
            self, arena, inputs, outputs, promote=promote)

    def _shm_call(self, family: str, op: str, call, *args,
                  region_name: Optional[str] = None, **kwargs):
        """Run one shm register/unregister RPC under data-plane accounting
        (registration latency + outcome). With no process-global recorder
        installed this is one attribute check around the plain call.
        A successful unregister also notifies the arena registration
        caches (``region_name``: the unregistered region; "" = all)."""
        rec = _observe._DATAPLANE
        if rec is None:
            result = call(*args, **kwargs)
        else:
            t0 = time.perf_counter_ns()
            try:
                result = call(*args, **kwargs)
            except BaseException:
                rec.on_rpc(self._FRONTEND, family, op,
                           (time.perf_counter_ns() - t0) * 1e-9, ok=False)
                raise
            rec.on_rpc(self._FRONTEND, family, op,
                       (time.perf_counter_ns() - t0) * 1e-9)
        if op == "unregister" and region_name is not None:
            self._arena_notify_unregister(region_name)
        return result

    async def _shm_call_async(self, family: str, op: str, call,
                              *args, region_name: Optional[str] = None,
                              **kwargs):
        """Async twin of :meth:`_shm_call` for the aio frontends."""
        rec = _observe._DATAPLANE
        if rec is None:
            result = await call(*args, **kwargs)
        else:
            t0 = time.perf_counter_ns()
            try:
                result = await call(*args, **kwargs)
            except BaseException:
                rec.on_rpc(self._FRONTEND, family, op,
                           (time.perf_counter_ns() - t0) * 1e-9, ok=False)
                raise
            rec.on_rpc(self._FRONTEND, family, op,
                       (time.perf_counter_ns() - t0) * 1e-9)
        if op == "unregister" and region_name is not None:
            self._arena_notify_unregister(region_name)
        return result

    def _arena_notify_unregister(self, region_name: str) -> None:
        """Tell every live arena the server no longer holds the
        registration (cache entries for this endpoint are dropped so the
        next use re-issues the RPC). Lazy import: processes that never
        touch the arena never load it."""
        import sys

        arena_mod = sys.modules.get("client_tpu.arena")
        if arena_mod is not None:
            arena_mod.notify_unregister(
                getattr(self, "_url", None), region_name)

    # -- ORCA endpoint load ---------------------------------------------------
    def _orca_opt_in(self, hdrs: Dict[str, str]) -> Dict[str, str]:
        """Stamp the ORCA opt-in request header when the configured
        telemetry declared an ``orca_format`` (caller-set values win)."""
        tel = self._telemetry
        if tel is not None and tel.orca_format is not None:
            hdrs.setdefault(
                _observe.ENDPOINT_LOAD_FORMAT_HEADER, tel.orca_format)
        return hdrs

    def _orca_ingest(self, result) -> None:
        """Feed a response's ORCA header (if any) into the telemetry's
        per-endpoint load gauges. Missing header → nothing happens, so
        this is safe to call on every infer."""
        tel = self._telemetry
        if tel is None:
            return
        value = result.get_response_header(_observe.ENDPOINT_LOAD_HEADER)
        if value is not None:
            tel.ingest_endpoint_load(self._url, value)

    # -- response integrity --------------------------------------------------
    def configure_integrity(self, policy) -> "InferenceServerClientBase":
        """Install an ``integrity.IntegrityPolicy`` (``True`` = the process
        default; ``None`` restores the default; ``False`` disables
        validation for this client). Contract validation runs under the
        process-default policy even when nothing is configured — every
        ``InferResult`` is checked against its request before the caller
        sees it (see docs/integrity.md)."""
        if policy is True:
            from .integrity import default_policy

            policy = default_policy()
        self._integrity = policy
        return self

    def integrity_policy(self):
        """The effective policy: the configured one, the process default
        when unconfigured, or None when explicitly disabled."""
        policy = self._integrity
        if policy is None:
            from .integrity import default_policy

            return default_policy()
        if policy is False:
            return None
        return policy

    def _integrity_check(self, result, inputs=None, outputs=None,
                         request_id: str = "", model_name: str = "") -> None:
        """Validate one unary ``InferResult`` before it reaches the caller.

        Raises ``integrity.IntegrityError`` (status INTEGRITY_VIOLATION →
        resilience's INVALID domain) on any contract violation; on the
        happy path it is pure arithmetic over bytes already in memory."""
        policy = self._integrity
        if policy is False:
            return
        from . import integrity as _integrity

        _integrity.check_result(
            result, inputs, outputs, request_id,
            url=getattr(self, "_url", "") or "", model_name=model_name,
            policy=policy, telemetry=self._telemetry)

    def _integrity_parse_note(self, err) -> None:
        """Stamp this client's url on a parse-time ``IntegrityError`` (a
        body the decoder could not even parse — torn JSON, overrun binary
        sizes) and account it into the same stats/flight/telemetry
        streams as post-parse contract violations. The caller re-raises;
        parse violations bypass the contract on/off switch because an
        undecodable body yields no result either way."""
        from . import integrity as _integrity

        policy = self._integrity
        _integrity.note_parse_violation(
            err, url=getattr(self, "_url", "") or "",
            telemetry=self._telemetry,
            policy=policy if policy not in (None, False) else None)

    def _integrity_note_metadata(self, model_name: str, metadata) -> None:
        """Fold a just-fetched model-metadata response into the effective
        policy's contract cache — the only way the cache is ever
        populated (responses never teach the contract: a byzantine
        replica answering first could otherwise poison it)."""
        policy = self.integrity_policy()
        if policy is not None and model_name:
            policy.note_metadata(model_name, metadata)

    def _integrity_stream_checker(self, model_name: str = ""):
        """A per-stream ``integrity.StreamChecker`` when the effective
        policy opted into stream-index checks, else None."""
        policy = self.integrity_policy()
        if policy is None or not policy.stream_index:
            return None
        from .integrity import StreamChecker

        return StreamChecker(getattr(self, "_url", "") or "", policy)

    # -- resilience ---------------------------------------------------------
    def configure_resilience(self, policy) -> "InferenceServerClientBase":
        """Install a ``resilience.ResiliencePolicy`` (or None to clear) that
        every network operation of this client runs under. Pay-for-what-you-
        use: with no policy configured the transport paths are untouched."""
        self._resilience = policy
        return self

    def resilience_policy(self):
        return self._resilience

    def _resilience_for(self, override):
        """The effective policy for one request (per-request override hook).

        ``override=False`` explicitly bypasses the configured policy — the
        health-probe paths use it so a probe observes the endpoint itself,
        never an open circuit breaker's fast-fail."""
        if override is False:
            return None
        return override if override is not None else self._resilience

    # -- micro-batching -----------------------------------------------------
    def coalescing(self, **kwargs):
        """Wrap this client in the opt-in coalescing dispatcher
        (``client_tpu.batch``): concurrent compatible ``infer()`` calls are
        stacked into one KServe request within an adaptive window and the
        result rows scattered back per caller. Returns a
        ``BatchingClient`` (or the asyncio twin for aio frontends); the
        client's configured telemetry is adopted automatically."""
        from .batch import AioBatchingClient, BatchingClient

        cls = AioBatchingClient if self._BATCH_AIO else BatchingClient
        return cls(self, **kwargs)

    # -- hot-key serving ----------------------------------------------------
    def caching(self, **kwargs):
        """Wrap this client in the opt-in singleflight + response-cache
        layer (``client_tpu.cache``): concurrent identical ``infer()``
        calls collapse onto one wire request, and repeated content keys
        are served from a bounded LRU+TTL cache as zero-copy arena views.
        Returns a ``CachingClient`` (or the asyncio twin for aio
        frontends); the client's configured telemetry is adopted
        automatically. Compose OUTSIDE ``.coalescing()`` — hits skip the
        coalescing window, misses may still ride a batch."""
        from .cache import AioCachingClient, CachingClient

        cls = AioCachingClient if self._BATCH_AIO else CachingClient
        return cls(self, **kwargs)

    def register_plugin(self, plugin: InferenceServerClientPlugin) -> None:
        if plugin is None:
            raise ValueError("cannot register a null plugin")
        if self._plugin is not None:
            raise ValueError("A plugin is already registered. Unregister it first.")
        self._plugin = plugin

    def plugin(self) -> Optional[InferenceServerClientPlugin]:
        return self._plugin

    def unregister_plugin(self) -> None:
        if self._plugin is None:
            raise ValueError("No plugin is registered.")
        self._plugin = None


class RequestTimers:
    """Per-request monotonic nanosecond timestamps.

    Kinds mirror the reference's six points and add two TPU device-transfer
    points (host->device and device->host staging around the wire/shm hop).
    """

    REQUEST_START = "REQUEST_START"
    REQUEST_END = "REQUEST_END"
    SEND_START = "SEND_START"
    SEND_END = "SEND_END"
    RECV_START = "RECV_START"
    RECV_END = "RECV_END"
    H2D_START = "H2D_START"  # host->HBM staging (TPU extension)
    H2D_END = "H2D_END"
    D2H_START = "D2H_START"  # HBM->host staging (TPU extension)
    D2H_END = "D2H_END"

    __slots__ = ("_ts",)

    def __init__(self):
        self._ts: Dict[str, int] = {}

    def capture(self, kind: str) -> None:
        self._ts[kind] = time.perf_counter_ns()

    def get(self, kind: str) -> Optional[int]:
        return self._ts.get(kind)

    def duration_ns(self, start_kind: str, end_kind: str) -> int:
        s, e = self._ts.get(start_kind), self._ts.get(end_kind)
        if s is None or e is None or e < s:
            return 0
        return e - s


class InferStat:
    """Cumulative client-side inference statistics (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed_request_count = 0
        self.cumulative_total_request_time_ns = 0
        self.cumulative_send_time_ns = 0
        self.cumulative_receive_time_ns = 0
        self.cumulative_h2d_time_ns = 0
        self.cumulative_d2h_time_ns = 0

    def update(self, timers: RequestTimers) -> None:
        with self._lock:
            self.completed_request_count += 1
            self.cumulative_total_request_time_ns += timers.duration_ns(
                RequestTimers.REQUEST_START, RequestTimers.REQUEST_END
            )
            self.cumulative_send_time_ns += timers.duration_ns(
                RequestTimers.SEND_START, RequestTimers.SEND_END
            )
            self.cumulative_receive_time_ns += timers.duration_ns(
                RequestTimers.RECV_START, RequestTimers.RECV_END
            )
            self.cumulative_h2d_time_ns += timers.duration_ns(
                RequestTimers.H2D_START, RequestTimers.H2D_END
            )
            self.cumulative_d2h_time_ns += timers.duration_ns(
                RequestTimers.D2H_START, RequestTimers.D2H_END
            )

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return {
                "completed_request_count": self.completed_request_count,
                "cumulative_total_request_time_ns": self.cumulative_total_request_time_ns,
                "cumulative_send_time_ns": self.cumulative_send_time_ns,
                "cumulative_receive_time_ns": self.cumulative_receive_time_ns,
                "cumulative_h2d_time_ns": self.cumulative_h2d_time_ns,
                "cumulative_d2h_time_ns": self.cumulative_d2h_time_ns,
            }

    def __str__(self) -> str:
        d = self.as_dict()
        n = max(d["completed_request_count"], 1)
        return (
            f"completed_request_count {d['completed_request_count']}\n"
            f"avg_request_time_us {d['cumulative_total_request_time_ns'] // n // 1000}\n"
            f"avg_send_time_us {d['cumulative_send_time_ns'] // n // 1000}\n"
            f"avg_receive_time_us {d['cumulative_receive_time_ns'] // n // 1000}\n"
            f"avg_h2d_time_us {d['cumulative_h2d_time_ns'] // n // 1000}\n"
            f"avg_d2h_time_us {d['cumulative_d2h_time_ns'] // n // 1000}"
        )
