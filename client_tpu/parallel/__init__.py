"""Device-mesh sharding for multi-chip serving and the dry-run train step.

The reference has no multi-device execution (it is a network client); this
package is where the TPU build scales the *server side*: a
``jax.sharding.Mesh`` over the chips, batch sharded on the ``data`` axis,
wide layers sharded on the ``model`` axis, XLA inserting the collectives.
Used by the in-process server for multi-chip model instances and by
``__graft_entry__.dryrun_multichip`` to validate the shardings compile.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple


def make_mesh(n_devices: Optional[int] = None, axis_names: Tuple[str, str] = ("data", "model")):
    """A 2D (data x model) mesh over the first ``n_devices`` devices.

    Factorizes n into (dp, tp) with tp as large as possible up to 4 — wide
    enough to exercise tensor-parallel collectives, while keeping a data
    axis for batch scaling.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices but only {len(devices)} available")
    tp = 1
    for cand in (4, 2):
        if n % cand == 0:
            tp = cand
            break
    dp = n // tp
    import numpy as np

    grid = np.array(devices[:n]).reshape(dp, tp)
    return Mesh(grid, axis_names)


def _param_sharding(mesh, path_leaf_shape):
    """model-axis sharding rule: shard the last (output-feature) axis of
    2D+ kernels over 'model'; replicate everything else."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def rule(path, leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 2 and leaf.shape[-1] % mesh.shape["model"] == 0:
            spec = [None] * (leaf.ndim - 1) + ["model"]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return rule


def shard_params(params, mesh):
    """Place a parameter pytree onto the mesh (tp on output features)."""
    import jax

    rule = _param_sharding(mesh, None)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.device_put(leaf, rule(path, leaf)), params
    )


def sharded_forward(module_apply, mesh):
    """jit the forward pass with batch sharded over 'data'.

    Parameters keep their (possibly model-sharded) placement; XLA inserts
    the all-gathers/psums the tp layout requires.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P("data"))

    @jax.jit
    def fwd(params, batch):
        return module_apply(params, batch)

    def run(params, batch):
        batch = jax.device_put(batch, batch_sharding)
        return fwd(params, batch)

    return run


def sharded_train_step(module_apply, optimizer, mesh):
    """A full dp+tp training step over the mesh (used by dryrun_multichip).

    Cross-entropy loss, grads averaged over the data axis (psum inserted by
    XLA from the sharded batch), optimizer update applied in place on the
    sharded params.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P("data"))

    def loss_fn(params, images, labels):
        logits = module_apply(params, images)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))

    @jax.jit
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def run(params, opt_state, images, labels):
        images = jax.device_put(images, batch_sharding)
        labels = jax.device_put(labels, batch_sharding)
        return step(params, opt_state, images, labels)

    return run
