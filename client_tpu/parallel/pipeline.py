"""Pipeline parallelism: GPipe-style microbatch streaming over the mesh.

Stage s of the network lives on device s of the pipeline axis; activations
hop one ICI link per step (``lax.ppermute``) while microbatches stream in,
so all devices compute concurrently once the pipeline fills. Exact: the
result equals applying the stages sequentially.

Layout: stage parameters are stacked on a leading axis sharded over the
pipeline axis (device s holds stack[s]); the input batch is split into
microbatches that enter at device 0 and exit at device S-1 after S hops.
"""

from __future__ import annotations


def mlp_stage_params(key, n_stages: int, dim: int):
    """Stacked per-stage MLP params: (W [S, dim, dim], b [S, dim])."""
    import jax
    import jax.numpy as jnp

    kw, kb = jax.random.split(key)
    scale = (2.0 / dim) ** 0.5
    w = jax.random.normal(kw, (n_stages, dim, dim), jnp.float32) * scale
    b = jax.random.normal(kb, (n_stages, dim), jnp.float32) * 0.01
    return w, b


def sequential_mlp(w, b, x):
    """Reference: apply all stages in order on one device."""
    import jax.numpy as jnp

    h = x
    for s in range(w.shape[0]):
        h = jnp.maximum(h @ w[s] + b[s], 0.0)
    return h


def pipeline_forward(w, b, x, mesh, axis: str = "model", n_microbatches: int = 4):
    """Run the stacked-stage MLP as a pipeline over ``axis``.

    w: [S, dim, dim], b: [S, dim] with S == mesh.shape[axis];
    x: [batch, dim] with batch divisible by n_microbatches.
    Returns [batch, dim], equal to ``sequential_mlp(w, b, x)``.
    """
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis]
    if w.shape[0] != n_stages:
        raise ValueError(f"need {n_stages} stages for mesh axis '{axis}', got {w.shape[0]}")
    batch, dim = x.shape
    if batch % n_microbatches != 0:
        raise ValueError(f"batch {batch} must divide by n_microbatches {n_microbatches}")
    mb = batch // n_microbatches
    x_mb = x.reshape(n_microbatches, mb, dim)
    total_steps = n_stages + n_microbatches - 1
    # one hop toward the next stage; the wrap link's payload is ignored
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def block(w_blk, b_blk, x_all):
        # w_blk: [1, dim, dim] this device's stage; x_all: [M, mb, dim] replicated
        stage_w = w_blk[0]
        stage_b = b_blk[0]
        stage_index = lax.axis_index(axis)

        def step(carry, t):
            buf = carry  # [mb, dim]: activation arriving at this device
            mb_index = jnp.clip(t, 0, n_microbatches - 1)
            fresh = lax.dynamic_index_in_dim(x_all, mb_index, 0, keepdims=False)
            feed = jnp.where(stage_index == 0, fresh, buf)
            y = jnp.maximum(feed @ stage_w + stage_b, 0.0)
            buf_next = lax.ppermute(y, axis, perm)
            return buf_next, y

        buf0 = lax.pvary(jnp.zeros((mb, dim), x.dtype), (axis,))
        _, ys = lax.scan(step, buf0, jnp.arange(total_steps))
        return ys[None]  # [1, T, mb, dim]; concat over devices outside

    ys = shard_map(
        block,
        mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None), P(None, None, None)),
        out_specs=P(axis, None, None, None),
    )(w, b, x_mb)
    # device S-1 emits microbatch m at step (S-1) + m
    last = ys[n_stages - 1]
    out = last[n_stages - 1 : n_stages - 1 + n_microbatches]
    return out.reshape(batch, dim)
