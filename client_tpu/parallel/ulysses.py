"""Ulysses-style sequence parallelism: all-to-all head/sequence repartition.

The second canonical long-context scheme next to ring attention
(``ring.py``): instead of rotating K/V around the ring, two ``all_to_all``
collectives re-partition the tensors so each device holds the FULL sequence
for a SLICE of the heads, runs dense attention locally, and re-partitions
back. Trade-offs vs the ring:

- collectives: 3 all-to-alls in, 1 out (O(1) steps) vs the ring's 2(n-1)
  ppermute hops — Ulysses wins when the interconnect handles all-to-all
  well (TPU ICI does) and sequence blocks are large;
- memory: each device materializes its heads' full [seq, seq] score matrix,
  so the ring remains the choice when seq² per head exceeds HBM;
- constraint: heads must divide by the mesh axis (the ring requires seq to).

Both are exact. ``sequence_parallel_attention`` picks per call.
"""

from __future__ import annotations


def ulysses_attention(q, k, v, mesh, axis: str = "data", causal: bool = False):
    """Exact attention with the sequence axis sharded over ``axis``.

    q, k, v: [batch, seq, heads, dim]; ``heads`` must divide by the axis
    size (and ``seq`` by it too, as it arrives sharded). Returns the same
    sharding as the inputs. ``causal`` is free here: after the all-to-all
    each device holds the full sequence, so the mask is the ordinary
    lower triangle.
    """
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    batch, seq, heads, dim = q.shape
    if seq % n != 0:
        raise ValueError(f"seq {seq} must divide by mesh axis size {n}")
    if heads % n != 0:
        raise ValueError(f"heads {heads} must divide by mesh axis size {n}")

    from .ring import full_attention

    def block(q_blk, k_blk, v_blk):
        # local shards: [b, seq/n, h, d] -> all-to-all -> [b, seq, h/n, d]
        def scatter_heads(x):
            return lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        def gather_heads(x):
            return lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        q_full = scatter_heads(q_blk)
        k_full = scatter_heads(k_blk)
        v_full = scatter_heads(v_blk)
        out = full_attention(q_full, k_full, v_full, causal=causal)
        return gather_heads(out)

    spec = P(None, axis, None, None)
    return shard_map(
        block, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


def sequence_parallel_attention(
    q, k, v, mesh, axis: str = "data", mode: str = "auto", causal: bool = False
):
    """Dispatch between ring and Ulysses context parallelism.

    ``mode``: "ring", "ulysses", or "auto" — auto prefers Ulysses when the
    head count divides the axis (fewer collective steps) and falls back to
    the ring otherwise (or when the local score matrix would be huge).
    """
    from .ring import ring_attention

    n = mesh.shape[axis]
    if mode == "ring":
        return ring_attention(q, k, v, mesh, axis, causal=causal)
    if mode == "ulysses":
        return ulysses_attention(q, k, v, mesh, axis, causal=causal)
    if mode != "auto":
        raise ValueError(f"unknown sequence-parallel mode {mode!r}")
    heads_divide = q.shape[2] % n == 0
    # per-device footprint under Ulysses: scores + probs for every local
    # head over every batch element, 2 * batch * h/n * seq^2 floats
    score_bytes = 2 * q.shape[0] * (q.shape[2] // max(n, 1)) * q.shape[1] ** 2 * 4
    if heads_divide and score_bytes < (1 << 30):
        return ulysses_attention(q, k, v, mesh, axis, causal=causal)
    return ring_attention(q, k, v, mesh, axis, causal=causal)
