"""Ring attention: context parallelism for long sequences.

Long-context serving shards the sequence axis across the mesh; attention
then needs every query block to see every key/value block. Ring attention
keeps Q resident per device and rotates K/V one hop around the ring each
step (``lax.ppermute`` — rides ICI on real hardware), accumulating the
softmax online (log-sum-exp streaming), so no device ever materializes the
full [seq, seq] score matrix and per-device memory is O(seq/n · seq/n).

This is the TPU-native answer to the template's long-context mandate: the
client framework's server side can host sequence lengths that exceed a
single chip's HBM. Exact (matches full attention to numerical tolerance).
"""

from __future__ import annotations


def full_attention(q, k, v, causal: bool = False):
    """Reference dense attention. q,k,v: [batch, seq, heads, dim]."""
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jnp.exp(scores - scores.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q, k, v, mesh, axis: str = "data", causal: bool = False):
    """Exact attention with the sequence axis sharded over ``axis``.

    q, k, v: [batch, seq, heads, dim]; seq must divide by the axis size.
    Returns [batch, seq, heads, dim] with the same sharding. ``causal``
    masks at block granularity: a K/V block strictly after the query block
    contributes nothing, the diagonal block applies the in-block triangle —
    the standard causal-ring formulation (the compute for skipped blocks
    still rotates; a production kernel would also skip the FLOPs).
    """
    import jax.numpy as jnp
    from jax import lax, shard_map  # requires the jax that also has lax.pvary
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"seq {q.shape[1]} must divide by mesh axis size {n}")
    scale = q.shape[-1] ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block(q_blk, k_blk, v_blk):
        # q_blk/k_blk/v_blk: the local [batch, seq/n, heads, dim] shards
        batch, sq, heads, dim = q_blk.shape
        my_index = lax.axis_index(axis)

        def scores_of(k_cur):
            return jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_cur) * scale

        def step(carry, i):
            k_cur, v_cur, acc, m, l = carry
            # rotate at the top of iterations 1..n-1: the ring sends exactly
            # 2(n-1) collectives, none wasted on a discarded final hop
            k_cur, v_cur = lax.cond(
                i > 0,
                lambda kv: (
                    lax.ppermute(kv[0], axis, perm),
                    lax.ppermute(kv[1], axis, perm),
                ),
                lambda kv: kv,
                (k_cur, v_cur),
            )
            s = scores_of(k_cur)  # [b, h, sq, sk]
            if causal:
                # after i hops this device holds the block that started at
                # device (my_index - i) mod n
                kv_index = (my_index - i) % n
                q_pos = my_index * sq + jnp.arange(sq)
                k_pos = kv_index * sq + jnp.arange(sq)
                allowed = q_pos[:, None] >= k_pos[None, :]  # [sq, sk]
                s = jnp.where(allowed[None, None], s, -jnp.inf)
            m_new = jnp.maximum(m, s.max(-1))
            p = jnp.exp(s - m_new[..., None])
            correction = jnp.exp(m - m_new)
            l_new = l * correction + p.sum(-1)
            acc_new = (
                acc * correction[..., None]
                + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur)
            )
            return (k_cur, v_cur, acc_new, m_new, l_new), None

        # pvary: the accumulators must carry the same varying-axes type as
        # the per-shard data or lax.scan rejects the carry
        acc0 = lax.pvary(jnp.zeros((batch, heads, sq, dim), jnp.float32), (axis,))
        m0 = lax.pvary(jnp.full((batch, heads, sq), -jnp.inf, jnp.float32), (axis,))
        l0 = lax.pvary(jnp.zeros((batch, heads, sq), jnp.float32), (axis,))
        (k_fin, v_fin, acc, m, l), _ = lax.scan(
            step,
            (k_blk.astype(jnp.float32), v_blk.astype(jnp.float32), acc0, m0, l0),
            jnp.arange(n),
        )
        del k_fin, v_fin
        # causal first row(s) see at least the diagonal block, so l > 0 for
        # every query; keep the guard for numerical robustness anyway
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q_blk.dtype)

    spec = P(None, axis, None, None)
    return shard_map(
        block, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


def place_sharded(arr, mesh, axis: str = "data"):
    """Shard [batch, seq, ...] on the sequence dim over ``axis``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndim = arr.ndim
    spec = [None] * ndim
    spec[1] = axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
