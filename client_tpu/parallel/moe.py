"""Expert parallelism: a mixture-of-experts FFN with token dispatch.

The fifth sharding family next to dp/tp (``__init__``), pp (``pipeline``),
and sp (``ring``/``ulysses``): expert weights live sharded over a mesh axis
and tokens travel to their expert's device over ICI ``all_to_all`` — the
canonical MoE dispatch (route → scatter into capacity buffers → all-to-all
→ expert FFN on resident weights → all-to-all back → combine).

Exact w.r.t. the dense reference when ``capacity`` admits every routed
token (tests use full capacity); production configs trade capacity for
balance and accept drops, which is a quality knob, not a correctness one.
"""

from __future__ import annotations


def dense_moe_reference(x, gate_w, w1, w2):
    """Reference top-1 MoE on one device. x: [T, d]; gate_w: [d, E];
    w1: [E, d, h]; w2: [E, h, d]."""
    import jax.numpy as jnp

    scores = x @ gate_w                      # [T, E]
    expert = jnp.argmax(scores, axis=-1)     # [T]
    gate = jnp.take_along_axis(
        jnp.asarray(scores, jnp.float32), expert[:, None], axis=-1
    )[:, 0]
    out = jnp.zeros_like(x)
    for e in range(w1.shape[0]):             # tiny E in tests; reference only
        h = jnp.maximum(x @ w1[e], 0.0)
        y = h @ w2[e]
        out = out + jnp.where((expert == e)[:, None], y, 0.0)
    return out * gate[:, None]


def moe_ffn(x, gate_w, w1, w2, mesh, axis: str = "model", capacity: int = 0):
    """Top-1 MoE FFN with experts sharded over ``axis``.

    x: [T, d] sharded over ``axis`` on the token dim; gate_w replicated;
    w1: [E, d, h] / w2: [E, h, d] sharded over ``axis`` on the expert dim.
    E and T must divide by the axis size. ``capacity`` is the per-(device,
    expert) token budget; 0 means the local token count (lossless).

    Dispatch shape: tokens scatter into [E, C, d] send buffers, an
    ``all_to_all`` regroups them by expert owner, the owner applies its
    resident experts, and the inverse ``all_to_all`` carries results home.
    """
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    tokens, d = x.shape
    n_experts = w1.shape[0]
    if tokens % n != 0:
        raise ValueError(f"tokens {tokens} must divide by mesh axis size {n}")
    if n_experts % n != 0:
        raise ValueError(f"experts {n_experts} must divide by mesh axis size {n}")
    local_tokens = tokens // n
    cap = capacity or local_tokens
    experts_per_device = n_experts // n

    def block(x_blk, gate_w_blk, w1_blk, w2_blk):
        # x_blk: [T/n, d]; w1_blk: [E/n, d, h]; w2_blk: [E/n, h, d]
        scores = x_blk @ gate_w_blk                       # [T/n, E]
        expert = jnp.argmax(scores, axis=-1)              # [T/n]
        gate = jnp.take_along_axis(
            jnp.asarray(scores, jnp.float32), expert[:, None], axis=-1
        )[:, 0]

        # position of each token within its expert's capacity buffer
        one_hot = jnp.asarray(expert[:, None] == jnp.arange(n_experts)[None, :],
                              jnp.int32)                  # [T/n, E]
        position = (jnp.cumsum(one_hot, axis=0) - 1)      # running index
        slot = jnp.take_along_axis(position, expert[:, None], axis=-1)[:, 0]
        keep = slot < cap                                 # capacity overflow drops

        # scatter local tokens into [E, C, d] send buffers
        send = jnp.zeros((n_experts, cap, d), x_blk.dtype)
        send = send.at[expert, jnp.where(keep, slot, 0)].add(
            jnp.where(keep[:, None], x_blk, 0.0)
        )

        # regroup by expert owner: [n, E/n, C, d] -> all_to_all over devices
        send = send.reshape(n, experts_per_device, cap, d)
        received = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        # received: [n, E/n, C, d] — every device's tokens for MY experts
        received = jnp.transpose(received, (1, 0, 2, 3))  # [E/n, n, C, d]
        flat = received.reshape(experts_per_device, n * cap, d)

        # resident experts run on their tokens (batched einsum over E/n)
        hidden = jnp.maximum(jnp.einsum("ekd,edh->ekh", flat, w1_blk), 0.0)
        result = jnp.einsum("ekh,ehd->ekd", hidden, w2_blk)

        # inverse path home
        result = jnp.transpose(result.reshape(experts_per_device, n, cap, d),
                               (1, 0, 2, 3))              # [n, E/n, C, d]
        back = lax.all_to_all(result, axis, split_axis=0, concat_axis=0,
                              tiled=True)
        back = back.reshape(n_experts, cap, d)            # my tokens' results

        # gather each token's result from (its expert, its slot)
        out = back[expert, slot] * keep[:, None]
        return (out * gate[:, None]).astype(x_blk.dtype)

    return shard_map(
        block,
        mesh=mesh,
        in_specs=(P(axis, None), P(None, None), P(axis, None, None),
                  P(axis, None, None)),
        out_specs=P(axis, None),
    )(x, gate_w, w1, w2)
