"""Multi-host distributed runtime: process bootstrap + DCN/ICI-aware meshes.

The reference scales its data plane across hosts with NCCL/MPI-backed
infrastructure; the TPU-native equivalent is the JAX distributed runtime —
every process calls :func:`initialize`, the PJRT client forms one global
device view, and XLA lowers collectives onto **ICI within a slice and DCN
between slices** according to mesh axis order. The scaling-book recipe this
module encodes: put DCN-parallel axes (data, fsdp) OUTERMOST and
ICI-parallel axes (model/tensor) INNERMOST, so the slow inter-host fabric
only carries gradient-sized traffic while activation-sized collectives ride
ICI.

Reference parity: there is no reference counterpart file — triton's client
is single-process — but SURVEY.md §5 maps "distributed comm backend" onto
exactly this layer. Validated two ways:
- `tests/test_multihost.py` spawns REAL separate OS processes (CPU
  backend, Gloo transport) forming a global mesh, and asserts psum / train
  step exactness against a single-process run;
- on TPU pods the same code path auto-detects the slice topology
  (``initialize()`` with no args).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join (or form) the multi-process runtime.

    On TPU pods call with no arguments — the plugin discovers the slice
    topology. Off-TPU (CPU/dev clusters) pass coordinator/count/id
    explicitly or via ``CLIENT_TPU_COORDINATOR`` / ``CLIENT_TPU_NPROCS`` /
    ``CLIENT_TPU_PROC_ID``. Idempotent: a second call is a no-op.
    """
    import jax

    if getattr(initialize, "_done", False):
        return
    coordinator_address = coordinator_address or os.environ.get(
        "CLIENT_TPU_COORDINATOR")
    if num_processes is None and "CLIENT_TPU_NPROCS" in os.environ:
        num_processes = int(os.environ["CLIENT_TPU_NPROCS"])
    if process_id is None and "CLIENT_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["CLIENT_TPU_PROC_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    initialize._done = True


def global_mesh(
    axis_names: Tuple[str, str] = ("data", "model"),
    data_parallel: Optional[int] = None,
):
    """A 2-D global mesh over every device in the cluster.

    The ``data`` (DCN-friendly) axis defaults to the number of PROCESSES —
    each host's local devices line up along ``model`` — so tensor-parallel
    collectives stay on-host (ICI) and only data-parallel gradient
    reductions cross DCN. ``data_parallel`` overrides when a host's devices
    should split across both axes.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = len(devices)
    dp = data_parallel or max(jax.process_count(), 1)
    if n % dp != 0:
        raise ValueError(
            f"{n} global devices do not divide into data_parallel={dp}")
    # jax.devices() orders by process then local id, so this reshape puts
    # each process's devices contiguous along the model axis
    grid = np.array(devices).reshape(dp, n // dp)
    return Mesh(grid, axis_names)


def hybrid_mesh(
    dcn_axes: Tuple[int, ...],
    ici_axes: Tuple[int, ...],
    axis_names: Tuple[str, ...],
):
    """Slice-topology-aware mesh for TPU pods (DCN axes outermost).

    Thin wrapper over ``mesh_utils.create_hybrid_device_mesh`` so callers
    state intent (which axes cross slices) instead of device orderings,
    e.g. ``hybrid_mesh((2,), (4, 4), ("data", "fsdp", "model"))`` for two
    v5e-16 slices. Falls back to a plain reshape off-TPU where slice
    boundaries don't exist.
    """
    import jax
    from jax.sharding import Mesh

    shape = tuple(dcn_axes) + tuple(ici_axes)
    if len(shape) != len(axis_names):
        raise ValueError(f"{len(shape)} axis sizes vs {len(axis_names)} names")
    devices = jax.devices()
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh {shape} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    if jax.default_backend() == "tpu":
        from jax.experimental import mesh_utils

        # Each named axis is PURELY dcn or PURELY ici: pad both per-axis
        # factor tuples with 1s so create_hybrid_device_mesh's elementwise
        # products land each size on its own axis — no reshape afterwards
        # (a reshape from the combined grid interleaves dcn/ici granules
        # across named axes and silently routes model collectives to DCN).
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) * len(dcn_axes) + tuple(ici_axes),
            dcn_mesh_shape=tuple(dcn_axes) + (1,) * len(ici_axes),
        )
    else:
        # CPU / GPU: no slice topology exists; document-order reshape is
        # the only meaningful layout (process-major, like jax.devices())
        grid = np.array(devices).reshape(shape)
    return Mesh(grid, axis_names)


def process_local_batch(global_batch: int) -> int:
    """Per-process slice of a global batch (data sharded over processes)."""
    import jax

    count = max(jax.process_count(), 1)
    if global_batch % count != 0:
        raise ValueError(
            f"global batch {global_batch} does not divide over "
            f"{count} processes")
    return global_batch // count
