"""First-class multi-tenancy: quotas, weights and per-tenant SLO windows.

"Millions of users" are not one user a million times. Until now the
client's QoS machinery — admission lanes, the response cache, the
singleflight table, batch coalescing — was tenant-blind: one hostile
caller could fill a lane's queue, evict every other caller's hot cache
set, or collapse onto answers it never computed. This module is the
shared vocabulary that makes tenancy a first-class, *enforced* dimension:

- :class:`TenantSpec` — one tenant's declared contract: scheduling
  ``weight`` (its share of contended admission capacity), a token-bucket
  ``rate``/``burst`` quota (requests/s; ``None`` = unmetered), an
  optional per-tenant latency SLO (``slo_ms`` at ``slo_objective``), and
  an optional response-cache byte budget (``cache_bytes``).

- :class:`TenancyPolicy` — the live registry the enforcement points
  share. ``client_tpu.admission.AdmissionController(tenancy=...)`` asks
  it for quota verdicts (:meth:`try_take` — an over-quota request sheds
  with the typed reason ``over_quota`` and an HONEST ``retry_after_s``,
  the time until the bucket refills one token) and for WFQ weights (the
  per-tenant virtual queues in the controller drain proportionally to
  weight). Completions feed per-tenant SLO burn windows
  (:meth:`on_result`); :meth:`snapshot` is the doctor's ``tenancy``
  section and :meth:`noisy_neighbors` its ``noisy_neighbor`` anomaly —
  naming the tenant whose offered load dwarfs its quota.

- **Quota sheds are policy, not capacity.** ``over_quota`` is
  deliberately NOT in ``admission.SPILL_REASONS``: a federation layer
  must never answer a quota denial by silently moving the tenant's
  excess to another cell (that would launder the quota away), and
  ``resilience.classify_fault`` maps the shed to the ``SHED`` domain —
  never retried, never a breaker/ejection signal.

- **Isolation, not just fairness.** The tenant is folded into the shared
  ``batch.plan_request`` content key, so the response cache, the
  singleflight table AND batch coalescing all partition by tenant in one
  place — a tenant can never be served (or collapse onto) another
  tenant's response object, and ``cache.ResponseCache`` additionally
  partitions its byte budget per tenant so one tenant's zipf churn
  cannot evict another's hot set. Tenantless callers (``tenant=None``)
  keep byte-identical keys and behavior.

Wiring: every frontend and wrapper accepts ``infer(..., tenant=...)``;
the pool pops it before the wire (like ``affinity_key``) and passes it to
admission. Telemetry export rides :meth:`TenancyPolicy.attach_telemetry`
(per-tenant admitted/shed/burn gauges at scrape time). See
docs/tenancy.md for the quota algebra and the full interaction matrix.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

__all__ = [
    "DEFAULT_TENANT_LABEL",
    "TenancyPolicy",
    "TenantSpec",
    "parse_tenancy_spec",
    "policies",
]

# the {tenant=...} label exported for tenantless traffic (tenant=None);
# a real tenant may not claim it (parse rejects the name)
DEFAULT_TENANT_LABEL = "_default"

# noisy-neighbor verdict thresholds: a tenant is flagged when its
# over-quota sheds are both numerous (>= _NOISY_MIN_SHEDS: one burst of a
# handful of sheds is not an attack) and dominate its admitted traffic
# (>= _NOISY_SHED_FACTOR x admitted: the tenant is offering a multiple of
# its quota, not riding the boundary)
_NOISY_MIN_SHEDS = 16
_NOISY_SHED_FACTOR = 2.0


class TenantSpec:
    """One tenant's declared contract (immutable after construction).

    ``weight`` is the WFQ share under contention (relative to the other
    tenants' weights; 2.0 drains twice as often as 1.0). ``rate`` /
    ``burst`` arm the token-bucket quota: a sustained ``rate`` requests/s
    with bursts up to ``burst`` tokens (default ``max(rate, 1)``);
    ``rate=None`` is unmetered. ``slo_ms`` (with ``slo_objective``)
    declares the tenant's latency SLO — completions feed a windowed
    burn gauge. ``cache_bytes`` caps the tenant's response-cache
    partition (``None``: an equal split of the cache's watermark)."""

    __slots__ = ("name", "weight", "rate", "burst", "slo_ms",
                 "slo_objective", "cache_bytes")

    def __init__(self, name: Optional[str], weight: float = 1.0,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 slo_ms: Optional[float] = None,
                 slo_objective: float = 0.99,
                 cache_bytes: Optional[int] = None):
        if name == DEFAULT_TENANT_LABEL:
            raise ValueError(
                f"tenant name {DEFAULT_TENANT_LABEL!r} is reserved for "
                "tenantless traffic")
        if weight <= 0.0:
            raise ValueError("weight must be > 0")
        if rate is not None and rate <= 0.0:
            raise ValueError("rate must be > 0 (or None for unmetered)")
        if burst is not None:
            if rate is None:
                raise ValueError("burst without rate is meaningless")
            if burst < 1.0:
                raise ValueError("burst must be >= 1")
        if not 0.0 < slo_objective < 1.0:
            raise ValueError("slo_objective must be in (0, 1)")
        if slo_ms is not None and slo_ms <= 0.0:
            raise ValueError("slo_ms must be > 0")
        if cache_bytes is not None and cache_bytes < 1:
            raise ValueError("cache_bytes must be >= 1")
        self.name = name
        self.weight = float(weight)
        self.rate = float(rate) if rate is not None else None
        self.burst = (float(burst) if burst is not None
                      else (max(self.rate, 1.0)
                            if self.rate is not None else None))
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        self.slo_objective = float(slo_objective)
        self.cache_bytes = int(cache_bytes) if cache_bytes else None

    @property
    def label(self) -> str:
        return self.name if self.name is not None else DEFAULT_TENANT_LABEL

    def replace(self, name: Optional[str]) -> "TenantSpec":
        """This spec re-issued under another tenant's name (the template
        path for tenants first seen at runtime)."""
        return TenantSpec(
            name, weight=self.weight, rate=self.rate, burst=self.burst,
            slo_ms=self.slo_ms, slo_objective=self.slo_objective,
            cache_bytes=self.cache_bytes)

    def to_obj(self) -> Dict[str, Any]:
        return {
            "weight": self.weight, "rate": self.rate, "burst": self.burst,
            "slo_ms": self.slo_ms, "slo_objective": self.slo_objective,
            "cache_bytes": self.cache_bytes,
        }


class _TokenBucket:
    """The quota meter: ``burst`` capacity refilled at ``rate``/s.
    Mutations happen under the owning policy's lock."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst  # a fresh tenant may burst immediately
        self.last = now

    def _refill(self, now: float) -> None:
        elapsed = now - self.last
        if elapsed > 0.0:
            self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self.last = now

    def take(self, now: float) -> Tuple[bool, Optional[float]]:
        """``(admitted, retry_after_s)``. The hint is the honest
        backpressure signal: exactly the time until the bucket holds one
        whole token again."""
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, None
        return False, (1.0 - self.tokens) / self.rate

    def charge(self, now: float) -> None:
        """Unconditional debit (force-admitted sequence steps): the debt
        is bounded at one burst below empty so a long sequence cannot
        mortgage the tenant's quota forever."""
        self._refill(now)
        self.tokens = max(-self.burst, self.tokens - 1.0)


class _BurnWindow:
    """A subwindowed good/bad event window (the per-tenant twin of the
    observe-layer SLO burn machinery, small enough to live on the
    admission path). Mutations under the owning policy's lock."""

    __slots__ = ("window_s", "subwindows", "_sub_s", "_good", "_bad",
                 "_period")

    def __init__(self, window_s: float = 30.0, subwindows: int = 6):
        self.window_s = float(window_s)
        self.subwindows = int(subwindows)
        self._sub_s = self.window_s / self.subwindows
        self._good = [0] * self.subwindows
        self._bad = [0] * self.subwindows
        self._period = 0

    def _rotate(self, now: float) -> int:
        period = int(now / self._sub_s)
        if period != self._period:
            empty = min(period - self._period, self.subwindows)
            for i in range(1, empty + 1):
                slot = (self._period + i) % self.subwindows
                self._good[slot] = 0
                self._bad[slot] = 0
            self._period = period
        return period % self.subwindows

    def observe(self, ok: bool, now: float) -> None:
        slot = self._rotate(now)
        if ok:
            self._good[slot] += 1
        else:
            self._bad[slot] += 1

    def counts(self, now: float) -> Tuple[int, int]:
        self._rotate(now)
        return sum(self._good), sum(self._bad)


class _TenantState:
    """One tenant's live accounting: quota bucket, cumulative counters
    and the windowed SLO burn. Mutations under the policy lock."""

    __slots__ = ("spec", "bucket", "admitted_total", "shed_by_reason",
                 "completions", "breaches_total", "window")

    def __init__(self, spec: TenantSpec, now: float,
                 window_s: float):
        self.spec = spec
        self.bucket = (_TokenBucket(spec.rate, spec.burst, now)
                       if spec.rate is not None else None)
        self.admitted_total = 0
        self.shed_by_reason: Dict[str, int] = {}
        self.completions = 0
        self.breaches_total = 0
        self.window = _BurnWindow(window_s)


class TenancyPolicy:
    """The per-tenant quota/weight/SLO registry shared by the
    enforcement points (admission, cache, doctor, telemetry).

    ``tenants``: the declared :class:`TenantSpec` contracts. ``default``
    is the TEMPLATE for tenants first seen at runtime (auto-registered
    under their own name); its default — unmetered, weight 1 — means an
    undeclared tenant is admitted like today's tenantless traffic, just
    separately queued and accounted. Tenantless requests
    (``tenant=None``) ride their own ``_default`` row. Thread-safe: one
    short lock around every operation."""

    def __init__(self, tenants: Iterable[TenantSpec] = (),
                 default: Optional[TenantSpec] = None,
                 window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.window_s = float(window_s)
        self._default = default or TenantSpec(None)
        self._states: "Dict[Optional[str], _TenantState]" = {}
        now = clock()
        for spec in tenants:
            if spec.name in self._states:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            self._states[spec.name] = _TenantState(
                spec, now, self.window_s)
        _POLICIES.add(self)

    # -- registry -------------------------------------------------------------
    def _state(self, tenant: Optional[str]) -> _TenantState:
        """The tenant's live state (auto-registered from the default
        template on first sight). Caller holds the lock."""
        state = self._states.get(tenant)
        if state is None:
            spec = (self._default if tenant is None
                    else self._default.replace(tenant))
            state = self._states[tenant] = _TenantState(
                spec, self._clock(), self.window_s)
        return state

    def spec(self, tenant: Optional[str]) -> TenantSpec:
        with self._lock:
            return self._state(tenant).spec

    def weight(self, tenant: Optional[str]) -> float:
        with self._lock:
            return self._state(tenant).spec.weight

    def tenants(self) -> List[Optional[str]]:
        with self._lock:
            return list(self._states)

    # -- quota ---------------------------------------------------------------
    def try_take(self, tenant: Optional[str]
                 ) -> Tuple[bool, Optional[float]]:
        """One admission attempt against the tenant's quota:
        ``(admitted, retry_after_s)``. Unmetered tenants always pass."""
        with self._lock:
            state = self._state(tenant)
            if state.bucket is None:
                return True, None
            return state.bucket.take(self._clock())

    def charge(self, tenant: Optional[str]) -> None:
        """Unconditional quota debit (force-admitted sequence steps)."""
        with self._lock:
            state = self._state(tenant)
            if state.bucket is not None:
                state.bucket.charge(self._clock())

    # -- accounting (fed by the admission controller) -------------------------
    def on_admit(self, tenant: Optional[str]) -> None:
        with self._lock:
            self._state(tenant).admitted_total += 1

    def on_shed(self, tenant: Optional[str], reason: str) -> None:
        with self._lock:
            state = self._state(tenant)
            state.shed_by_reason[reason] = (
                state.shed_by_reason.get(reason, 0) + 1)
            # a shed counts against the tenant's SLO window: the request
            # was NOT served inside its objective (same rule as the
            # capacity harness — shed capacity is not delivered capacity)
            state.window.observe(False, self._clock())

    def on_result(self, tenant: Optional[str],
                  latency_s: Optional[float], ok: bool) -> None:
        """One completion under the tenant's admission slot. ``ok=False``
        or a latency above the tenant's ``slo_ms`` is a bad event in the
        burn window; tenants with no declared SLO count errors only."""
        with self._lock:
            state = self._state(tenant)
            state.completions += 1
            good = ok
            if (good and state.spec.slo_ms is not None
                    and latency_s is not None
                    and latency_s * 1e3 > state.spec.slo_ms):
                good = False
            if not good and ok:
                state.breaches_total += 1
            elif not ok:
                state.breaches_total += 1
            state.window.observe(good, self._clock())

    # -- read side ------------------------------------------------------------
    def _row(self, state: _TenantState, now: float) -> Dict[str, Any]:
        good, bad = state.window.counts(now)
        total = good + bad
        budget = 1.0 - state.spec.slo_objective
        burn = ((bad / total) / budget if total and budget > 0.0 else 0.0)
        row: Dict[str, Any] = {
            "spec": state.spec.to_obj(),
            "admitted_total": state.admitted_total,
            "shed": dict(state.shed_by_reason),
            "completions": state.completions,
            "slo_breaches_total": state.breaches_total,
            "window": {"good": good, "bad": bad,
                       "burn_rate": round(burn, 4),
                       "breached": bool(total) and burn > 1.0},
        }
        if state.bucket is not None:
            state.bucket._refill(now)
            row["quota_tokens"] = round(state.bucket.tokens, 3)
        return row

    def snapshot(self) -> Dict[str, Any]:
        """The doctor's ``tenancy`` section: one row per tenant plus the
        policy-level noisy-neighbor verdicts."""
        with self._lock:
            now = self._clock()
            rows = {
                (DEFAULT_TENANT_LABEL if name is None else name):
                    self._row(state, now)
                for name, state in self._states.items()
            }
        noisy = self.noisy_neighbors()
        return {
            "tenants": rows,
            "window_s": self.window_s,
            "noisy_neighbors": noisy,
        }

    def noisy_neighbors(self) -> List[Dict[str, Any]]:
        """Tenants whose over-quota sheds dominate their admitted
        traffic — the adversarial-neighbor signature. Each verdict NAMES
        the tenant and quantifies its overreach (offered ≈ admitted +
        sheds vs the quota that admitted implies)."""
        from .admission import SHED_OVER_QUOTA

        out: List[Dict[str, Any]] = []
        with self._lock:
            for name, state in self._states.items():
                sheds = state.shed_by_reason.get(SHED_OVER_QUOTA, 0)
                if sheds < _NOISY_MIN_SHEDS:
                    continue
                admitted = state.admitted_total
                if sheds < _NOISY_SHED_FACTOR * max(1, admitted):
                    continue
                offered = admitted + sum(state.shed_by_reason.values())
                out.append({
                    "tenant": (DEFAULT_TENANT_LABEL if name is None
                               else name),
                    "over_quota_sheds": sheds,
                    "admitted_total": admitted,
                    "offered_over_admitted": round(
                        offered / max(1, admitted), 2),
                })
        return out

    # -- telemetry ------------------------------------------------------------
    def attach_telemetry(self, telemetry) -> "TenancyPolicy":
        """Export per-tenant gauges on the telemetry's registry at scrape
        time (cumulative counters exported as gauges, like the cache
        layer's eviction export): admitted/shed totals, quota tokens,
        SLO burn rate and the breached flag. Held by weak reference —
        attaching never extends this policy's lifetime."""
        reg = telemetry.registry
        admitted = reg.gauge(
            "client_tpu_tenant_admitted_total",
            "Requests admitted per tenant (cumulative, exported at "
            "scrape)", ("tenant",))
        shed = reg.gauge(
            "client_tpu_tenant_shed_total",
            "Requests shed per tenant by reason (cumulative, exported "
            "at scrape)", ("tenant", "reason"))
        tokens = reg.gauge(
            "client_tpu_tenant_quota_tokens",
            "Live token-bucket level per metered tenant", ("tenant",))
        burn = reg.gauge(
            "client_tpu_tenant_slo_burn_rate",
            "Windowed per-tenant SLO burn rate (1.0 = burning exactly "
            "the budget)", ("tenant",))
        breached = reg.gauge(
            "client_tpu_tenant_slo_breached",
            "1 when the tenant's windowed burn rate exceeds its budget",
            ("tenant",))
        self_ref = weakref.ref(self)

        def collect() -> None:
            policy = self_ref()
            if policy is None:
                return
            snap = policy.snapshot()
            for label, row in snap["tenants"].items():
                admitted.labels(label).set(row["admitted_total"])
                for reason, n in row["shed"].items():
                    shed.labels(label, reason).set(n)
                if "quota_tokens" in row:
                    tokens.labels(label).set(row["quota_tokens"])
                window = row["window"]
                burn.labels(label).set(window["burn_rate"])
                breached.labels(label).set(
                    1.0 if window["breached"] else 0.0)

        reg.add_collector(collect)
        return self


# live policies (the doctor's tenancy section enumerates these, exactly
# like cache.caches())
_POLICIES: "weakref.WeakSet[TenancyPolicy]" = weakref.WeakSet()


def policies() -> List[TenancyPolicy]:
    """Every live TenancyPolicy in this process."""
    return list(_POLICIES)


# spec-string keys -> TenantSpec kwargs (the CLI/bench surface)
_SPEC_KEYS = {
    "weight": float, "w": float,
    "rate": float, "r": float,
    "burst": float, "b": float,
    "slo_ms": float,
    "slo_objective": float,
    "cache_bytes": int,
}
_SPEC_CANON = {"w": "weight", "r": "rate", "b": "burst"}


def parse_tenancy_spec(spec: str,
                       default: Optional[TenantSpec] = None,
                       window_s: float = 30.0,
                       clock: Callable[[], float] = time.monotonic,
                       ) -> TenancyPolicy:
    """Build a policy from a flat spec string (the perf/bench surface):
    ``name,key=value,...;name2,...`` — e.g.
    ``"alpha,rate=50,weight=2;beta,rate=50;adv,rate=50,slo_ms=250"``.
    Keys: ``weight``/``w``, ``rate``/``r``, ``burst``/``b``, ``slo_ms``,
    ``slo_objective``, ``cache_bytes``."""
    specs: List[TenantSpec] = []
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        name, _, rest = entry.partition(",")
        name = name.strip()
        if not name:
            raise ValueError(f"tenancy spec entry {entry!r} has no name")
        kwargs: Dict[str, Any] = {}
        for part in filter(None, (p.strip() for p in rest.split(","))):
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(
                    f"malformed tenancy param {part!r} (want key=value)")
            key = key.strip()
            conv = _SPEC_KEYS.get(key)
            if conv is None:
                raise ValueError(
                    f"unknown tenancy param {key!r} "
                    f"(one of {sorted(set(_SPEC_CANON.values()) | set(k for k in _SPEC_KEYS if len(k) > 1))})")
            kwargs[_SPEC_CANON.get(key, key)] = conv(value.strip())
        specs.append(TenantSpec(name, **kwargs))
    if not specs:
        raise ValueError(f"empty tenancy spec {spec!r}")
    return TenancyPolicy(specs, default=default, window_s=window_s,
                         clock=clock)
