"""Hot-key serving: client-side singleflight + a bounded response cache.

Zipfian fleets repeat themselves: identical concurrent prompts, the same
classification input from thousands of users, the same feature vector
polled every second. Until now every one of those requests paid a full
wire round-trip — N callers, N serializations, N server executions for
ONE answer. This module makes a hot key cost the fleet ~one request:

- **Singleflight** — concurrent ``infer()`` calls with an identical
  *content key* (a stable hash over model, version, input names/dtypes/
  shapes/bytes, requested outputs and parameters — the same
  compatibility-key plumbing as ``client_tpu.batch``, via
  :func:`~client_tpu.batch.plan_request`) collapse onto ONE wire request:
  the first caller in becomes the leader, everyone else parks until the
  leader's result scatters back. A failed leader fans the SAME typed
  error to every collapsed caller. The leader's single inner ``infer``
  composes with ``.coalescing()`` (a leader may still ride a batch) and
  with pools (one routing/admission decision per collapsed group).

- **A bounded response cache** — LRU + TTL with a byte-size watermark.
  Entries are staged into :class:`~client_tpu.arena.ShmArena` slabs
  (``ShmArena.stage``) held by ref-counted leases, so a cache hit's
  ``as_numpy`` is a ZERO-COPY lease-pinned view that stays valid past the
  wire buffer — and a trimmed/evicted entry raises the typed
  :class:`~client_tpu.arena.ArenaLeaseReleased` instead of ever returning
  aliased memory. Errors are never cached. ``invalidate(model=...)``
  drops entries explicitly, and ``load_model``/``unload_model`` through
  the wrapper (including a pool's fleet-wide broadcast) invalidate that
  model's entries automatically. ``stale_while_revalidate_s`` is a typed
  opt-in: a TTL-expired entry inside the staleness window is served
  immediately (marked ``stale=True``) while ONE background refresh —
  deduplicated through the same singleflight table — repopulates it.

What never collapses or caches (the exact ``batch.py`` exclusion
matrix, shared via :func:`~client_tpu.batch.plan_request`): sequence
requests, per-request ``resilience=`` overrides, shm-bound or
JSON-staged tensors, per-tensor parameters, classification and
shm-placed outputs. Those bypass to the inner client verbatim.

Usage::

    from client_tpu.cache import CachingClient

    client = CachingClient("127.0.0.1:8000", protocol="http",
                           ttl_s=5.0, max_bytes=64 << 20)
    client.infer("classifier", inputs)      # hot keys cost ~one request

    # or wrap an existing client/pool/batcher (cache OUTSIDE batching:
    # hits skip the coalescing window entirely, misses may ride a batch)
    client = PoolClient(urls).coalescing().caching()

See docs/caching.md for the key algebra and the full interaction matrix.
"""

from __future__ import annotations

import asyncio
import hashlib
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import flight as _flight_recorder
from ._base import fold_infer_args
from .batch import plan_request
from .utils import (
    InferenceServerException,
    serialize_bf16_tensor,
    serialize_byte_tensor,
)

__all__ = [
    "AioCachingClient",
    "CachedInferResult",
    "CachingClient",
    "ResponseCache",
    "caches",
    "content_key",
]


def content_key(model_name: str, inputs, kwargs: Optional[Dict] = None,
                ) -> Optional[str]:
    """The stable content hash identifying one request's ANSWER: model,
    version, per-input (name, dtype, shape) plus the staged bytes,
    requested outputs, and every semantic parameter. Two requests with
    equal keys are guaranteed byte-identical on the wire, so one may
    answer for the other. Returns None for requests outside the shared
    eligibility matrix (see :func:`~client_tpu.batch.plan_request`)."""
    kwargs = dict(kwargs or {})
    plan = plan_request(list(inputs), kwargs)
    if plan is None:
        return None
    return _digest(model_name, plan)


def _digest(model_name: str, plan) -> str:
    sig, rows, raw_by_name, out_sig, extra_key = plan
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((model_name, rows, sig, out_sig, extra_key)).encode())
    for name, _, _ in sig:  # sig is sorted, so payload order is canonical
        payload = raw_by_name[name]
        # length framing: adjacent payloads can never collide by shifting
        h.update(len(payload).to_bytes(8, "little"))
        h.update(payload)
    return h.hexdigest()


class _CacheEntry:
    """One cached response: the sanitized response header plus each
    output's payload staged in an arena lease (datatype, shape, lease).
    The entry owns ONE reference per lease; eviction/invalidation
    releases them, after which views raise ``ArenaLeaseReleased``."""

    __slots__ = ("key", "model", "response", "outputs", "nbytes",
                 "inserted_at", "hits", "tenant")

    def __init__(self, key: str, model: str, response: Dict[str, Any],
                 outputs: Dict[str, Tuple[str, Tuple[int, ...], Any]],
                 nbytes: int, inserted_at: float,
                 tenant: Optional[str] = None):
        self.key = key
        self.model = model
        self.response = response
        self.outputs = outputs
        self.nbytes = nbytes
        self.inserted_at = inserted_at
        self.hits = 0
        self.tenant = tenant

    def release(self) -> None:
        from .arena import ArenaError

        for _, _, lease in self.outputs.values():
            try:
                lease.release()
            except ArenaError:
                pass  # already torn down elsewhere (arena close at exit)


class CachedInferResult:
    """A cache hit, quacking like the frontends' ``InferResult``.

    ``as_numpy`` returns a zero-copy view over the entry's arena slab,
    pinned by the entry's lease: valid while the entry lives, and raising
    the typed :class:`~client_tpu.arena.ArenaLeaseReleased` once the
    entry was evicted, invalidated or TTL-expired — never aliased bytes.
    ``retain()``/``release()`` pin the underlying leases past eviction
    for callers that hold views across cache churn — ``release()`` drops
    only references THIS result added, so a caller cannot release the
    cache's own hold on a still-resident entry."""

    __slots__ = ("_entry", "_retains", "stale")

    cached = True

    def __init__(self, entry: _CacheEntry, stale: bool = False):
        self._entry = entry
        self._retains = 0
        self.stale = stale

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        spec = self._entry.outputs.get(name)
        if spec is None:
            return None
        datatype, shape, lease = spec
        return lease.as_numpy(datatype, shape)

    def as_jax(self, name: str, device=None):
        arr = self.as_numpy(name)
        if arr is None:
            return None
        if arr.dtype == np.object_:
            raise InferenceServerException(
                "BYTES outputs cannot be placed on device")
        import jax

        return jax.device_put(arr, device)

    def get_response(self) -> Dict[str, Any]:
        return self._entry.response

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        for out in self._entry.response.get("outputs", []):
            if out.get("name") == name:
                return out
        return None

    def get_response_header(self, name: str, default=None):
        # transport headers (ORCA load et al.) describe a LIVE exchange;
        # a cached answer has none — never serve a stale load report
        return default

    def age_s(self, clock=time.monotonic) -> float:
        return max(0.0, clock() - self._entry.inserted_at)

    def retain(self) -> "CachedInferResult":
        for _, _, lease in self._entry.outputs.values():
            lease.retain()
        self._retains += 1
        return self

    def release(self) -> None:
        """Drop one retain this result holds (no-op when it holds none —
        the entry's own references belong to the cache, and releasing
        them here would corrupt a still-resident entry)."""
        if self._retains <= 0:
            return
        self._retains -= 1
        self._entry.release()


class ResponseCache:
    """LRU + TTL response cache bounded by a byte-size watermark.

    Entries are arena-staged (``ShmArena.stage``) so hits serve zero-copy
    lease-pinned views. Thread-safe; all methods are one short lock.
    ``clock`` is injectable for deterministic TTL tests.

    **Tenant partitioning**: the byte/entry watermarks are split into
    per-tenant PARTITIONS — eviction only ever reclaims within the
    inserting tenant's partition, so one tenant's zipf churn can never
    evict another tenant's hot set. A tenant's byte budget is its
    ``TenantSpec.cache_bytes`` when a ``tenancy`` policy declares one,
    else an equal share (``max_bytes // partitions``); entry budgets are
    always equal shares. With a single partition (the tenantless default)
    the split is the whole watermark — byte-identical legacy behavior.
    Isolation of CONTENT (tenant A never *served* tenant B's response)
    does not live here: the tenant is folded into the content key by
    ``batch.plan_request``, so cross-tenant keys never collide."""

    def __init__(
        self,
        ttl_s: float = 30.0,
        max_bytes: int = 64 * 1024 * 1024,
        max_entries: int = 4096,
        stale_while_revalidate_s: float = 0.0,
        arena=None,
        tenancy=None,
        clock=time.monotonic,
    ):
        if ttl_s <= 0:
            raise ValueError("ttl_s must be > 0")
        if max_bytes <= 0 or max_entries < 1:
            raise ValueError("max_bytes/max_entries must be positive")
        if stale_while_revalidate_s < 0:
            raise ValueError("stale_while_revalidate_s must be >= 0")
        if arena is None:
            from .arena import default_arena

            arena = default_arena()
        self.ttl_s = float(ttl_s)
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.stale_while_revalidate_s = float(stale_while_revalidate_s)
        self.arena = arena
        self.tenancy = tenancy
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _CacheEntry]" = OrderedDict()
        self._bytes = 0
        # tenant partitions: a partition exists from the first insert
        # under that tenant and persists (budgets stay stable even when a
        # partition momentarily empties)
        self._partitions: set = set()
        self._tenant_bytes: Dict[Optional[str], int] = {}
        self._tenant_entries: Dict[Optional[str], int] = {}
        self._stats = {
            "hits": 0, "misses": 0, "stale_hits": 0, "insertions": 0,
            "uncacheable": 0, "invalidations": 0,
            "evictions": {"capacity": 0, "ttl": 0, "replaced": 0,
                          "oversize": 0},
        }
        _CACHES.add(self)

    # -- partition accounting ----------------------------------------------
    def _account_remove_locked(self, entry: _CacheEntry) -> None:
        self._bytes -= entry.nbytes
        t = entry.tenant
        self._tenant_bytes[t] = self._tenant_bytes.get(t, 0) - entry.nbytes
        self._tenant_entries[t] = self._tenant_entries.get(t, 0) - 1

    def _account_add_locked(self, entry: _CacheEntry) -> None:
        self._bytes += entry.nbytes
        t = entry.tenant
        self._tenant_bytes[t] = self._tenant_bytes.get(t, 0) + entry.nbytes
        self._tenant_entries[t] = self._tenant_entries.get(t, 0) + 1

    def _partition_budget_locked(
            self, tenant: Optional[str]) -> Tuple[int, int]:
        """The partition's ``(byte_budget, entry_budget)``: the declared
        ``cache_bytes`` when a tenancy policy carries one for this
        tenant, else an equal share of the watermark. One partition
        (the tenantless default) gets the whole cache."""
        nparts = max(1, len(self._partitions))
        byte_budget = self.max_bytes // nparts
        entry_budget = max(1, self.max_entries // nparts)
        if self.tenancy is not None:
            declared = self.tenancy.spec(tenant).cache_bytes
            if declared:
                byte_budget = declared
        return max(1, byte_budget), entry_budget

    def _evict_tenant_locked(self, tenant: Optional[str],
                             victims: List[_CacheEntry],
                             newcomer: Optional[_CacheEntry] = None) -> None:
        """Reclaim the tenant's partition down to its budget — oldest of
        THIS tenant first, other tenants' entries untouchable."""
        byte_budget, entry_budget = self._partition_budget_locked(tenant)
        while (self._tenant_bytes.get(tenant, 0) > byte_budget
               or self._tenant_entries.get(tenant, 0) > entry_budget):
            victim_key = next(
                (k for k, e in self._entries.items() if e.tenant == tenant),
                None)
            if victim_key is None:
                break
            victim = self._entries[victim_key]
            if victim is newcomer:
                # the newcomer alone busts the partition against a hot
                # survivor set: stop — nothing older of ours remains
                break
            del self._entries[victim_key]
            self._account_remove_locked(victim)
            self._stats["evictions"]["capacity"] += 1
            victims.append(victim)

    def _register_partition_locked(self, tenant: Optional[str],
                                   victims: List[_CacheEntry]) -> None:
        """First insert under a new tenant: the equal-share budgets
        shrank for every existing partition — trim them NOW so the new
        tenant's guaranteed share is actually free, not hostage to
        whoever filled the cache first."""
        if tenant in self._partitions:
            return
        self._partitions.add(tenant)
        for other in self._partitions:
            if other != tenant:
                self._evict_tenant_locked(other, victims)

    # -- lookup ------------------------------------------------------------
    def lookup(self, key: str) -> Tuple[str, Optional[_CacheEntry]]:
        """``("hit"|"stale"|"miss", entry)``. A TTL-expired entry inside
        the stale-while-revalidate window is returned as ``"stale"`` (the
        caller serves it and revalidates); past the window it is evicted
        (reason ``ttl``) and reported as a miss."""
        now = self._clock()
        released: Optional[_CacheEntry] = None
        try:
            with self._lock:
                entry = self._entries.get(key)
                if entry is None:
                    self._stats["misses"] += 1
                    return "miss", None
                age = now - entry.inserted_at
                if age <= self.ttl_s:
                    self._entries.move_to_end(key)
                    entry.hits += 1
                    self._stats["hits"] += 1
                    return "hit", entry
                if (self.stale_while_revalidate_s
                        and age <= self.ttl_s + self.stale_while_revalidate_s):
                    self._entries.move_to_end(key)
                    entry.hits += 1
                    self._stats["stale_hits"] += 1
                    return "stale", entry
                released = self._entries.pop(key)
                self._account_remove_locked(released)
                self._stats["evictions"]["ttl"] += 1
                self._stats["misses"] += 1
                return "miss", None
        finally:
            if released is not None:
                released.release()  # outside the lock: may take arena locks

    # -- insert ------------------------------------------------------------
    @staticmethod
    def _serialize_output(datatype: str, arr: np.ndarray):
        """One output's staged payload: exactly the arena lease encoding
        that ``ArenaLease.as_numpy(datatype, shape)`` decodes back."""
        if datatype == "BYTES" or arr.dtype == np.object_ \
                or arr.dtype.kind in ("S", "U"):
            s = serialize_byte_tensor(arr)
            return s.item() if s.size else b""
        if datatype == "BF16":
            s = serialize_bf16_tensor(arr)
            return s.item() if s.size else b""
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)

    def insert(self, key: str, model: str, result,
               tenant: Optional[str] = None) -> Optional[_CacheEntry]:
        """Stage one successful response into the cache; returns the new
        entry, or None when the response is uncacheable (an output whose
        payload the client cannot decode — e.g. a non-arena shm region).
        Errors must never reach here: the wrapper only inserts successes.
        ``tenant`` selects the partition charged (and reclaimed from) —
        eviction never crosses into another tenant's partition."""
        outputs: Dict[str, Tuple[str, Tuple[int, ...], Any]] = {}
        out_rows: List[Dict[str, Any]] = []
        nbytes = 0
        try:
            response = result.get_response()
            for out in response.get("outputs", []) or []:
                name = out.get("name")
                datatype = out.get("datatype")
                shape = tuple(int(d) for d in out.get("shape") or ())
                arr = result.as_numpy(name)
                if arr is None:
                    raise _Uncacheable()
                lease = self.arena.stage(
                    self._serialize_output(datatype, arr))
                outputs[name] = (datatype, shape, lease)
                nbytes += lease.byte_size
                # the sanitized header: wire-body byte counts and shm
                # params describe buffers this entry does not hold
                row = {k: v for k, v in out.items() if k != "parameters"}
                params = {
                    k: v for k, v in (out.get("parameters") or {}).items()
                    if k not in ("binary_data_size", "shared_memory_region",
                                 "shared_memory_byte_size",
                                 "shared_memory_offset")}
                if params:
                    row["parameters"] = params
                out_rows.append(row)
        except _Uncacheable:
            for _, _, lease in outputs.values():
                lease.release()
            with self._lock:
                self._stats["uncacheable"] += 1
            return None
        except BaseException:
            for _, _, lease in outputs.values():
                lease.release()
            raise
        header = {k: v for k, v in response.items()
                  if k != "raw_output_contents"}
        header["outputs"] = out_rows
        entry = _CacheEntry(key, model, header, outputs, nbytes,
                            self._clock(), tenant)
        victims: List[_CacheEntry] = []
        oversize = False
        with self._lock:
            self._register_partition_locked(tenant, victims)
            byte_budget, _ = self._partition_budget_locked(tenant)
            if nbytes > byte_budget:
                # oversize is judged against the PARTITION's budget: a
                # response no amount of own-partition eviction could fit
                self._stats["evictions"]["oversize"] += 1
                oversize = True
            else:
                old = self._entries.pop(key, None)
                if old is not None:
                    victims.append(old)
                    self._account_remove_locked(old)
                    self._stats["evictions"]["replaced"] += 1
                self._entries[key] = entry
                self._account_add_locked(entry)
                self._stats["insertions"] += 1
                self._evict_tenant_locked(tenant, victims, newcomer=entry)
        if oversize:
            for _, _, lease in outputs.values():
                lease.release()
        for victim in victims:
            victim.release()
        return None if oversize else entry

    # -- invalidation ------------------------------------------------------
    def invalidate(self, model: Optional[str] = None,
                   key: Optional[str] = None) -> int:
        """Drop entries by model name, by exact key, or (neither given)
        ALL entries. Returns the number dropped."""
        victims: List[_CacheEntry] = []
        with self._lock:
            if key is not None:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    victims.append(entry)
            else:
                for k in [k for k, e in self._entries.items()
                          if model is None or e.model == model]:
                    victims.append(self._entries.pop(k))
            for victim in victims:
                self._account_remove_locked(victim)
            self._stats["invalidations"] += len(victims)
        for victim in victims:
            victim.release()
        return len(victims)

    def clear(self) -> int:
        return self.invalidate()

    # -- read side ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self._stats.items()}
            s["entries"] = len(self._entries)
            s["bytes_resident"] = self._bytes
            s["max_bytes"] = self.max_bytes
            s["ttl_s"] = self.ttl_s
            lookups = s["hits"] + s["stale_hits"] + s["misses"]
            s["hit_rate"] = (round((s["hits"] + s["stale_hits"]) / lookups, 4)
                             if lookups else None)
            # per-tenant partition rows, only once a real (non-None)
            # tenant has inserted — tenantless stats stay byte-identical
            if any(t is not None for t in self._partitions):
                s["tenants"] = {
                    (t if t is not None else "_default"): {
                        "bytes_resident": self._tenant_bytes.get(t, 0),
                        "entries": self._tenant_entries.get(t, 0),
                        "byte_budget":
                            self._partition_budget_locked(t)[0],
                    }
                    for t in sorted(self._partitions,
                                    key=lambda t: (t is None, t or ""))
                }
        return s


class _Uncacheable(Exception):
    """Internal: an output's payload cannot be staged client-side."""


def _fan_error(error: Optional[BaseException]) -> Optional[BaseException]:
    """What a collapsed follower should see for its leader's failure: the
    SAME typed error for real failures, but an interrupted/cancelled
    leader (KeyboardInterrupt, asyncio cancellation) must NOT propagate
    its control-flow exception into tasks that were never interrupted —
    followers get a typed retryable error instead."""
    if error is None or isinstance(error, Exception):
        return error
    return InferenceServerException(
        "singleflight leader was interrupted/cancelled before completing; "
        "retry the request", status="499")


# live caches (the doctor's cache section enumerates these)
_CACHES: "weakref.WeakSet[ResponseCache]" = weakref.WeakSet()


def caches() -> List[ResponseCache]:
    """Every live ResponseCache in this process."""
    return list(_CACHES)


class _Flight:
    """One in-flight singleflight group: the leader publishes its outcome
    here and every collapsed follower reads it. ``entry`` set = serve a
    fresh cache view; else ``result`` is the shared transport result."""

    __slots__ = ("cond", "done", "entry", "result", "error", "followers",
                 "future")

    def __init__(self):
        self.cond = threading.Condition()
        self.done = False
        self.entry: Optional[_CacheEntry] = None
        self.result = None
        self.error: Optional[BaseException] = None
        self.followers = 0
        self.future = None  # aio only

    def materialize(self):
        if self.entry is not None:
            return CachedInferResult(self.entry)
        return self.result


class _CachingCore:
    """Construction, eligibility, accounting and cache plumbing shared by
    the sync and asyncio wrappers."""

    _AIO = False

    def __init__(
        self,
        client,
        protocol: str = "http",
        cache=True,
        singleflight: bool = True,
        ttl_s: float = 30.0,
        max_bytes: int = 64 * 1024 * 1024,
        max_entries: int = 4096,
        stale_while_revalidate_s: float = 0.0,
        arena=None,
        tenancy=None,
        telemetry=None,
    ):
        """``client``: an existing frontend/pool/batching client to wrap,
        or a ``host:port`` url (built with ``protocol``). ``cache``: a
        :class:`ResponseCache` to share, ``True`` to build one from
        ``ttl_s``/``max_bytes``/``max_entries``/
        ``stale_while_revalidate_s``/``arena``, or ``None``/``False`` for
        singleflight-only operation (no entries retained). ``tenancy``:
        a ``client_tpu.tenancy.TenancyPolicy`` whose per-tenant
        ``cache_bytes`` declarations size the cache's tenant partitions
        (forwarded to the built :class:`ResponseCache`). ``telemetry``:
        an ``observe.Telemetry``; when omitted the inner client's is
        adopted."""
        if isinstance(client, str):
            from .pool import _default_client_factory

            client = _default_client_factory(protocol, self._AIO)(client)
        self._inner = client
        if cache is True:
            cache = ResponseCache(
                ttl_s=ttl_s, max_bytes=max_bytes, max_entries=max_entries,
                stale_while_revalidate_s=stale_while_revalidate_s,
                arena=arena, tenancy=tenancy)
        elif cache is False:
            cache = None
        self._cache: Optional[ResponseCache] = cache
        self._singleflight = bool(singleflight)
        if self._cache is None and not self._singleflight:
            raise ValueError(
                "a CachingClient with cache=None and singleflight=False "
                "would be a no-op wrapper")
        self._frontend = f"{getattr(client, '_FRONTEND', 'client')}+cache"
        self._flights_lock = threading.Lock()
        self._flights: Dict[str, _Flight] = {}
        self._closed = False
        self._stats_lock = threading.Lock()
        self._counts = {
            "bypass": 0, "hit": 0, "stale": 0, "miss": 0,
            "collapsed": 0, "revalidations": 0, "revalidate_errors": 0,
        }
        self._telemetry = None
        self._instruments = None
        if telemetry is None:
            accessor = getattr(client, "telemetry", None)
            if callable(accessor):
                try:
                    telemetry = accessor()
                except Exception:
                    telemetry = None
        if telemetry is not None:
            self.configure_telemetry(telemetry)

    # -- configuration -------------------------------------------------------
    def configure_telemetry(self, telemetry):
        """Install (or clear) the telemetry this wrapper reports into:
        per-caller spans with a ``cache_lookup`` phase, hit/miss/collapse
        counters, and scrape-time residency gauges. The inner client's
        telemetry (tracing the wire request on a miss) is configured
        separately on the inner client."""
        self._telemetry = telemetry
        if telemetry is None:
            self._instruments = None
            return self
        reg = telemetry.registry
        requests = reg.counter(
            "client_tpu_cache_requests_total",
            "Caller-level infers through the caching wrapper, by outcome "
            "(hit/stale/miss/bypass)", ("model", "outcome"))
        collapsed = reg.counter(
            "client_tpu_singleflight_collapsed_total",
            "Callers that rode another caller's in-flight identical "
            "request instead of issuing their own", ("model",))
        bytes_gauge = reg.gauge(
            "client_tpu_cache_bytes_resident",
            "Bytes held by live response-cache entries (arena slabs)")
        entries_gauge = reg.gauge(
            "client_tpu_cache_entries", "Live response-cache entries")
        evictions_gauge = reg.gauge(
            "client_tpu_cache_evictions_total",
            "Cache evictions by reason (cumulative, exported at scrape)",
            ("reason",))
        self._instruments = (requests, collapsed)
        cache = self._cache
        if cache is not None:
            cache_ref = weakref.ref(cache)

            def collect() -> None:
                c = cache_ref()
                if c is None:
                    return
                s = c.stats()
                bytes_gauge.set(s["bytes_resident"])
                entries_gauge.set(s["entries"])
                for reason, n in s["evictions"].items():
                    evictions_gauge.labels(reason).set(n)

            reg.add_collector(collect)
        return self

    def telemetry(self):
        return self._telemetry

    def cache(self) -> Optional[ResponseCache]:
        return self._cache

    def invalidate(self, model: Optional[str] = None,
                   key: Optional[str] = None) -> int:
        """Explicitly drop cached entries (see ResponseCache.invalidate);
        0 when running singleflight-only."""
        if self._cache is None:
            return 0
        return self._cache.invalidate(model=model, key=key)

    # -- accounting ----------------------------------------------------------
    def _count(self, model: str, outcome: str) -> None:
        with self._stats_lock:
            self._counts[outcome] += 1
        instruments = self._instruments
        if instruments is not None:
            requests, collapsed = instruments
            if outcome == "collapsed":
                collapsed.labels(model).inc()
            else:
                requests.labels(model, outcome).inc()

    # note: no ``stats()`` here on purpose — the name belongs to the
    # batching dispatcher, and ``pool.coalescing().caching()`` must keep
    # delegating it through __getattr__; this wrapper's row is cache_stats
    def cache_stats(self) -> Dict[str, Any]:
        """One JSON-ready row: wrapper outcome counts + the cache's own
        stats. ``wire_requests`` counts the infers that actually reached
        the inner client (misses + background revalidations); everything
        else was served client-side."""
        with self._stats_lock:
            counts = dict(self._counts)
        row: Dict[str, Any] = dict(counts)
        row["singleflight_collapsed"] = counts["collapsed"]
        row["wire_requests"] = counts["miss"] + counts["revalidations"]
        served = (counts["hit"] + counts["stale"] + counts["miss"]
                  + counts["collapsed"])
        row["logical_requests"] = served
        row["collapse_ratio"] = (
            round(1.0 - row["wire_requests"] / served, 4) if served else 0.0)
        # caller-level hit rate: followers probe the cache before they
        # collapse, so the cache's internal miss count over-counts — the
        # honest denominator is callers served, not cache probes
        row["hit_rate"] = (
            round((counts["hit"] + counts["stale"]) / served, 4)
            if served else None)
        if self._cache is not None:
            cs = self._cache.stats()
            row["cache"] = cs
            row["bytes_resident"] = cs["bytes_resident"]
            row["entries"] = cs["entries"]
        else:
            row["bytes_resident"] = 0
            row["entries"] = 0
        return row

    # -- span plumbing --------------------------------------------------------
    def _begin_span(self, model: str):
        tel = self._telemetry
        if tel is None:
            return None
        return tel.begin(self._frontend, model)

    def _finish_span(self, span, t0: int, t1: int, t2: Optional[int],
                     outcome: str, error=None) -> None:
        tel = self._telemetry
        if tel is None or span is None:
            return
        span.phase("cache_lookup", t0, t1)
        if t2 is not None:
            span.phase("attempt", t1, t2)
        span.event("cache", outcome=outcome)
        tel.finish(span, error=error)

    # -- shared helpers -------------------------------------------------------
    def _plan_key(self, model_name: str, inputs, kwargs) -> Optional[str]:
        if self._closed:
            return None
        plan = plan_request(inputs, kwargs)
        if plan is None:
            return None
        return _digest(model_name, plan)

    @staticmethod
    def _revalidate_args(inputs, kwargs):
        """Detached copies for a background refresh: the caller may
        re-stage its InferInput objects the moment we return the stale
        view, so the refresh rebuilds inputs from the staged bytes."""
        from ._tensor import InferInput

        fresh = []
        for inp in inputs:
            clone = InferInput(inp.name(), list(inp.shape()), inp.datatype())
            clone._raw_data = bytes(inp._get_binary_data())
            fresh.append(clone)
        kw = dict(kwargs)
        kw.pop("request_id", None)
        return fresh, kw

    # -- generic surface delegation -------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)


class CachingClient(_CachingCore):
    """Synchronous singleflight + response-cache wrapper over any sync
    frontend, pool or batching client. ``infer`` runs the collapse/cache
    engine; ``load_model``/``unload_model`` delegate then invalidate; every
    other method is delegated untouched."""

    _AIO = False

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        if self._cache is not None:
            self._cache.clear()
        self._inner.close()

    def __enter__(self) -> "CachingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- model admin: automatic invalidation ----------------------------------
    def load_model(self, model_name: str, *args, **kwargs):
        """Delegate (a pool broadcasts to every replica), then drop the
        model's cached responses — a (re)loaded model may answer
        differently."""
        try:
            return self._inner.load_model(model_name, *args, **kwargs)
        finally:
            self.invalidate(model=model_name)

    def unload_model(self, model_name: str, *args, **kwargs):
        try:
            return self._inner.unload_model(model_name, *args, **kwargs)
        finally:
            self.invalidate(model=model_name)

    # -- inference -------------------------------------------------------------
    def infer(self, model_name: str, inputs, *args, **kwargs):
        """Collapsing/caching ``infer`` (drop-in: positionals follow the
        frontends' shared prefix). Ineligible requests bypass verbatim; a
        hit returns a zero-copy :class:`CachedInferResult`; concurrent
        identical misses collapse onto one inner request."""
        kwargs = fold_infer_args(args, kwargs)
        inputs = list(inputs) if inputs is not None else inputs
        key = self._plan_key(model_name, inputs, kwargs)
        if key is None:
            self._count(model_name, "bypass")
            return self._inner.infer(model_name, inputs, **kwargs)
        scratch = _flight_recorder.layer_begin(
            self._telemetry, "cache", model_name)
        if scratch is None:
            return self._infer_keyed(key, model_name, inputs, kwargs)
        try:
            result = self._infer_keyed(key, model_name, inputs, kwargs)
        except BaseException as e:
            _flight_recorder.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight_recorder.layer_commit(self._telemetry, scratch)
        return result

    def _infer_keyed(self, key, model_name: str, inputs, kwargs):
        """The lookup/collapse engine behind :meth:`infer` (split out so
        the flight-recorder wrapper above owns one scratch per caller —
        a pure cache hit's timeline is just cache events, no wire leg)."""
        span = self._begin_span(model_name)
        t0 = time.perf_counter_ns()
        cache = self._cache
        if cache is not None:
            state, entry = cache.lookup(key)
            t1 = time.perf_counter_ns()
            if state == "hit":
                self._count(model_name, "hit")
                _flight_recorder.note("cache", "hit")
                self._finish_span(span, t0, t1, None, "hit")
                return CachedInferResult(entry)
            if state == "stale":
                self._count(model_name, "stale")
                _flight_recorder.note("cache", "stale_refresh")
                self._spawn_revalidation(key, model_name, inputs, kwargs)
                self._finish_span(span, t0, t1, None, "stale")
                return CachedInferResult(entry, stale=True)
        else:
            t1 = time.perf_counter_ns()
        if not self._singleflight:
            _flight_recorder.note("cache", "miss")
            return self._miss(key, model_name, inputs, kwargs, span, t0, t1)
        with self._flights_lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
            else:
                flight.followers += 1
                leader = False
        if leader:
            _flight_recorder.note("cache", "leader", key=key[:12])
            return self._lead(flight, key, model_name, inputs, kwargs,
                              span, t0, t1)
        _flight_recorder.note("cache", "follower", key=key[:12])
        with flight.cond:
            while not flight.done:
                flight.cond.wait()
        t2 = time.perf_counter_ns()
        self._count(model_name, "collapsed")
        _flight_recorder.note("cache", "collapsed")
        self._finish_span(span, t0, t1, t2, "collapsed", error=flight.error)
        if flight.error is not None:
            raise flight.error
        return flight.materialize()

    def _miss(self, key, model_name, inputs, kwargs, span, t0, t1):
        """Cache-only miss (singleflight disabled): fetch, insert, serve."""
        error: Optional[BaseException] = None
        result = entry = None
        try:
            result = self._inner.infer(model_name, inputs, **kwargs)
        except BaseException as e:
            error = e
        t2 = time.perf_counter_ns()
        if error is None and self._cache is not None:
            entry = self._cache.insert(key, model_name, result,
                                           tenant=kwargs.get("tenant"))
        self._count(model_name, "miss")
        self._finish_span(span, t0, t1, t2, "miss", error=error)
        if error is not None:
            raise error
        return CachedInferResult(entry) if entry is not None else result

    def _lead(self, flight, key, model_name, inputs, kwargs, span, t0, t1):
        error: Optional[BaseException] = None
        result = entry = None
        try:
            result = self._inner.infer(model_name, inputs, **kwargs)
        except BaseException as e:
            error = e  # errors are NEVER cached; fanned to every follower
        t2 = time.perf_counter_ns()
        if error is None and self._cache is not None:
            try:
                entry = self._cache.insert(key, model_name, result,
                                           tenant=kwargs.get("tenant"))
            except BaseException as e:
                # a broken insert (arena closed mid-flight) must not turn
                # a SERVED answer into an error — serve the wire result
                entry = None
                if not isinstance(e, Exception):
                    error = e
        # retire the flight BEFORE settling: a caller arriving after the
        # settle must start a fresh flight, never join a finished one
        with self._flights_lock:
            self._flights.pop(key, None)
        with flight.cond:
            flight.error = _fan_error(error)
            flight.entry = entry
            flight.result = result if error is None else None
            flight.done = True
            flight.cond.notify_all()
        self._count(model_name, "miss")
        self._finish_span(span, t0, t1, t2, "miss", error=error)
        if error is not None:
            raise error
        return CachedInferResult(entry) if entry is not None else result

    def _spawn_revalidation(self, key, model_name, inputs, kwargs) -> None:
        """ONE background refresh per stale key, deduplicated through the
        singleflight table (a concurrent true miss after full expiry joins
        it as a follower). Failures leave the stale entry in place — it
        ages out at ttl + stale window."""
        with self._flights_lock:
            if key in self._flights:
                return  # refresh (or a miss) already in flight
            flight = _Flight()
            self._flights[key] = flight
        fresh_inputs, kw = self._revalidate_args(inputs, kwargs)

        def run() -> None:
            error: Optional[BaseException] = None
            result = entry = None
            try:
                result = self._inner.infer(model_name, fresh_inputs, **kw)
            except BaseException as e:
                error = e
            if error is None and self._cache is not None:
                try:
                    entry = self._cache.insert(key, model_name, result,
                                           tenant=kwargs.get("tenant"))
                except Exception:
                    entry = None
            with self._flights_lock:
                self._flights.pop(key, None)
            with flight.cond:
                flight.error = _fan_error(error)
                flight.entry = entry
                flight.result = result if error is None else None
                flight.done = True
                flight.cond.notify_all()
            with self._stats_lock:
                self._counts["revalidations"] += 1
                if error is not None:
                    self._counts["revalidate_errors"] += 1

        threading.Thread(target=run, name="client_tpu_cache_revalidate",
                         daemon=True).start()


class AioCachingClient(_CachingCore):
    """Asyncio twin of :class:`CachingClient` over the aio frontends (or
    an ``AioPoolClient``/``AioBatchingClient``). Flights are futures;
    stale revalidation runs as a background task."""

    _AIO = True

    def __init__(self, client, **kwargs):
        super().__init__(client, **kwargs)
        self._revalidate_tasks: set = set()

    # -- lifecycle -----------------------------------------------------------
    async def close(self) -> None:
        self._closed = True
        for task in list(self._revalidate_tasks):
            task.cancel()
        if self._revalidate_tasks:
            await asyncio.gather(*list(self._revalidate_tasks),
                                 return_exceptions=True)
        if self._cache is not None:
            self._cache.clear()
        result = self._inner.close()
        if asyncio.iscoroutine(result):
            await result

    async def __aenter__(self) -> "AioCachingClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- model admin: automatic invalidation ----------------------------------
    async def load_model(self, model_name: str, *args, **kwargs):
        try:
            return await self._inner.load_model(model_name, *args, **kwargs)
        finally:
            self.invalidate(model=model_name)

    async def unload_model(self, model_name: str, *args, **kwargs):
        try:
            return await self._inner.unload_model(model_name, *args, **kwargs)
        finally:
            self.invalidate(model=model_name)

    # -- inference -------------------------------------------------------------
    async def infer(self, model_name: str, inputs, *args, **kwargs):
        """Collapsing/caching async ``infer`` (same eligibility/bypass
        contract as the sync twin)."""
        kwargs = fold_infer_args(args, kwargs)
        inputs = list(inputs) if inputs is not None else inputs
        key = self._plan_key(model_name, inputs, kwargs)
        if key is None:
            self._count(model_name, "bypass")
            return await self._inner.infer(model_name, inputs, **kwargs)
        scratch = _flight_recorder.layer_begin(
            self._telemetry, "cache", model_name)
        if scratch is None:
            return await self._infer_keyed(key, model_name, inputs, kwargs)
        try:
            result = await self._infer_keyed(key, model_name, inputs,
                                             kwargs)
        except BaseException as e:
            _flight_recorder.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight_recorder.layer_commit(self._telemetry, scratch)
        return result

    async def _infer_keyed(self, key, model_name: str, inputs, kwargs):
        """Async twin of the sync ``_infer_keyed`` split."""
        span = self._begin_span(model_name)
        t0 = time.perf_counter_ns()
        cache = self._cache
        if cache is not None:
            state, entry = cache.lookup(key)
            t1 = time.perf_counter_ns()
            if state == "hit":
                self._count(model_name, "hit")
                _flight_recorder.note("cache", "hit")
                self._finish_span(span, t0, t1, None, "hit")
                return CachedInferResult(entry)
            if state == "stale":
                self._count(model_name, "stale")
                _flight_recorder.note("cache", "stale_refresh")
                self._spawn_revalidation(key, model_name, inputs, kwargs)
                self._finish_span(span, t0, t1, None, "stale")
                return CachedInferResult(entry, stale=True)
        else:
            t1 = time.perf_counter_ns()
        if not self._singleflight:
            _flight_recorder.note("cache", "miss")
            return await self._fetch(key, model_name, inputs, kwargs,
                                     span, t0, t1, flight=None)
        loop = asyncio.get_running_loop()
        flight = self._flights.get(key)
        if flight is not None and flight.future is not None:
            # follower: await the leader's published outcome
            _flight_recorder.note("cache", "follower", key=key[:12])
            try:
                outcome = await asyncio.shield(flight.future)
            except BaseException:
                t2 = time.perf_counter_ns()
                self._count(model_name, "collapsed")
                self._finish_span(span, t0, t1, t2, "collapsed",
                                  error=flight.error)
                raise
            t2 = time.perf_counter_ns()
            self._count(model_name, "collapsed")
            _flight_recorder.note("cache", "collapsed")
            self._finish_span(span, t0, t1, t2, "collapsed")
            entry, result = outcome
            return CachedInferResult(entry) if entry is not None else result
        flight = _Flight()
        flight.future = loop.create_future()
        self._flights[key] = flight
        _flight_recorder.note("cache", "leader", key=key[:12])
        return await self._fetch(key, model_name, inputs, kwargs,
                                 span, t0, t1, flight=flight)

    async def _fetch(self, key, model_name, inputs, kwargs, span, t0, t1,
                     flight: Optional[_Flight]):
        error: Optional[BaseException] = None
        result = entry = None
        try:
            result = await self._inner.infer(model_name, inputs, **kwargs)
        except BaseException as e:
            error = e
        t2 = time.perf_counter_ns()
        if error is None and self._cache is not None:
            try:
                entry = self._cache.insert(key, model_name, result,
                                           tenant=kwargs.get("tenant"))
            except Exception:
                entry = None
        if flight is not None:
            self._flights.pop(key, None)
            fan = _fan_error(error)  # never a CancelledError for followers
            flight.error = fan
            if not flight.future.done():
                if fan is not None:
                    flight.future.set_exception(fan)
                    # the leader re-raises its own error below; followers
                    # consume the future's
                    flight.future.exception()
                else:
                    flight.future.set_result((entry, result))
        self._count(model_name, "miss")
        self._finish_span(span, t0, t1, t2, "miss", error=error)
        if error is not None:
            raise error
        return CachedInferResult(entry) if entry is not None else result

    def _spawn_revalidation(self, key, model_name, inputs, kwargs) -> None:
        if key in self._flights:
            return
        flight = _Flight()
        flight.future = asyncio.get_running_loop().create_future()
        self._flights[key] = flight
        fresh_inputs, kw = self._revalidate_args(inputs, kwargs)

        async def run() -> None:
            error: Optional[BaseException] = None
            result = entry = None
            try:
                result = await self._inner.infer(model_name, fresh_inputs,
                                                 **kw)
            except BaseException as e:
                error = e
            if error is None and self._cache is not None:
                try:
                    entry = self._cache.insert(key, model_name, result,
                                           tenant=kwargs.get("tenant"))
                except Exception:
                    entry = None
            self._flights.pop(key, None)
            fan = _fan_error(error)
            flight.error = fan
            if not flight.future.done():
                if fan is not None:
                    flight.future.set_exception(fan)
                    flight.future.exception()  # consumed: may have no waiter
                else:
                    flight.future.set_result((entry, result))
            with self._stats_lock:
                self._counts["revalidations"] += 1
                if error is not None:
                    self._counts["revalidate_errors"] += 1
            if error is not None and not isinstance(error, Exception):
                raise error  # cancellation at close(): honor it

        task = asyncio.ensure_future(run())
        self._revalidate_tasks.add(task)
        task.add_done_callback(self._revalidate_tasks.discard)
