"""ctypes binding to the native C++ client (``native/``).

The image has no pybind11, so the native library exposes a flat C API
(native/src/c_api.cc) bound here with ctypes. Build it first::

    cmake -S native -B native/build -G Ninja && ninja -C native/build

``load()`` returns a NativeClient factory or raises if the library is not
built; ``available()`` probes quietly.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .utils import InferenceServerException, np_to_triton_dtype

# (user, InferResult*, error message or NULL) from the native stream reader
STREAM_CALLBACK = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p
)

# (user, InferResult*) from the native async completion-queue worker;
# failures arrive as a result whose ctpu_result_status is non-NULL
ASYNC_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "native", "build", "libclient_tpu_http.so"),
    "libclient_tpu_http.so",
)

_lib = None


def _bind(lib):
    lib.ctpu_last_error.restype = ctypes.c_char_p
    lib.ctpu_client_create.restype = ctypes.c_void_p
    lib.ctpu_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.ctpu_client_create_ssl.restype = ctypes.c_void_p
    lib.ctpu_client_create_ssl.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.ctpu_client_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_server_live.argtypes = [ctypes.c_void_p]
    lib.ctpu_model_ready.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ctpu_infer_raw.restype = ctypes.c_longlong
    lib.ctpu_infer_raw.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.c_void_p, ctypes.c_ulonglong,
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_ulonglong,
    ]
    lib.ctpu_shm_create.restype = ctypes.c_void_p
    lib.ctpu_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_int]
    lib.ctpu_shm_attach.restype = ctypes.c_void_p
    lib.ctpu_shm_attach.argtypes = [ctypes.c_char_p]
    lib.ctpu_shm_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_shm_raw_handle.restype = ctypes.c_char_p
    lib.ctpu_shm_raw_handle.argtypes = [ctypes.c_void_p]
    lib.ctpu_shm_write.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_ulonglong
    ]
    lib.ctpu_shm_read.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_ulonglong
    ]
    lib.ctpu_register_system_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong,
        ctypes.c_ulonglong,
    ]
    lib.ctpu_register_tpu_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_ulonglong,
    ]
    lib.ctpu_unregister_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
    ]
    # full value-model surface
    lib.ctpu_input_create.restype = ctypes.c_void_p
    lib.ctpu_input_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int,
    ]
    lib.ctpu_input_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_input_append_raw.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_ulonglong
    ]
    lib.ctpu_input_set_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_ulonglong
    ]
    lib.ctpu_output_create.restype = ctypes.c_void_p
    lib.ctpu_output_create.argtypes = [ctypes.c_char_p, ctypes.c_ulonglong]
    lib.ctpu_output_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_output_set_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_ulonglong, ctypes.c_ulonglong
    ]
    lib.ctpu_options_create.restype = ctypes.c_void_p
    lib.ctpu_options_create.argtypes = [ctypes.c_char_p]
    lib.ctpu_options_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_options_set_request_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ctpu_options_set_sequence.argtypes = [
        ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int
    ]
    lib.ctpu_options_set_timeouts.argtypes = [
        ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_ulonglong
    ]
    lib.ctpu_infer.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.ctpu_result_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_result_raw.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_ulonglong),
    ]
    lib.ctpu_result_shape.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int,
    ]
    lib.ctpu_result_shape.restype = ctypes.c_int
    lib.ctpu_result_datatype.restype = ctypes.c_char_p
    lib.ctpu_result_datatype.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ctpu_result_output_name.restype = ctypes.c_char_p
    lib.ctpu_result_output_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ctpu_result_output_names.restype = ctypes.c_char_p
    lib.ctpu_result_output_names.argtypes = [ctypes.c_void_p]
    lib.ctpu_result_status.restype = ctypes.c_char_p
    lib.ctpu_result_status.argtypes = [ctypes.c_void_p]
    lib.ctpu_grpc_async_infer.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ASYNC_CALLBACK, ctypes.c_void_p,
    ]
    lib.ctpu_grpc_set_async_concurrency.argtypes = [
        ctypes.c_void_p, ctypes.c_int
    ]
    lib.ctpu_grpc_set_compression.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    # grpc client (same value-model handles; results use ctpu_result_*)
    lib.ctpu_grpc_client_create.restype = ctypes.c_void_p
    lib.ctpu_grpc_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.ctpu_grpc_client_create_ssl.restype = ctypes.c_void_p
    lib.ctpu_grpc_client_create_ssl.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.ctpu_grpc_client_destroy.argtypes = [ctypes.c_void_p]
    lib.ctpu_grpc_server_live.argtypes = [ctypes.c_void_p]
    lib.ctpu_grpc_model_ready.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ctpu_grpc_infer.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.ctpu_grpc_register_system_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong,
        ctypes.c_ulonglong,
    ]
    lib.ctpu_grpc_register_tpu_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_ulonglong,
    ]
    lib.ctpu_grpc_unregister_shm.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
    ]
    lib.ctpu_grpc_start_stream.argtypes = [
        ctypes.c_void_p, STREAM_CALLBACK, ctypes.c_void_p
    ]
    lib.ctpu_grpc_stream_infer.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
    ]
    lib.ctpu_grpc_stop_stream.argtypes = [ctypes.c_void_p]
    lib.ctpu_set_header.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
    ]
    lib.ctpu_grpc_set_header.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p
    ]
    return lib


def load():
    """Load (and cache) the native library; raises when unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    last = None
    for path in _LIB_PATHS:
        try:
            _lib = _bind(ctypes.CDLL(os.path.abspath(path) if os.sep in path else path))
            return _lib
        except OSError as e:
            last = e
    raise InferenceServerException(
        f"native library not built (run cmake/ninja in native/): {last}"
    )


def available() -> bool:
    try:
        load()
        return True
    except InferenceServerException:
        return False


def _err(lib) -> str:
    return lib.ctpu_last_error().decode("utf-8", errors="replace")


def _decode_result(lib, result_ptr, names=None):
    """{output: np.ndarray} from a ctpu result handle.

    ``names=None`` enumerates every output the server returned. Raises
    InferenceServerException on accessor failures (both the blocking and
    streaming paths share these semantics).
    """
    from .utils import deserialize_bytes_tensor, triton_to_np_dtype

    decoded = {}
    if names is None:
        joined = lib.ctpu_result_output_names(result_ptr)
        names = [n for n in (joined.decode().split("\n") if joined else []) if n]
    for name in names:
        buf = ctypes.c_void_p()
        nbytes = ctypes.c_ulonglong()
        if lib.ctpu_result_raw(
            result_ptr, name.encode(), ctypes.byref(buf), ctypes.byref(nbytes)
        ) != 0:
            raise InferenceServerException(_err(lib))
        dims = (ctypes.c_longlong * 16)()
        ndim = lib.ctpu_result_shape(result_ptr, name.encode(), dims, 16)
        if ndim < 0:
            raise InferenceServerException(_err(lib))
        shape = [dims[i] for i in range(ndim)]
        datatype = lib.ctpu_result_datatype(result_ptr, name.encode()).decode()
        raw = ctypes.string_at(buf, nbytes.value)
        if datatype == "BYTES":
            decoded[name] = deserialize_bytes_tensor(raw).reshape(shape)
            continue
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise InferenceServerException(
                f"output '{name}' has unknown datatype {datatype!r}"
            )
        decoded[name] = np.frombuffer(raw, dtype=np.dtype(np_dtype)).reshape(shape)
    return decoded


def _build_array_input(lib, name, value, keepalive):
    """A ctpu input handle for a host array, BYTES-serialized when needed."""
    from .utils import serialize_byte_tensor

    arr = np.ascontiguousarray(value)
    datatype = np_to_triton_dtype(arr.dtype)
    if datatype is None:
        raise InferenceServerException(
            f"input '{name}' has unsupported dtype {arr.dtype}"
        )
    if datatype == "BYTES":
        serialized = serialize_byte_tensor(arr)
        payload = np.frombuffer(
            serialized.item() if serialized.size else b"", dtype=np.uint8
        )
    else:
        payload = arr
    keepalive.append(payload)
    dims = (ctypes.c_longlong * arr.ndim)(*arr.shape)
    handle = lib.ctpu_input_create(
        name.encode(), datatype.encode(), dims, arr.ndim
    )
    lib.ctpu_input_append_raw(
        handle, payload.ctypes.data_as(ctypes.c_void_p), payload.nbytes
    )
    return handle


class NativeClient:
    """Thin Python handle over the native HTTP client."""

    # C entry points; NativeGrpcClient swaps in the grpc set (results and
    # the value-model handles are shared across both clients)
    _FN = {
        "create": "ctpu_client_create",
        "create_ssl": "ctpu_client_create_ssl",
        "destroy": "ctpu_client_destroy",
        "live": "ctpu_server_live",
        "ready": "ctpu_model_ready",
        "infer": "ctpu_infer",
        "register_system_shm": "ctpu_register_system_shm",
        "register_tpu_shm": "ctpu_register_tpu_shm",
        "unregister_shm": "ctpu_unregister_shm",
        "set_header": "ctpu_set_header",
    }

    def __init__(self, url: str, verbose: bool = False, ssl: bool = False,
                 ssl_options: Optional[dict] = None):
        """``ssl=True`` (or an ``https://`` url) negotiates TLS.
        ``ssl_options`` keys (all optional): ``ca_cert``, ``client_cert``,
        ``client_key`` (PEM file paths), ``verify_peer``, ``verify_host``
        (bools, default True) — HttpSslOptions / grpc SslOptions parity."""
        self._lib = load()
        # eager, not lazy-on-first-use: concurrent async_infer calls racing
        # a lazy init could each install a fresh dict and orphan the other's
        # live callback trampoline (native callback into freed memory)
        self._async_pending = {}  # id -> trampoline (CFUNCTYPE unhashable)
        if ssl or url.startswith("https://") or ssl_options:
            if not url.startswith("https://"):
                # ssl=True must never downgrade to cleartext: the HTTP C
                # path's SSL options only configure verification, the scheme
                # is what selects TLS
                url = "https://" + url.removeprefix("http://")
            opts = ssl_options or {}
            self._handle = getattr(self._lib, self._FN["create_ssl"])(
                url.encode(), int(verbose),
                (opts.get("ca_cert") or "").encode() or None,
                (opts.get("client_cert") or "").encode() or None,
                (opts.get("client_key") or "").encode() or None,
                int(opts.get("verify_peer", True)),
                int(opts.get("verify_host", True)),
            )
        else:
            self._handle = getattr(self._lib, self._FN["create"])(
                url.encode(), int(verbose)
            )
        if not self._handle:
            raise InferenceServerException(f"native client create failed: {_err(self._lib)}")

    def close(self) -> None:
        if self._handle:
            getattr(self._lib, self._FN["destroy"])(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def set_header(self, key: str, value: str) -> None:
        """Attach ``key: value`` to every request (auth tokens etc. — the
        native twin of the Python plugin hook)."""
        getattr(self._lib, self._FN["set_header"])(
            self._handle, key.encode(), value.encode()
        )

    def is_server_live(self) -> bool:
        rc = getattr(self._lib, self._FN["live"])(self._handle)
        if rc < 0:
            raise InferenceServerException(_err(self._lib))
        return bool(rc)

    def is_model_ready(self, model_name: str) -> bool:
        rc = getattr(self._lib, self._FN["ready"])(self._handle, model_name.encode())
        if rc < 0:
            raise InferenceServerException(_err(self._lib))
        return bool(rc)

    def infer_raw(
        self,
        model_name: str,
        input_name: str,
        tensor: np.ndarray,
        output_name: str,
        output_dtype=None,
        output_capacity: Optional[int] = None,
    ) -> np.ndarray:
        """Single-tensor inference through the native data path."""
        datatype = np_to_triton_dtype(tensor.dtype)
        tensor = np.ascontiguousarray(tensor)
        shape = (ctypes.c_longlong * tensor.ndim)(*tensor.shape)
        capacity = output_capacity or max(tensor.nbytes * 2, 1 << 16)
        out = np.empty(capacity, dtype=np.uint8)
        nbytes = self._lib.ctpu_infer_raw(
            self._handle, model_name.encode(), input_name.encode(),
            datatype.encode(), shape, tensor.ndim,
            tensor.ctypes.data_as(ctypes.c_void_p), tensor.nbytes,
            output_name.encode(), out.ctypes.data_as(ctypes.c_void_p), capacity,
        )
        if nbytes < 0:
            raise InferenceServerException(_err(self._lib))
        np_dtype = np.dtype(output_dtype or tensor.dtype)
        return out[:nbytes].view(np_dtype)

    def infer(self, model_name: str, inputs, outputs=None, request_id: str = "",
              sequence=None, client_timeout_s: float = 0.0):
        """Full value-model inference through the native data path.

        ``inputs``: list of (name, np.ndarray) and/or
        (name, ("shm", region, byte_size, offset, datatype, shape)).
        ``outputs``: optional list of names or (name, ("shm", ...)) tuples.
        Returns {output_name: np.ndarray} for non-shm outputs.
        """
        from .utils import triton_to_np_dtype

        lib = self._lib
        in_handles = []
        out_handles = []
        keepalive = []
        options = lib.ctpu_options_create(model_name.encode())
        try:
            if request_id:
                lib.ctpu_options_set_request_id(options, request_id.encode())
            if sequence is not None:
                seq_id, start, end = sequence
                lib.ctpu_options_set_sequence(options, seq_id, int(start), int(end))
            if client_timeout_s:
                if client_timeout_s < 0:
                    raise InferenceServerException(
                        "client_timeout_s must be non-negative"
                    )
                lib.ctpu_options_set_timeouts(
                    options, max(1, int(round(client_timeout_s * 1e6))), 0
                )
            out_names = []
            for name, value in inputs:
                if isinstance(value, tuple) and value and value[0] == "shm":
                    _, region, nbytes, offset, datatype, shape = value
                    dims = (ctypes.c_longlong * len(shape))(*shape)
                    handle = lib.ctpu_input_create(
                        name.encode(), datatype.encode(), dims, len(shape)
                    )
                    lib.ctpu_input_set_shm(handle, region.encode(), nbytes, offset)
                else:
                    handle = _build_array_input(lib, name, value, keepalive)
                if not handle:
                    raise InferenceServerException(_err(lib))
                in_handles.append(handle)
            for spec in outputs or []:
                if isinstance(spec, tuple):
                    name, shm_spec = spec
                    handle = lib.ctpu_output_create(name.encode(), 0)
                    _, region, nbytes, offset = shm_spec[:4]
                    lib.ctpu_output_set_shm(handle, region.encode(), nbytes, offset)
                else:
                    name = spec
                    handle = lib.ctpu_output_create(name.encode(), 0)
                    out_names.append(name)
                out_handles.append(handle)

            ins = (ctypes.c_void_p * len(in_handles))(*in_handles)
            outs = (ctypes.c_void_p * len(out_handles))(*out_handles)
            result_ptr = ctypes.c_void_p()
            rc = getattr(lib, self._FN["infer"])(
                self._handle, options, ins, len(in_handles), outs,
                len(out_handles), ctypes.byref(result_ptr),
            )
            if rc != 0:
                if result_ptr:
                    lib.ctpu_result_destroy(result_ptr)
                raise InferenceServerException(_err(lib))
            try:
                # shm-placed outputs live in regions; with explicit outputs
                # only the non-shm names decode
                return _decode_result(
                    lib, result_ptr, None if outputs is None else out_names
                )
            finally:
                lib.ctpu_result_destroy(result_ptr)
        finally:
            for handle in in_handles:
                lib.ctpu_input_destroy(handle)
            for handle in out_handles:
                lib.ctpu_output_destroy(handle)
            lib.ctpu_options_destroy(options)

    def register_system_shared_memory(
        self, name: str, key: str, byte_size: int, offset: int = 0
    ) -> None:
        if getattr(self._lib, self._FN["register_system_shm"])(
            self._handle, name.encode(), key.encode(), byte_size, offset
        ) != 0:
            raise InferenceServerException(_err(self._lib))

    def register_tpu_shared_memory(
        self, name: str, raw_handle: str, device_id: int, byte_size: int
    ) -> None:
        if getattr(self._lib, self._FN["register_tpu_shm"])(
            self._handle, name.encode(), raw_handle.encode(), device_id, byte_size
        ) != 0:
            raise InferenceServerException(_err(self._lib))

    def unregister_shared_memory(self, family: str = "tpu", name: str = "") -> None:
        if getattr(self._lib, self._FN["unregister_shm"])(
            self._handle, family.encode(), name.encode()
        ) != 0:
            raise InferenceServerException(_err(self._lib))


class NativeGrpcClient(NativeClient):
    """Thin Python handle over the native GRPC client (h2c transport).

    Same value-model ``infer`` surface as :class:`NativeClient`; the wire
    underneath is hand-framed gRPC over the library's own HTTP/2
    (native/src/grpc_client.cc, native/src/h2.cc). Bi-di streaming mirrors
    the Python grpc client: ``start_stream(callback)`` /
    ``stream_infer(...)`` / ``stop_stream()`` with ``callback(outputs,
    error)`` fired from the native reader thread (outputs is a
    ``{name: np.ndarray}`` dict, or None with an error string).
    """

    _FN = {
        "create": "ctpu_grpc_client_create",
        "create_ssl": "ctpu_grpc_client_create_ssl",
        "destroy": "ctpu_grpc_client_destroy",
        "live": "ctpu_grpc_server_live",
        "ready": "ctpu_grpc_model_ready",
        "infer": "ctpu_grpc_infer",
        "register_system_shm": "ctpu_grpc_register_system_shm",
        "register_tpu_shm": "ctpu_grpc_register_tpu_shm",
        "unregister_shm": "ctpu_grpc_unregister_shm",
        "set_header": "ctpu_grpc_set_header",
    }

    # -- async (completion-queue worker) -----------------------------------
    def async_infer(self, model_name: str, inputs, callback,
                    client_timeout_s: float = 0.0) -> None:
        """Queue one inference on the native async worker; returns at once.

        ``callback(outputs, error)`` fires from the worker thread when the
        RPC completes — ``outputs`` is ``{name: np.ndarray}``, or ``None``
        with an error string. The worker keeps many RPCs in flight on ONE
        multiplexed h2 connection (completion-queue model; reference
        grpc_client.cc:1583-1626), so N queued requests against a slow model
        overlap rather than serialize. ``inputs``: list of
        (name, np.ndarray).
        """
        lib = self._lib
        pending = self._async_pending
        holder = []

        def on_complete(_user, result_ptr):
            try:
                if not result_ptr:
                    callback(None, "async infer returned no result")
                    return
                status = lib.ctpu_result_status(result_ptr)
                if status is not None:
                    callback(None, status.decode("utf-8", "replace"))
                    return
                try:
                    decoded = _decode_result(lib, result_ptr)
                except InferenceServerException as e:
                    callback(None, str(e))
                    return
                callback(decoded, None)
            finally:
                if result_ptr:
                    lib.ctpu_result_destroy(result_ptr)
                pending.pop(id(holder[0]), None)

        trampoline = ASYNC_CALLBACK(on_complete)
        holder.append(trampoline)
        in_handles = []
        keepalive = []
        options = lib.ctpu_options_create(model_name.encode())
        try:
            if client_timeout_s:
                lib.ctpu_options_set_timeouts(
                    options, max(1, int(round(client_timeout_s * 1e6))), 0
                )
            for name, value in inputs:
                handle = _build_array_input(lib, name, value, keepalive)
                if not handle:
                    raise InferenceServerException(_err(lib))
                in_handles.append(handle)
            ins = (ctypes.c_void_p * len(in_handles))(*in_handles)
            # the native side serializes the request before returning, so
            # the input handles and numpy buffers may be freed on return;
            # only the callback trampoline must outlive the RPC
            pending[id(trampoline)] = trampoline
            rc = lib.ctpu_grpc_async_infer(
                self._handle, options, ins, len(in_handles), None, 0,
                trampoline, None,
            )
            if rc != 0:
                pending.pop(id(trampoline), None)
                raise InferenceServerException(_err(lib))
        finally:
            for handle in in_handles:
                lib.ctpu_input_destroy(handle)
            lib.ctpu_options_destroy(options)

    def set_compression(self, algorithm: Optional[str]) -> None:
        """Default message compression for infer RPCs and streams:
        ``"gzip"``, ``"deflate"``, or ``None`` (off). The twin of the
        Python clients' ``compression_algorithm`` argument."""
        self._lib.ctpu_grpc_set_compression(
            self._handle, (algorithm or "").encode()
        )

    def set_async_concurrency(self, n: int) -> None:
        """In-flight window for :meth:`async_infer` (default 16): how many
        RPCs the native worker keeps open concurrently on its multiplexed
        connection, clamped to the server's advertised
        SETTINGS_MAX_CONCURRENT_STREAMS."""
        self._lib.ctpu_grpc_set_async_concurrency(self._handle, int(n))

    # -- bi-di streaming ---------------------------------------------------
    def start_stream(self, callback) -> None:
        """Open the ModelStreamInfer stream; ``callback(outputs, error)``
        per response from the native reader thread."""
        lib = self._lib
        if getattr(self, "_stream_cb", None) is not None:
            # never clobber a live trampoline: the active stream's reader
            # still holds its function pointer
            raise InferenceServerException(
                "cannot start a stream: one is already active; stop it first"
            )

        def on_response(_user, result_ptr, error_message):
            try:
                if error_message is not None:
                    callback(None, error_message.decode("utf-8", "replace"))
                    return
                try:
                    decoded = _decode_result(lib, result_ptr) if result_ptr else {}
                except InferenceServerException as e:
                    callback(None, str(e))
                    return
                callback(decoded, None)
            finally:
                if result_ptr:
                    lib.ctpu_result_destroy(result_ptr)

        # keep the CFUNCTYPE alive for the stream's lifetime
        trampoline = STREAM_CALLBACK(on_response)
        if lib.ctpu_grpc_start_stream(self._handle, trampoline, None) != 0:
            raise InferenceServerException(_err(lib))
        self._stream_cb = trampoline

    def stream_infer(self, model_name: str, inputs, sequence=None) -> None:
        """Send one request on the open stream. ``inputs``: list of
        (name, np.ndarray)."""
        lib = self._lib
        in_handles = []
        keepalive = []
        options = lib.ctpu_options_create(model_name.encode())
        try:
            if sequence is not None:
                seq_id, start, end = sequence
                lib.ctpu_options_set_sequence(options, seq_id, int(start), int(end))
            for name, value in inputs:
                in_handles.append(
                    _build_array_input(lib, name, value, keepalive)
                )
            ins = (ctypes.c_void_p * len(in_handles))(*in_handles)
            # the native client serializes the request before returning, so
            # the input handles (and numpy buffers) may be freed right after
            if lib.ctpu_grpc_stream_infer(
                self._handle, options, ins, len(in_handles), None, 0
            ) != 0:
                raise InferenceServerException(_err(lib))
        finally:
            for handle in in_handles:
                lib.ctpu_input_destroy(handle)
            lib.ctpu_options_destroy(options)

    def stop_stream(self) -> None:
        if getattr(self, "_stream_cb", None) is None:
            return
        rc = self._lib.ctpu_grpc_stop_stream(self._handle)
        self._stream_cb = None
        if rc != 0:
            raise InferenceServerException(_err(self._lib))

    def close(self) -> None:
        if self._handle and getattr(self, "_stream_cb", None) is not None:
            try:
                self.stop_stream()
            except InferenceServerException:
                pass
        super().close()

    def infer_raw(self, model_name, input_name, tensor, output_name,
                  output_dtype=None, output_capacity=None):
        """Single-tensor convenience over the full value-model path.

        Matches the base class contract: a flat 1-D array of the output
        bytes reinterpreted as ``output_dtype`` (default: the input dtype),
        bounded by ``output_capacity`` when given.
        """
        result = self.infer(
            model_name, [(input_name, tensor)], outputs=[output_name]
        )
        if output_name not in result:
            raise InferenceServerException(
                f"output '{output_name}' missing from response"
            )
        raw = np.ascontiguousarray(result[output_name]).tobytes()
        if output_capacity is not None and len(raw) > output_capacity:
            raise InferenceServerException("output buffer too small")
        np_dtype = np.dtype(output_dtype or tensor.dtype)
        return np.frombuffer(raw, dtype=np_dtype)


class NativeTpuShmRegion:
    """Native tpu shared-memory region, handle-compatible with the Python module."""

    def __init__(self, name: str, byte_size: int, device_id: int = 0, _handle=None):
        self._lib = load()
        self.byte_size = byte_size
        if _handle is not None:
            self._handle = _handle
        else:
            self._handle = self._lib.ctpu_shm_create(name.encode(), byte_size, device_id)
        if not self._handle:
            raise InferenceServerException(f"shm create failed: {_err(self._lib)}")

    @classmethod
    def attach(cls, raw_handle: str, byte_size: int) -> "NativeTpuShmRegion":
        lib = load()
        handle = lib.ctpu_shm_attach(raw_handle.encode())
        if not handle:
            raise InferenceServerException(f"shm attach failed: {_err(lib)}")
        return cls("", byte_size, _handle=handle)

    def raw_handle(self) -> str:
        return self._lib.ctpu_shm_raw_handle(self._handle).decode()

    def write(self, arr: np.ndarray, offset: int = 0) -> None:
        arr = np.ascontiguousarray(arr)
        if self._lib.ctpu_shm_write(
            self._handle, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, offset
        ) != 0:
            raise InferenceServerException(_err(self._lib))

    def read(self, dtype, shape, offset: int = 0) -> np.ndarray:
        out = np.empty(shape, dtype=dtype)
        if self._lib.ctpu_shm_read(
            self._handle, out.ctypes.data_as(ctypes.c_void_p), out.nbytes, offset
        ) != 0:
            raise InferenceServerException(_err(self._lib))
        return out

    def destroy(self) -> None:
        if self._handle:
            self._lib.ctpu_shm_destroy(self._handle)
            self._handle = None
