"""Perf harness: the framework's perf_analyzer equivalent.

The reference moved perf_analyzer out of repo (src/c++/perf_analyzer/README.md
is a redirect), so this is a from-scratch load generator with the same core
controls: concurrency sweep, infer/sec, p50/p90/p99 latency, and a
``--shared-memory={none,system,tpu}`` data-plane switch (the reference's
``none/system/cuda``).

Usage::

    python -m client_tpu.perf -m simple -u 127.0.0.1:8000 -i http \
        --concurrency-range 1:4 --shared-memory tpu --measurement-requests 200

Inputs are generated from the model's metadata (random data per datatype;
dynamic dims default to 1, override with ``--shape NAME:d1,d2``).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .utils import sorted_percentile as _percentile


def _random_tensor(datatype: str, shape: List[int], rng) -> np.ndarray:
    from .utils import triton_to_np_dtype

    if datatype == "BYTES":
        flat = int(np.prod(shape))
        return np.array(
            [str(rng.integers(0, 100)).encode() for _ in range(flat)], dtype=np.object_
        ).reshape(shape)
    np_dtype = np.dtype(triton_to_np_dtype(datatype))
    if np_dtype.kind in "iu":
        return rng.integers(0, 100, size=shape).astype(np_dtype)
    return rng.standard_normal(shape).astype(np_dtype)


def _latency_ms_row(lat_sorted: List[float]) -> Dict[str, float]:
    """The avg/p50/p90/p99 row every result dict carries, from an
    ALREADY-SORTED list of latencies in seconds."""
    n = len(lat_sorted)
    return {
        "avg": round(1000 * sum(lat_sorted) / n, 3) if n else 0.0,
        "p50": round(1000 * _percentile(lat_sorted, 0.50), 3),
        "p90": round(1000 * _percentile(lat_sorted, 0.90), 3),
        "p99": round(1000 * _percentile(lat_sorted, 0.99), 3),
    }


def _lag_ms_row(lag_sorted: List[float]) -> Dict[str, float]:
    """The schedule-slip row shared by the open-loop and trace-replay
    results, from an ALREADY-SORTED list of lags in seconds."""
    return {
        "p50": round(1000 * _percentile(lag_sorted, 0.50), 3),
        "p99": round(1000 * _percentile(lag_sorted, 0.99), 3),
        "max": round(1000 * lag_sorted[-1], 3) if lag_sorted else 0.0,
    }


def _parse_chaos_fault(spec: str):
    """``--chaos`` spec -> a testing.chaos.Fault (None = clean proxy)."""
    from .testing.chaos import Fault

    if spec in ("", "none"):
        return None
    kind, _, arg = spec.partition(":")
    if kind == "latency":
        return Fault("latency", latency_s=float(arg or 0.001))
    if kind == "reset":
        return Fault("reset", after_bytes=int(arg or 0))
    if kind == "stall":
        return Fault("stall", after_bytes=int(arg or 0))
    if kind == "flap":
        return Fault("flap", every=int(arg or 2))
    if kind == "blackhole":
        return Fault("blackhole")
    raise ValueError(
        f"unknown --chaos spec {spec!r} "
        "(none|latency:S|reset:N|stall:N|flap:K|blackhole)")


class PerfRunner:
    """Drives one (concurrency, shared-memory-mode) measurement."""

    def __init__(
        self,
        url: str,
        protocol: str = "http",
        model_name: str = "simple",
        shared_memory: str = "none",
        shape_overrides: Optional[Dict[str, List[int]]] = None,
        batch_size: int = 0,
        seed: int = 0,
        retries: int = 0,
        chaos: Optional[str] = None,
        endpoints: Optional[List[str]] = None,
        hedge: bool = False,
        hedge_delay_s: Optional[float] = None,
        observe: bool = False,
        observe_sample: str = "always",
        generate_stream: bool = False,
        stream_prompt_tokens: int = 32,
        stream_output_tokens: int = 16,
        coalesce: bool = False,
        batch_window_us: Optional[float] = None,
        batch_max: int = 32,
        routing: Optional[str] = None,
        admission: bool = False,
        admission_mode: str = "aimd",
        admission_target_ms: Optional[float] = None,
        admission_max_queue_wait_s: float = 0.05,
        tenancy: Optional[str] = None,
        endpoint_limits: bool = False,
        shard_layout=None,
        cache: bool = False,
        cache_ttl_s: float = 30.0,
        singleflight: bool = False,
        affinity_key: Optional[str] = None,
        flight: bool = False,
        cells: Optional[Dict[str, List[str]]] = None,
        home_cell: Optional[str] = None,
        shadow_cell: Optional[str] = None,
        shadow_ratio: float = 0.05,
        canary_cell: Optional[str] = None,
        canary_weight: float = 0.1,
        canary_slo: Optional[str] = None,
        canary_min_events: int = 20,
        cells_deadline_s: Optional[float] = 5.0,
        cells_attempt_timeout_s: Optional[float] = None,
        roles=None,
        pipeline=None,
        validate: bool = False,
        watch: bool = False,
    ):
        """``retries``: arm a resilience policy (RetryPolicy with
        ``retries``+1 attempts) on every measurement client — benchmarks
        the pay-for-what-you-use overhead of the policy path. ``chaos``:
        route measurement traffic through an in-process fault-injection
        proxy (``client_tpu.testing.chaos``); spec is ``none`` (proxy
        only), ``latency:S``, ``reset:N``, ``stall:N``, ``flap:K`` or
        ``blackhole``. Control/probe traffic always goes direct.
        ``endpoints``: N replica urls — measurement clients become
        health-aware ``PoolClient``s (``client_tpu.pool``) over them;
        ``url`` stays the control-plane address. ``hedge`` arms hedged
        requests on the pool (``hedge_delay_s`` pins the hedge delay;
        default is the rolling p95). ``observe``: arm a fresh
        ``observe.Telemetry`` (sample=always) on every measurement run and
        append a client-phase p50/p99 breakdown
        (serialize/send/ttfb/recv/deserialize) to each result row.
        ``coalesce``: wrap every measurement client in the micro-batching
        dispatcher (``client_tpu.batch.BatchingClient``) so concurrent
        workers share coalesced wire requests; ``batch_window_us`` pins
        the coalescing window (default: adaptive) and ``batch_max``
        bounds the stacked batch dimension. Each result row then carries
        a ``client_batch`` block with achieved batch-size p50/p99."""
        self.url = url
        self._direct_url = url
        self.protocol = protocol
        self.model_name = model_name
        self.shared_memory = shared_memory
        self.shape_overrides = shape_overrides or {}
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        self.retries = max(0, retries)
        self.endpoints = list(endpoints) if endpoints else None
        self.hedge = hedge
        self.hedge_delay_s = hedge_delay_s
        self.observe = observe
        self.observe_sample = observe_sample
        # --flight: attach a flight recorder to every measurement run's
        # telemetry and append a client_flight row (events/request,
        # retained fraction, commit cost) to each result
        self.flight = flight
        self.generate_stream = generate_stream
        self.coalesce = coalesce
        self.batch_window_us = batch_window_us
        self.batch_max = batch_max
        self.routing = routing
        self.admission = admission
        self.admission_mode = admission_mode
        self.admission_target_ms = admission_target_ms
        self.admission_max_queue_wait_s = admission_max_queue_wait_s
        # multi-tenant QoS (client_tpu.tenancy): a parse_tenancy_spec
        # string arming per-tenant weighted-fair queueing + quotas on the
        # pool's admission controller; trace replay threads each record's
        # ``tenant`` (format v4) through the client stack
        self.tenancy = tenancy
        self.endpoint_limits = endpoint_limits
        # hot-key serving layer (client_tpu.cache): wrap measurement
        # clients in the singleflight/response-cache wrapper; replay
        # threads each record's content_key into per-key payloads so the
        # layer has real hot keys to collapse
        self.cache = cache
        self.cache_ttl_s = cache_ttl_s
        self.singleflight = singleflight
        self.affinity_key = affinity_key
        # multi-cell federation (client_tpu.federation): measurement
        # clients become FederatedClients over named cells, each cell its
        # own PoolClient (routing/admission/endpoint-limit flags apply
        # PER CELL); shadow/canary arm the rollout primitives and every
        # result row gains a ``client_federation`` block
        if isinstance(cells, str):
            from .federation import parse_cells_spec

            cells = parse_cells_spec(cells)
        self.cells = cells
        self.home_cell = home_cell
        self.shadow_cell = shadow_cell
        self.shadow_ratio = shadow_ratio
        self.canary_cell = canary_cell
        self.canary_weight = canary_weight
        self.canary_slo = canary_slo
        self.canary_min_events = canary_min_events
        self.cells_deadline_s = cells_deadline_s
        self.cells_attempt_timeout_s = cells_attempt_timeout_s
        # disaggregated prefill/decode (client_tpu.disagg): a
        # {role: [urls]} dict or its spec string
        # ("prefill=u1+u2;decode=u3") labeling replay endpoints with
        # serving roles; trace replay drives ``prefill_decode`` records
        # (format v5) through a DisaggClient over them
        if isinstance(roles, str):
            from .federation import parse_cells_spec

            roles = parse_cells_spec(roles)
        self.roles = roles
        # client-orchestrated model-DAG replay (client_tpu.pipeline): a
        # Pipeline or its spec string ("chain" or an inline graph spec);
        # trace replay drives ``pipeline`` records (format v6) through a
        # PipelineClient over the replay endpoints
        if isinstance(pipeline, str):
            from .pipeline import resolve_pipeline

            pipeline = resolve_pipeline(pipeline)
        self.pipeline = pipeline
        self.validate = validate
        # --watch: arm a continuous Watchtower (client_tpu.watch) on each
        # measurement run's telemetry and append a client_watch block
        # (alerts fired/resolved by kind, tick overhead, changepoint
        # trips) to every result row
        self.watch = watch
        self._watchtower = None
        self.seed = seed
        # sharded scatter-gather (client_tpu.shard): a ShardLayout or a
        # spec string ("IN=0->OUT=0") resolved over --endpoints in order;
        # measurement clients become ShardedClients over the pool
        if isinstance(shard_layout, str):
            from .shard import ShardLayout

            if not endpoints:
                raise ValueError(
                    "--shard-layout requires --endpoints: each shard is "
                    "pinned to one replica url")
            shard_layout = ShardLayout.parse(shard_layout, list(endpoints))
        self.shard_layout = shard_layout
        # orca_weighted routing needs the frontends to OPT IN to the ORCA
        # response header; every Telemetry this runner builds carries it
        self._orca_format = "json" if routing == "orca_weighted" else None
        self._telemetry = None  # fresh per measurement run (see run())
        # one ShmArena per runner (created lazily on the first shm-mode
        # worker setup): slabs and cached registrations survive across
        # workers AND runs, so a sweep's steady state pays zero region
        # create/destroy and zero registration RPCs per request
        self._arena = None
        self._arena_lock = threading.Lock()
        self._arena_before = None
        self._proxy = None
        if generate_stream:
            # one streamed generation per "request": each worker iteration
            # drives a full SSE session; latency_ms becomes session e2e
            # and --observe adds the ttft/itl breakdown (client_stream_ms)
            if protocol != "http":
                raise ValueError(
                    "--generate-stream requires the http protocol (the "
                    "generate extension is an HTTP SSE surface)")
            if shared_memory != "none":
                raise ValueError(
                    "--generate-stream requires --shared-memory none")
            prompt_rng = np.random.default_rng(seed)
            self._stream_payload = {
                "TOKENS": prompt_rng.integers(
                    0, 256, size=(1, max(1, stream_prompt_tokens)),
                    dtype=np.int32).tolist(),
                "MAX_TOKENS": max(1, stream_output_tokens),
            }
        if protocol in ("native", "native-grpc") and shared_memory == "system":
            raise ValueError("native protocols support --shared-memory none|tpu")
        if protocol == "native-grpc-async" and shared_memory != "none":
            raise ValueError("native-grpc-async supports --shared-memory none")
        if self.retries and protocol.startswith("native"):
            raise ValueError(
                "--retries requires a python frontend (http|grpc): the native "
                "clients have no resilience hook")
        if self.observe and protocol.startswith("native"):
            raise ValueError(
                "--observe requires a python frontend (http|grpc): the "
                "native clients have no telemetry hook")
        if self.endpoints and protocol not in ("http", "grpc"):
            raise ValueError(
                "--endpoints requires a python frontend (http|grpc): the "
                "pool wraps the python clients")
        if self.endpoints and shared_memory != "none":
            raise ValueError(
                "--endpoints requires --shared-memory none: regions would "
                "register on one replica while infers route to all of them")
        if self.endpoints and chaos is not None:
            raise ValueError(
                "--chaos proxies a single url; with --endpoints, stand up "
                "one ChaosProxy per replica instead (tools/bench_pool.py)")
        if self.hedge and not self.endpoints:
            raise ValueError("--hedge requires --endpoints")
        if (routing or admission or endpoint_limits) and not (
                self.endpoints or cells):
            raise ValueError(
                "--routing/--admission/--endpoint-limits require "
                "--endpoints (pool-level policies) or --cells (applied "
                "to every cell's pool)")
        if self.shard_layout is not None:
            if not self.endpoints:
                raise ValueError(
                    "--shard-layout requires --endpoints: each shard is "
                    "pinned to one replica url")
            if self.hedge or self.coalesce:
                raise ValueError(
                    "--shard-layout rejects --hedge and --coalesce: "
                    "sharded requests never hedge (a hedge would race a "
                    "replica holding a different partition) and never "
                    "coalesce (see docs/sharding.md)")
            if generate_stream:
                raise ValueError(
                    "--shard-layout applies to unary/sharded infers, not "
                    "--generate-stream")
        if self.coalesce:
            if protocol not in ("http", "grpc"):
                raise ValueError(
                    "--coalesce requires a python frontend (http|grpc): the "
                    "batching dispatcher wraps the python clients")
            if shared_memory != "none":
                raise ValueError(
                    "--coalesce requires --shared-memory none: shm-bound "
                    "tensors never coalesce")
            if generate_stream:
                raise ValueError(
                    "--coalesce applies to unary infers, not "
                    "--generate-stream")
        if self.cache or self.singleflight:
            if protocol not in ("http", "grpc"):
                raise ValueError(
                    "--cache/--singleflight require a python frontend "
                    "(http|grpc): the caching wrapper wraps the python "
                    "clients")
            if shared_memory != "none":
                raise ValueError(
                    "--cache/--singleflight require --shared-memory none: "
                    "shm-bound tensors never cache or collapse")
            if generate_stream:
                raise ValueError(
                    "--cache/--singleflight apply to unary infers, not "
                    "--generate-stream")
            if self.shard_layout is not None:
                raise ValueError(
                    "--cache/--singleflight reject --shard-layout: a "
                    "sharded logical request has per-replica partitions, "
                    "not one cacheable answer")
        if self.affinity_key is not None and self.routing != "affinity":
            raise ValueError(
                "--affinity-key requires --routing affinity (and "
                "--endpoints): the key only steers the affinity policy")
        if self.tenancy is not None and not self.admission:
            raise ValueError(
                "--tenancy requires --admission: tenant quotas and "
                "weighted-fair queueing live in the admission controller")
        if self.cells:
            if protocol not in ("http", "grpc"):
                raise ValueError(
                    "--cells requires a python frontend (http|grpc): the "
                    "federation wraps per-cell PoolClients")
            if self.endpoints:
                raise ValueError(
                    "--cells and --endpoints are mutually exclusive: each "
                    "cell already declares its own replica urls")
            if shared_memory != "none":
                raise ValueError(
                    "--cells requires --shared-memory none (same rule as "
                    "--endpoints)")
            if chaos is not None:
                raise ValueError(
                    "--chaos proxies a single url; with --cells, stand up "
                    "one ChaosProxy per replica and group them per cell "
                    "(testing.ChaosCell / tools/bench_federation.py)")
            if self.hedge or self.coalesce or self.cache or self.singleflight:
                raise ValueError(
                    "--cells rejects --hedge/--coalesce/--cache/"
                    "--singleflight: compose them per cell (each cell IS "
                    "a PoolClient) rather than across cells")
            if self.shard_layout is not None:
                raise ValueError(
                    "--cells rejects --shard-layout: a shard layout pins "
                    "replicas of ONE pool")
            for name in (self.home_cell, self.shadow_cell,
                         self.canary_cell):
                if name is not None and name not in self.cells:
                    raise ValueError(
                        f"cell {name!r} is not declared in --cells")
        elif (self.home_cell or self.shadow_cell or self.canary_cell):
            raise ValueError(
                "--home-cell/--shadow-cell/--canary-cell require --cells")
        if chaos is not None:
            from .testing.chaos import ChaosProxy

            fault = _parse_chaos_fault(chaos)  # validate BEFORE binding
            host, _, port = url.partition(":")
            self._proxy = ChaosProxy(host, int(port)).start()
            self._proxy.fault = fault
            self.url = self._proxy.url
        try:
            self._client_mod = self._import_client_mod()
            self._metadata = self._fetch_metadata()
            self._tensors = self._generate_tensors()
            # shm modes place outputs in regions too; probe once over the
            # wire to learn output byte sizes (perf_analyzer's
            # output-shared-memory sizing, derived instead of flag-supplied)
            self._output_sizes = (
                self._probe_output_sizes() if shared_memory != "none" else {})
        except Exception:
            self.close()  # don't leak the proxy listener on init failure
            raise

    def close(self) -> None:
        if self._proxy is not None:
            self._proxy.stop()
            self._proxy = None

    def _import_client_mod(self):
        if self.protocol in ("http", "native"):
            import client_tpu.http as mod
        else:  # grpc and native-grpc* share the grpc value model
            import client_tpu.grpc as mod
        return mod

    def _make_client(self, concurrency: int = 1):
        if self.protocol == "native":
            from client_tpu.native import NativeClient

            return NativeClient(self.url)
        if self.protocol in ("native-grpc", "native-grpc-async"):
            from client_tpu.native import NativeGrpcClient

            return NativeGrpcClient(self.url)
        if self.cells:
            return self._make_federated_client(concurrency)
        if self.endpoints:
            pool = self._make_pool_client(concurrency)
            if self.shard_layout is not None:
                from .shard import ShardedClient

                # one ShardedClient per measurement client: logical infers
                # scatter across the replica-pinned endpoints (the pool
                # carries the arena so shards stage zero-copy). Every
                # logical request holds n_shards fan-out threads, so the
                # executor must admit the full worker concurrency or the
                # harness would measure its own thread pool
                return ShardedClient(
                    pool, self.shard_layout,
                    executor_workers=max(
                        8, 2 * concurrency * self.shard_layout.n_shards))
            return self._wrap_caching(self._wrap_coalescing(pool))
        if self.protocol == "http":
            client = self._client_mod.InferenceServerClient(
                self.url, concurrency=concurrency)
        else:
            client = self._client_mod.InferenceServerClient(self.url)
        if self.retries:
            from .resilience import ResiliencePolicy, RetryPolicy

            client.configure_resilience(ResiliencePolicy(
                retry=RetryPolicy(max_attempts=self.retries + 1)))
        if self._telemetry is not None:
            client.configure_telemetry(self._telemetry)
        return self._wrap_caching(self._wrap_coalescing(client))

    def _wrap_caching(self, client):
        """Cache OUTSIDE batching: a hit skips the coalescing window
        entirely, a collapsed group's one miss may still ride a batch."""
        if not (self.cache or self.singleflight):
            return client
        from .cache import CachingClient

        return CachingClient(
            client,
            cache=self.cache,
            ttl_s=self.cache_ttl_s,
            singleflight=self.singleflight,
            telemetry=self._telemetry,
        )

    def _wrap_coalescing(self, client):
        """ALL measurement workers share one client, so wrapping it in the
        batching dispatcher coalesces across workers — the deployment
        shape the dispatcher exists for."""
        if not self.coalesce:
            return client
        from .batch import BatchingClient

        return BatchingClient(
            client,
            window_us=self.batch_window_us,
            batch_max_rows=self.batch_max,
            telemetry=self._telemetry,
        )

    def _shard_arena(self):
        """One NON-promoting arena per runner for the sharded arms: the
        scatter path leases fresh per-request slabs explicitly (safe), but
        transparent promotion of the replay's SHARED cached InferInputs
        would mutate one input's raw-data/shm-params state from many
        workers at once — unsharded replay records must stay plain
        binary."""
        with self._arena_lock:
            if self._arena is None:
                from .arena import ShmArena

                self._arena = ShmArena(promote_inputs=False,
                                       name_prefix="perf_shard")
            return self._arena

    def _make_federated_client(self, concurrency: int):
        """A FederatedClient over ``--cells``: per-cell PoolClients with
        the pool-level flags (routing/admission/endpoint limits/retries)
        applied to EVERY cell, plus the shadow/canary rollout policies
        when named."""
        from .federation import CanaryPolicy, FederatedClient, ShadowPolicy
        from .resilience import RetryPolicy

        factory = None
        if self.protocol == "http":
            mod = self._client_mod

            def factory(url):
                return mod.InferenceServerClient(url, concurrency=concurrency)

        pool_kwargs: Dict[str, Any] = {
            "client_factory": factory,
            "routing": self.routing or "round_robin",
            "health_interval_s": 0.5,
            "probe_timeout_s": 0.5,
            "endpoint_retry": (RetryPolicy(max_attempts=self.retries + 1)
                               if self.retries else None),
            # admission=True (or the kwargs-dict form, when tenancy is
            # armed) builds a FRESH controller inside each cell's pool —
            # one shared controller would meter the cells jointly and
            # hide exactly the per-cell saturation the federation
            # spills on
            "admission": (
                {"mode": self.admission_mode,
                 "target_ms": self.admission_target_ms,
                 "max_queue_wait_s": self.admission_max_queue_wait_s,
                 "tenancy": self.tenancy}
                if self.admission and self.tenancy is not None
                else True if self.admission else None),
            "endpoint_limits": True if self.endpoint_limits else None,
        }
        shadow = None
        if self.shadow_cell:
            shadow = ShadowPolicy(self.shadow_cell, ratio=self.shadow_ratio)
        canary = None
        if self.canary_cell:
            canary = CanaryPolicy(
                self.canary_cell, weight=self.canary_weight,
                slo=self.canary_slo or "p95<250ms",
                min_events=self.canary_min_events)
        return FederatedClient(
            self.cells,
            home=self.home_cell,
            protocol=self.protocol,
            telemetry=self._telemetry,
            shadow=shadow,
            canary=canary,
            default_deadline_s=self.cells_deadline_s,
            per_attempt_timeout_s=self.cells_attempt_timeout_s,
            pool_kwargs=pool_kwargs,
        )

    def _make_pool_client(self, concurrency: int):
        from .pool import HedgePolicy, PoolClient
        from .resilience import RetryPolicy

        factory = None
        if self.protocol == "http":
            mod = self._client_mod

            def factory(url):
                return mod.InferenceServerClient(url, concurrency=concurrency)

        hedge = None
        if self.hedge:
            hedge = HedgePolicy(delay_s=self.hedge_delay_s)
        endpoint_retry = (
            RetryPolicy(max_attempts=self.retries + 1) if self.retries else None)
        telemetry = self._telemetry
        if self.routing == "orca_weighted" and telemetry is None:
            # the pool can only route on loads somebody ingests: a quiet
            # (sample=off) telemetry carries the ORCA opt-in + gauges
            from .observe import Telemetry

            telemetry = Telemetry(sample="off", orca_format="json")
        admission = None
        if self.admission:
            from .admission import AdmissionController

            admission = AdmissionController(
                mode=self.admission_mode,
                target_ms=self.admission_target_ms,
                max_queue_wait_s=self.admission_max_queue_wait_s,
                tenancy=self.tenancy)
        return PoolClient(
            self.endpoints,
            protocol=self.protocol,
            # sharded scatter staging rides the arena fast path (cached
            # per-endpoint registrations; see client_tpu.shard)
            shm_arena=self._shard_arena() if self.shard_layout is not None
            else None,
            client_factory=factory,
            routing=self.routing or "round_robin",
            health_interval_s=0.5,
            endpoint_retry=endpoint_retry,
            hedge=hedge,
            # primary + hedge both ride the executor: size it so the full
            # worker concurrency never queues behind hedge threads
            hedge_executor_workers=max(8, 2 * concurrency),
            telemetry=telemetry,
            admission=admission,
            endpoint_limits=True if self.endpoint_limits else None,
        )

    def _control_client(self):
        """(client, module) for metadata/probing: the protocol's own python
        client, except native (whose C API is a data-plane surface) -> http.
        Always dials the server directly (never the chaos proxy)."""
        if self.protocol in ("grpc", "native-grpc", "native-grpc-async"):
            import client_tpu.grpc as mod
        else:
            import client_tpu.http as mod
        return mod.InferenceServerClient(self._direct_url), mod

    def _fetch_metadata(self) -> Dict[str, Any]:
        client, _ = self._control_client()
        try:
            md = client.get_model_metadata(self.model_name)
        finally:
            client.close()
        return md

    def _resolve_shape(self, name: str, shape: List[int]) -> List[int]:
        if name in self.shape_overrides:
            return self.shape_overrides[name]
        resolved = [d if d != -1 else 1 for d in shape]
        if self.batch_size:
            resolved = [self.batch_size] + resolved
        return resolved

    def _generate_tensors(self) -> List[Tuple[str, str, List[int], np.ndarray]]:
        tensors = []
        for t in self._metadata["inputs"]:
            shape = self._resolve_shape(t["name"], list(t["shape"]))
            tensors.append(
                (t["name"], t["datatype"], shape, _random_tensor(t["datatype"], shape, self.rng))
            )
        return tensors

    def _probe_output_sizes(self) -> Dict[str, int]:
        from .utils import serialized_byte_size

        client, mod = self._control_client()
        try:
            inputs = []
            for name, datatype, shape, data in self._tensors:
                inp = mod.InferInput(name, shape, datatype)
                inp.set_data_from_numpy(data)
                inputs.append(inp)
            result = client.infer(self.model_name, inputs)
            sizes = {}
            for out in self._metadata["outputs"]:
                arr = result.as_numpy(out["name"])
                if arr is None:
                    continue
                nbytes = serialized_byte_size(arr) if arr.dtype == np.object_ else arr.nbytes
                sizes[out["name"]] = nbytes + nbytes // 4  # slack for growth
            return sizes
        finally:
            client.close()

    def _run_arena(self):
        """The runner's lazily-created ShmArena (uuid-keyed regions, so
        concurrent runs on one host can never collide on fixed names).
        Lock-guarded: every worker thread sets up concurrently and all of
        them must share ONE arena."""
        with self._arena_lock:
            if self._arena is None:
                from .arena import ShmArena

                family = "tpu" if (self.shared_memory == "tpu"
                                   or self.protocol.startswith("native")) \
                    else "system"
                self._arena = ShmArena(default_family=family, colocated=True)
            return self._arena

    def _shm_worker_setup(self, client, worker_id, family=None):
        """ONE shared setup path for every shm mode (system / tpu / native):
        leases input+output slabs from the runner's arena, writes each
        payload once, and lets the (cached) registration machinery issue
        the register RPC only on a region's first use per endpoint — this
        replaces the five per-use-site create/register/destroy blocks this
        file used to carry. Returns (inputs, outputs_or_None, cleanup)."""
        from .utils import serialized_byte_size

        family = family or self.shared_memory
        native = self.protocol in ("native", "native-grpc")
        arena = self._run_arena()
        mod = self._client_mod
        leases = []

        def cleanup():
            for lease in leases:
                try:
                    lease.release()
                except Exception:
                    pass

        try:
            inputs = []
            for name, datatype, shape, data in self._tensors:
                nbytes = (serialized_byte_size(data)
                          if datatype == "BYTES" else data.nbytes)
                lease = arena.lease(nbytes, family=family)
                leases.append(lease)
                if family == "tpu" and datatype != "BYTES":
                    import jax

                    dev = jax.device_put(data)
                    dev.block_until_ready()
                    lease.write_jax(dev)
                else:
                    lease.write_numpy(data)
                if native:
                    arena.ensure_registered(client, lease._region)
                    inputs.append((name, ("shm", lease.region_name, nbytes,
                                          lease.offset, datatype, shape)))
                else:
                    # bind_input attaches the lease, so infer() ensures the
                    # (cached) registration against whichever endpoint the
                    # request actually lands on
                    inputs.append(lease.bind_input(
                        mod.InferInput(name, shape, datatype)))
            outputs = []
            for name, nbytes in self._output_sizes.items():
                lease = arena.lease(nbytes, family=family)
                leases.append(lease)
                if native:
                    arena.ensure_registered(client, lease._region)
                    outputs.append((name, ("shm", lease.region_name,
                                           lease.byte_size, lease.offset)))
                else:
                    outputs.append(lease.bind_output(
                        mod.InferRequestedOutput(name)))
        except Exception:
            cleanup()
            raise
        return inputs, outputs or None, cleanup

    # -- one worker --------------------------------------------------------
    def _worker_setup(self, client, worker_id):
        """Per-worker client/tensor/shm setup shared by the closed-loop
        (concurrency) and open-loop (request-rate) workers.

        Returns (client, inputs, outputs, shm_cleanup, own_client)."""
        mod = self._client_mod
        shm_ctx = None
        own_client = None
        if self.protocol == "native-grpc-async":
            # ONE client shared by every worker: the async worker keeps
            # all their RPCs in flight on a single multiplexed h2
            # connection (completion-queue model) — this mode measures
            # exactly what per-worker instances cannot: one instance's
            # concurrent throughput
            inputs = [(name, data) for name, _, _, data in self._tensors]
            outputs = None
        elif self.protocol in ("native", "native-grpc"):
            # one C++ client per worker: the native sync Infer serializes
            # on a per-client transport handle, so sharing one client
            # would measure lock contention instead of concurrency
            own_client = self._make_client()
            client = own_client
            try:
                inputs, outputs, shm_ctx = self._native_worker_setup(
                    client, worker_id
                )
            except Exception:
                # the caller never receives own_client on failure — close
                # here or the native socket/handle leaks per failed worker
                own_client.close()
                raise
        elif self.shared_memory in ("system", "tpu"):
            inputs, outputs, shm_ctx = self._shm_worker_setup(
                client, worker_id)
        else:
            outputs = None
            inputs = []
            for name, datatype, shape, data in self._tensors:
                inp = mod.InferInput(name, shape, datatype)
                inp.set_data_from_numpy(data)
                inputs.append(inp)
        return client, inputs, outputs, shm_ctx, own_client

    def _worker(self, client, barrier, stop, latencies, errors, sheds,
                counter, worker_id):
        from .admission import AdmissionRejected
        from .resilience import CircuitOpenError

        shm_ctx = None
        own_client = None
        setup_failed = False
        try:
            client, inputs, outputs, shm_ctx, own_client = self._worker_setup(
                client, worker_id)
        except Exception as e:
            errors.append(f"worker setup failed: {e}")
            setup_failed = True
        try:
            # the barrier must be reached even on setup failure, or run()
            # would wait forever for this worker
            barrier.wait(timeout=120)
            if setup_failed:
                stop.set()
                return
            lock, count, limit = counter
            # keyword only when armed: harness hooks that stub _infer_once
            # with the bare (client, inputs, outputs) signature keep working
            akw = ({"affinity_key": self._affinity_key_for(worker_id)}
                   if self.affinity_key is not None else {})
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    self._infer_once(client, inputs, outputs, **akw)
                    latencies.append(time.perf_counter() - t0)
                except (CircuitOpenError, AdmissionRejected) as e:
                    sheds.append(str(e))  # deliberate shedding, not error
                except Exception as e:  # measured as failure, loop continues
                    errors.append(str(e))
                with lock:
                    count[0] += 1
                    if count[0] >= limit:
                        stop.set()
        finally:
            if shm_ctx is not None:
                shm_ctx()
            if own_client is not None:
                own_client.close()

    def _rate_worker(self, client, barrier, stop, schedule, cursor, t0_box,
                     records, lags, issues, errors, sheds, worker_id):
        """Open-loop worker: claims the next arrival slot from the shared
        schedule, sleeps until its wall-clock time, then issues one sync
        infer. Lateness (actual start - scheduled start) is recorded per
        request — under saturation the pool can't keep up and the lag
        distribution, not just latency, shows it (perf_analyzer's delayed
        request semantics for --request-rate-range)."""
        from .admission import AdmissionRejected
        from .resilience import CircuitOpenError

        shm_ctx = None
        own_client = None
        setup_failed = False
        try:
            client, inputs, outputs, shm_ctx, own_client = self._worker_setup(
                client, worker_id)
        except Exception as e:
            errors.append(f"worker setup failed: {e}")
            setup_failed = True
        try:
            barrier.wait(timeout=120)
            if setup_failed:
                stop.set()
                return
            lock, idx = cursor
            akw = ({"affinity_key": self._affinity_key_for(worker_id)}
                   if self.affinity_key is not None else {})
            while not stop.is_set():
                with lock:
                    i = idx[0]
                    if i >= len(schedule):
                        return
                    idx[0] += 1
                target = t0_box[0] + schedule[i]
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                lag = max(0.0, time.perf_counter() - target)
                # lag is recorded for EVERY issued request — under overload
                # the failing requests are the latest-starting ones, and
                # excluding them would understate exactly the slip this
                # mode exists to measure
                lags.append(lag)
                # actual arrival offset: feeds the achieved-ARRIVAL rate, so
                # a saturated replay that silently under-offers (workers all
                # busy, schedule slipping) can't flatter the result
                issues.append(schedule[i] + lag)
                t1 = time.perf_counter()
                try:
                    self._infer_once(client, inputs, outputs, **akw)
                    records.append(time.perf_counter() - t1)
                except (CircuitOpenError, AdmissionRejected) as e:
                    sheds.append(str(e))  # deliberate shedding, not error
                except Exception as e:  # measured as failure, loop continues
                    errors.append(str(e))
        finally:
            if shm_ctx is not None:
                shm_ctx()
            if own_client is not None:
                own_client.close()

    def _affinity_key_for(self, worker_id) -> Optional[str]:
        """The closed/open-loop worker's session key: ``worker`` = one
        key per worker (a steady per-session stream, the KV-reuse shape);
        any other value is a shared literal key (the hot-key shape)."""
        if self.affinity_key is None:
            return None
        if self.affinity_key == "worker":
            return f"w{worker_id}"
        return self.affinity_key

    def _infer_once(self, client, inputs, outputs=None, affinity_key=None):
        if self.generate_stream:
            # one "request" = one fully-drained SSE generation session
            kw = ({"affinity_key": affinity_key}
                  if affinity_key is not None else {})
            for _event in client.generate_stream(
                    self.model_name, self._stream_payload, **kw):
                pass
            return
        if self.protocol == "native-grpc-async":
            done = threading.Event()
            box = {}

            def on_complete(result, error):
                box["error"] = error
                done.set()

            client.async_infer(self.model_name, inputs, on_complete)
            if not done.wait(timeout=120):
                raise RuntimeError("async infer did not complete in 120s")
            if box.get("error"):
                raise RuntimeError(box["error"])
            return
        if affinity_key is not None:
            client.infer(self.model_name, inputs, outputs=outputs,
                         affinity_key=affinity_key)
            return
        client.infer(self.model_name, inputs, outputs=outputs)

    def _native_worker_setup(self, client, worker_id):
        """(inputs, outputs, cleanup) for the native protocol's worker —
        shm mode rides the same arena helper as the python frontends."""
        if self.shared_memory == "none":
            inputs = [(name, data) for name, _, _, data in self._tensors]
            return inputs, None, None
        return self._shm_worker_setup(client, worker_id, family="tpu")

    def _arm_telemetry(self, measurement_requests: int):
        """A fresh Telemetry per measurement run (sample=always, ring sized
        to hold every request) so each result row's phase breakdown covers
        exactly that run."""
        if not (self.observe or self.flight or self.watch):
            return
        from .observe import Telemetry

        self._telemetry = Telemetry(
            # --flight without --observe keeps span retention off: the
            # recorder's own tail ring is the retention mechanism
            sample=self.observe_sample if self.observe else "off",
            trace_capacity=max(measurement_requests, 1024),
            orca_format=self._orca_format,
            flight=self._make_flight())
        self._arm_watch()

    def _arm_watch(self):
        """A run-scoped Watchtower over the run's telemetry: background
        ticks during the measurement window, final synchronous tick and
        stats harvest in :meth:`_watch_result`."""
        if not self.watch or self._telemetry is None:
            return
        from .watch import Watchtower

        if self._watchtower is not None:
            self._watchtower.stop()
        self._watchtower = Watchtower(
            self._telemetry, interval_s=0.25).start()

    def _watch_result(self, result: Dict[str, Any]) -> Dict[str, Any]:
        """Append ``client_watch``: the run's continuous-monitoring
        verdicts (alerts fired/resolved by kind, the active set, tick
        overhead p50/p99, changepoint trips)."""
        tower, self._watchtower = self._watchtower, None
        if tower is None:
            return result
        tower.tick()  # short runs still get at least one full evaluation
        tower.stop()
        stats = tower.stats()
        result["client_watch"] = {
            "ticks": stats["ticks"],
            "tick_ns": stats.get("tick_ns"),
            "alerts_fired": stats["alerts_fired"],
            "alerts_resolved": stats["alerts_resolved"],
            "alerts_active": stats["alerts_active"],
            "changepoint_trips": stats["changepoint_trips"],
            "active": [a.as_dict() for a in tower.active_alerts()],
        }
        return result

    def _arm_dataplane(self):
        """Scoped shm accounting for shm-mode runs: reuse an already
        installed recorder, else install one for the run (the caller's
        try/finally uninstalls an owned one even when the run raises).
        Returns (recorder, before-snapshot, owned)."""
        if self.shared_memory not in ("system", "tpu"):
            return None, None, False
        from . import observe

        # arena hit-rate baseline for this run's client_shm row (the arena
        # itself is cumulative across a sweep's runs — that reuse IS the
        # point — so the row reports deltas)
        self._arena_before = (self._arena.stats()
                              if self._arena is not None else None)
        recorder = observe.dataplane()
        if recorder is not None:
            return recorder, recorder.snapshot(), False
        registry = (self._telemetry.registry
                    if self._telemetry is not None else None)
        recorder = observe.enable_dataplane(registry)
        return recorder, recorder.snapshot(), True

    def _shm_result(self, result: Dict[str, Any], recorder,
                    before) -> Dict[str, Any]:
        """Registration-churn counters for the run: regions created and
        register RPCs issued, bytes peak — so BASELINE-style shm sweeps
        record the data-plane cost the pooled-arena work (ROADMAP item 1)
        will eliminate."""
        if recorder is None:
            return result
        after = recorder.snapshot()
        family = self.shared_memory
        before_fam = before["families"][family]
        after_fam = after["families"][family]

        def rpc_delta(op: str) -> int:
            key = f"{family}.{op}.ok"
            return int(after["rpcs"].get(key, 0) - before["rpcs"].get(key, 0))

        result["client_shm"] = {
            "family": family,
            "regions_created": int(
                after_fam["created"] - before_fam["created"]),
            "regions_destroyed": int(
                after_fam["destroyed"] - before_fam["destroyed"]),
            "regions_registered": rpc_delta("register"),
            "regions_unregistered": rpc_delta("unregister"),
            "map_writes": int(
                after_fam["map_writes"] - before_fam["map_writes"]),
            "map_reads": int(
                after_fam["map_reads"] - before_fam["map_reads"]),
            # the recorder's high-water mark is attributable to THIS run
            # only when the run raised it (always true for the run-scoped
            # recorder _arm_dataplane installs; a reused process-global
            # recorder may carry an earlier run's peak -> unknown/None)
            "bytes_peak": (int(after_fam["bytes_peak"])
                           if after_fam["bytes_peak"]
                           > before_fam["bytes_peak"] else None),
        }
        if self._arena is not None:
            astats = self._arena.stats()
            abefore = self._arena_before or {}

            def adelta(key: str) -> int:
                return int(astats[key] - abefore.get(key, 0))

            leases = adelta("leases")
            reg_issued = adelta("registrations_issued")
            reg_cached = adelta("registrations_cached")
            result["client_shm"]["arena"] = {
                "leases": leases,
                "hits": adelta("hits"),
                "misses": adelta("misses"),
                # a warm sweep's later runs should approach 1.0: slabs and
                # registrations outlive the run that created them
                "hit_rate": (round(adelta("hits") / leases, 4)
                             if leases else None),
                "registrations_issued": reg_issued,
                "registrations_cached": reg_cached,
                "registration_cache_hit_rate": (
                    round(reg_cached / (reg_cached + reg_issued), 4)
                    if (reg_cached + reg_issued) else None),
                "leased_bytes": astats["leased_bytes"],
                "regions": astats["regions"],
            }
        return result

    @staticmethod
    def _disarm_dataplane(owned: bool) -> None:
        if owned:
            from . import observe

            observe.install_dataplane(None)

    @staticmethod
    def _admission_stats(client) -> Optional[Dict[str, Any]]:
        """The pool's admission-controller snapshot (limit, inflight,
        per-lane sheds), when one is armed — appended to result rows as
        ``client_admission`` so artifacts carry the shed story."""
        getter = getattr(client, "admission", None)
        if getter is None:
            return None
        try:
            ctrl = getter()
            return ctrl.snapshot() if ctrl is not None else None
        except Exception:
            return None

    @staticmethod
    def _admission_result(result: Dict[str, Any],
                          admission_stats: Optional[Dict[str, Any]],
                          ) -> Dict[str, Any]:
        if admission_stats is not None:
            result["client_admission"] = admission_stats
        return result

    def _integrity_stats(self) -> Optional[Dict[str, Any]]:
        """Pre-run snapshot of the process-global integrity counters,
        when ``--validate`` armed the row. Contract validation itself is
        default-ON regardless — this flag only opts the RESULT ROW into
        carrying the delta, so A/B artifacts stay byte-stable when
        validation reporting is off."""
        if not self.validate:
            return None
        from . import integrity

        return integrity.global_stats().snapshot()

    def _integrity_result(self, result: Dict[str, Any],
                          before: Optional[Dict[str, Any]],
                          ) -> Dict[str, Any]:
        """Append ``client_integrity``: this run's delta of the global
        validation counters (results checked, per-check count,
        violations by kind) plus the overhead percentile window — the
        measured nanoseconds the contract walk cost per response."""
        if before is None:
            return result
        from . import integrity

        after = integrity.global_stats().snapshot()
        kinds = {
            k: after["violations_by_kind"].get(k, 0)
            - before["violations_by_kind"].get(k, 0)
            for k in after.get("violations_by_kind", {})
        }
        result["client_integrity"] = {
            "results": after["results"] - before["results"],
            "checks": after["checks"] - before["checks"],
            "violations": after["violations"] - before["violations"],
            "violations_by_kind": {k: v for k, v in kinds.items() if v},
            # the stats ring holds the most recent samples, which for a
            # just-finished run IS the run's window
            "overhead_ns": after.get("overhead_ns", {}),
        }
        return result

    def _federation_stats(self, client) -> Optional[Dict[str, Any]]:
        """The federation snapshot (per-cell spill/serve counters plus
        the shadow/canary views) when ``--cells`` is armed — appended to
        result rows as ``client_federation`` so artifacts carry the
        spillover/rollout story."""
        if not self.cells:
            return None
        getter = getattr(client, "federation_stats", None)
        if getter is None:
            return None
        try:
            # let in-flight shadow mirrors settle so the row's counters
            # cover the run (bounded; mirrors are themselves bounded)
            drain = getattr(client, "shadow_drain", None)
            if drain is not None and self.shadow_cell:
                drain(timeout_s=5.0)
            return getter()
        except Exception:
            return None

    @staticmethod
    def _federation_result(result: Dict[str, Any],
                           fed_stats: Optional[Dict[str, Any]],
                           ) -> Dict[str, Any]:
        if fed_stats is not None:
            cells = fed_stats.get("cells", {})
            result["client_federation"] = {
                "home": fed_stats.get("home"),
                "order": fed_stats.get("order"),
                "spills": sum(
                    n for row in cells.values()
                    for n in (row.get("spill_out") or {}).values()),
                "cells": cells,
                "shadow": fed_stats.get("shadow"),
                "canary": fed_stats.get("canary"),
            }
        return result

    def _cache_stats_row(self, client) -> Optional[Dict[str, Any]]:
        """The caching wrapper's snapshot, when armed — the per-arm
        hit/collapse story every harness row carries as ``client_cache``."""
        if not (self.cache or self.singleflight):
            return None
        getter = getattr(client, "cache_stats", None)
        if getter is None:
            return None
        try:
            return getter()
        except Exception:
            return None

    @staticmethod
    def _cache_result(result: Dict[str, Any],
                      cache_stats: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        if cache_stats is not None:
            result["client_cache"] = {
                "hit_rate": cache_stats["hit_rate"],
                "hits": cache_stats["hit"],
                "stale_hits": cache_stats["stale"],
                "misses": cache_stats["miss"],
                "bypass": cache_stats["bypass"],
                "singleflight_collapsed": cache_stats[
                    "singleflight_collapsed"],
                "collapse_ratio": cache_stats["collapse_ratio"],
                "wire_requests": cache_stats["wire_requests"],
                "logical_requests": cache_stats["logical_requests"],
                "bytes_resident": cache_stats["bytes_resident"],
                "entries": cache_stats["entries"],
            }
        return result

    @staticmethod
    def _batch_result(result: Dict[str, Any],
                      batch_stats: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """Achieved client-side batch sizes alongside the latency row."""
        if batch_stats is not None:
            result["client_batch"] = {
                "dispatches": batch_stats["dispatches"],
                "coalesced_calls": batch_stats["coalesced_calls"],
                "solo_calls": batch_stats["solo_calls"],
                "bypass_calls": batch_stats["bypass_calls"],
                "window_us": batch_stats["window_us"],
                "rows_p50": batch_stats["batch_rows"]["p50"],
                "rows_p99": batch_stats["batch_rows"]["p99"],
                "rows_mean": batch_stats["batch_rows"]["mean"],
            }
        return result

    def _make_flight(self):
        """A fresh FlightRecorder per measurement run under ``--flight``
        (None otherwise), so each row's retention accounting covers
        exactly that run."""
        if not self.flight:
            return None
        from .flight import FlightRecorder

        return FlightRecorder()

    def _observe_result(self, result: Dict[str, Any]) -> Dict[str, Any]:
        if self._telemetry is not None:
            # --flight without --observe runs sample="off": the empty
            # trace ring yields empty breakdowns, skip the rows entirely
            if self.observe or self._telemetry.sample != "off":
                result["client_phase_ms"] = \
                    self._telemetry.phase_breakdown()
                stream = self._telemetry.stream_breakdown()
                if stream:
                    # streaming runs: ttft/itl/duration p50/p99 from the
                    # exact StreamSpan samples in the trace ring
                    result["client_stream_ms"] = stream
            recorder = getattr(self._telemetry, "flight", None)
            if recorder is not None:
                stats = recorder.stats()
                result["client_flight"] = {
                    "requests": stats["requests"],
                    "events_per_request": stats["events_per_request"],
                    "retained": stats["retained"],
                    "retained_total": stats["retained_total"],
                    "retained_fraction": stats["retained_fraction"],
                    "dropped": stats["dropped"],
                    "ring": stats["ring"],
                    "capacity": stats["capacity"],
                    "commit_retained_ns": stats.get("commit_retained_ns"),
                    "commit_dropped_ns": stats.get("commit_dropped_ns"),
                }
        return result

    # -- sweep -------------------------------------------------------------
    def run(self, concurrency: int, measurement_requests: int) -> Dict[str, Any]:
        self._arm_telemetry(measurement_requests)
        shm_rec, shm_before, shm_owned = self._arm_dataplane()
        try:
            return self._run_closed(
                concurrency, measurement_requests, shm_rec, shm_before)
        finally:
            # an owned recorder must not outlive the run, even on error
            self._disarm_dataplane(shm_owned)

    def _run_closed(self, concurrency: int, measurement_requests: int,
                    shm_rec, shm_before) -> Dict[str, Any]:
        integrity_before = self._integrity_stats()
        client = self._make_client(concurrency)
        if self.protocol == "native-grpc-async":
            # the shared instance must admit as many RPCs as we have
            # workers, or the measurement clamps at the default window
            client.set_async_concurrency(concurrency)
        latencies: List[float] = []
        errors: List[str] = []
        sheds: List[str] = []  # breaker fast-fails + admission rejections
        stop = threading.Event()
        barrier = threading.Barrier(concurrency + 1)
        counter = (threading.Lock(), [0], measurement_requests)
        workers = [
            threading.Thread(
                target=self._worker,
                args=(client, barrier, stop, latencies, errors, sheds,
                      counter, i),
                daemon=True,
            )
            for i in range(concurrency)
        ]
        for w in workers:
            w.start()
        barrier.wait()
        t_start = time.perf_counter()
        for w in workers:
            w.join(timeout=600)
        elapsed = time.perf_counter() - t_start
        batch_stats = client.stats() if self.coalesce else None
        cache_stats = self._cache_stats_row(client)
        admission_stats = self._admission_stats(client)
        fed_stats = self._federation_stats(client)
        client.close()

        lat_sorted = sorted(latencies)
        n = len(lat_sorted)
        issued = n + len(errors) + len(sheds)
        return self._watch_result(self._integrity_result(
            self._federation_result(self._cache_result(
            self._admission_result(
            self._shm_result(self._batch_result(
            self._observe_result({
            "model": self.model_name,
            "protocol": self.protocol,
            "shared_memory": self.shared_memory,
            "concurrency": concurrency,
            "requests": n,
            "errors": len(errors),
            "shed": len(sheds),
            # a breaker fast-fail / admission rejection is deliberate
            # load-shedding, not a server error: the two rates must never
            # share a bucket (that would make overload unreadable)
            "error_pct": round(100.0 * len(errors) / issued, 2)
            if issued else 0.0,
            "shed_pct": round(100.0 * len(sheds) / issued, 2)
            if issued else 0.0,
            "error_sample": errors[0] if errors else None,
            "shed_sample": sheds[0] if sheds else None,
            "duration_s": round(elapsed, 3),
            "infer_per_sec": round(n / elapsed, 1) if elapsed > 0 else 0.0,
            "latency_ms": _latency_ms_row(lat_sorted),
        }), batch_stats), shm_rec, shm_before), admission_stats),
            cache_stats), fed_stats), integrity_before))

    def run_rate(self, rate: float, measurement_requests: int,
                 distribution: str = "constant",
                 pool_size: int = 16) -> Dict[str, Any]:
        """Open-loop measurement at a fixed arrival rate (perf_analyzer's
        --request-rate-range). Arrivals follow the schedule regardless of
        completions, so queueing shows up as schedule lag + latency growth
        instead of the closed-loop's self-throttling."""
        if rate <= 0:
            raise ValueError("rate must be > 0")
        if measurement_requests < 1:
            raise ValueError("measurement_requests must be >= 1")
        if distribution == "constant":
            gaps = np.full(measurement_requests, 1.0 / rate)
        elif distribution == "poisson":
            gaps = self.rng.exponential(1.0 / rate, size=measurement_requests)
        else:
            raise ValueError(f"unknown distribution {distribution!r}")
        schedule = np.concatenate([[0.0], np.cumsum(gaps[:-1])]).tolist()

        self._arm_telemetry(measurement_requests)
        shm_rec, shm_before, shm_owned = self._arm_dataplane()
        try:
            return self._run_open(
                rate, distribution, pool_size, schedule, shm_rec, shm_before)
        finally:
            # an owned recorder must not outlive the run, even on error
            self._disarm_dataplane(shm_owned)

    def _run_open(self, rate: float, distribution: str, pool_size: int,
                  schedule: List[float], shm_rec,
                  shm_before) -> Dict[str, Any]:
        integrity_before = self._integrity_stats()
        client = self._make_client(pool_size)
        if self.protocol == "native-grpc-async":
            client.set_async_concurrency(pool_size)
        records: List[float] = []  # latency_s of successful requests
        lags: List[float] = []  # schedule lag of EVERY issued request
        issues: List[float] = []  # actual arrival offset of every request
        errors: List[str] = []
        sheds: List[str] = []  # breaker fast-fails + admission rejections
        stop = threading.Event()
        barrier = threading.Barrier(pool_size + 1)
        cursor = (threading.Lock(), [0])
        t0_box = [0.0]
        workers = [
            threading.Thread(
                target=self._rate_worker,
                args=(client, barrier, stop, schedule, cursor, t0_box,
                      records, lags, issues, errors, sheds, i),
                daemon=True,
            )
            for i in range(pool_size)
        ]
        for w in workers:
            w.start()
        # t0 must be written BEFORE the barrier releases the workers — they
        # read it immediately to place the schedule on the wall clock
        t0_box[0] = time.perf_counter()
        barrier.wait()
        for w in workers:
            w.join(timeout=600)
        elapsed = time.perf_counter() - t0_box[0]
        batch_stats = client.stats() if self.coalesce else None
        cache_stats = self._cache_stats_row(client)
        admission_stats = self._admission_stats(client)
        fed_stats = self._federation_stats(client)
        client.close()

        lat_sorted = sorted(records)
        lag_sorted = sorted(lags)
        n = len(lat_sorted)
        issued = len(lag_sorted)
        # a request is "delayed" when the pool could not start it on time
        # (reference threshold: perf_analyzer flags schedule slip; 1 ms
        # separates scheduler jitter from genuine queueing)
        delayed = sum(1 for lag in lag_sorted if lag > 1e-3)
        # offered vs achieved ARRIVAL rate: the schedule asked for ``rate``
        # req/s; what the workers actually managed to issue is the honest
        # denominator for every capacity claim (a saturated pool that
        # silently under-offers would otherwise flatter its own number)
        arrival_window = max(issues) if issues else 0.0
        return self._watch_result(self._integrity_result(
            self._federation_result(self._cache_result(
            self._admission_result(
            self._shm_result(self._batch_result(
            self._observe_result({
            "model": self.model_name,
            "protocol": self.protocol,
            "shared_memory": self.shared_memory,
            "request_rate": rate,
            "offered_rate": rate,
            "distribution": distribution,
            "pool_size": pool_size,
            "requests": n,
            "issued": issued,
            "errors": len(errors),
            "shed": len(sheds),
            # under saturation the split is the whole story: shed_pct is
            # honest load-shedding (breaker fast-fail / admission), while
            # error_pct is genuine failure — they never share a bucket
            "error_pct": round(100.0 * len(errors) / issued, 2)
            if issued else 0.0,
            "shed_pct": round(100.0 * len(sheds) / issued, 2)
            if issued else 0.0,
            "error_sample": errors[0] if errors else None,
            "shed_sample": sheds[0] if sheds else None,
            "duration_s": round(elapsed, 3),
            "achieved_rate": round(n / elapsed, 1) if elapsed > 0 else 0.0,
            "achieved_arrival_rate": round(issued / arrival_window, 1)
            if arrival_window > 0 else 0.0,
            "latency_ms": _latency_ms_row(lat_sorted),
            "schedule_lag_ms": _lag_ms_row(lag_sorted),
            "delayed_pct": round(100.0 * delayed / issued, 1) if issued else 0.0,
        }), batch_stats), shm_rec, shm_before), admission_stats),
            cache_stats), fed_stats), integrity_before))

    # -- trace replay --------------------------------------------------------
    _SEQ_GATE_TIMEOUT_S = 60.0

    def run_trace(self, trace, speed: float = 1.0, replay_workers: int = 32,
                  slos: Sequence[Any] = (), on_result=None,
                  warmup: bool = True) -> Dict[str, Any]:
        """Open-loop replay of a workload trace (``client_tpu.trace``)
        against the configured frontend/pool: arrivals are scheduled at
        ``at_s / speed`` regardless of completions, and all three request
        kinds run concurrently — unary infers, ``generate_stream`` SSE
        sessions (TTFT/ITL via StreamSpan), and sequences whose steps are
        issued in order (the pool pins each group to one replica).

        ``slos``: declared objectives — ``observe.SLOSpec`` values or spec
        strings (``ttft_p95<200ms``, ``p99<50ms``, ``error_rate<0.1%``).
        Stream-metric SLOs are tracked by a fresh per-run
        ``observe.Telemetry`` (one StreamSpan per session; exact over the
        replay window); ``request_ms`` SLOs are fed one event per
        unary/sequence record from the replay's own outcome accounting
        (so batching's inner dispatches and hedging's extra attempts
        cannot skew the population); error-rate SLOs are evaluated from
        the shed/error fractions.
        The result row carries per-kind latency/TTFT/ITL percentiles,
        offered-vs-achieved rates, schedule slip, shed/error fractions
        and the per-SLO verdicts (``slo_ok`` = every objective attained).

        ``on_result(record, outcome)`` (optional) is called with each
        completed record and its result object / exception — test hooks
        only; keep it cheap, it runs on the replay workers.

        ``warmup`` (default True): before the schedule starts, one
        best-effort dispatch per distinct (kind, model) through a
        separate telemetry-free client, so the first measured record of
        each model never bills jit compilation to an SLO."""
        from .observe import SLO, SLOSpec, parse_slo_spec, Telemetry
        from .trace import Trace

        if speed <= 0:
            raise ValueError("speed must be > 0")
        if self.protocol not in ("http", "grpc"):
            raise ValueError(
                "trace replay requires a python frontend (http|grpc): the "
                "native clients take (name, array) pairs and have no "
                "sequence/telemetry surface")
        if self.shared_memory != "none":
            raise ValueError(
                "trace replay supports --shared-memory none only: replay "
                "payloads are synthesized per record, not staged in "
                "pre-registered regions")
        if isinstance(trace, Trace):
            header, records = trace.header, trace.records
        else:
            header, records = {}, list(trace)
        if not records:
            raise ValueError("empty trace")
        records = sorted(records, key=lambda r: r.at_s)
        if (any(r.kind == "generate_stream" for r in records)
                and self.protocol != "http"):
            raise ValueError(
                "trace contains generate_stream records: the generate "
                "extension is an HTTP SSE surface (use -i http)")
        if (any(r.kind == "sharded" for r in records)
                and self.shard_layout is None):
            raise ValueError(
                "trace contains sharded records: configure --shard-layout "
                "(with --endpoints) so the replayer can scatter them "
                "(client_tpu.shard)")
        if any(r.kind == "prefill_decode" for r in records):
            if self.protocol != "http":
                raise ValueError(
                    "trace contains prefill_decode records: the decode "
                    "leg is an HTTP SSE surface (use -i http)")
            if not self.roles:
                raise ValueError(
                    "trace contains prefill_decode records: configure "
                    "--roles 'prefill=u1;decode=u2' so the replayer can "
                    "build a DisaggClient over role-labeled endpoints "
                    "(client_tpu.disagg)")
        if (any(r.kind == "pipeline" for r in records)
                and self.pipeline is None):
            raise ValueError(
                "trace contains pipeline records: configure --pipeline "
                "('chain' or an inline graph spec) so the replayer can "
                "run them as client-orchestrated DAGs "
                "(client_tpu.pipeline)")
        specs: List[SLOSpec] = [
            spec if isinstance(spec, SLOSpec) else parse_slo_spec(spec)
            for spec in slos]

        trace_duration = records[-1].at_s or (1.0 / speed)
        # a fresh Telemetry per replay, sample FORCED to "always": SLO
        # good/bad counters must cover exactly this run (observe.SLO.report's
        # bounded-window contract) — a ratio mode would silently drop
        # unsampled (including errored) requests from the verdict. The
        # window must outlive the replay so nothing ages out mid-run.
        window_s = max(300.0, 4.0 * trace_duration / speed)
        self._telemetry = Telemetry(
            sample="always",
            trace_capacity=len(records) + 64,
            stream_window_s=window_s,
            orca_format=self._orca_format,
            flight=self._make_flight())
        self._arm_watch()
        # request_ms SLOs are fed PER TRACE RECORD from the replay's own
        # outcome accounting, NOT from telemetry spans: under coalescing
        # every batch adds an inner-dispatch span and under hedging every
        # attempt (including cancelled losers) is its own span — span-fed
        # counts would make per-arm capacity verdicts incomparable
        # populations. Stream-metric SLOs stay span-fed (one StreamSpan
        # per session by construction).
        request_slos: List[SLO] = []
        for spec in specs:
            if spec.kind != "latency":
                continue
            if spec.metric == "request_ms":
                request_slos.append(SLO(
                    spec.name, "request_ms", spec.threshold_ms,
                    spec.objective, window_s))
            else:
                self._telemetry.track_slo(
                    spec.name, spec.metric, spec.threshold_ms,
                    spec.objective, window_s=window_s)

        try:
            return self._run_trace_measured(
                header, records, speed, replay_workers, specs, on_result,
                warmup, trace_duration, request_slos)
        finally:
            if not self.observe:
                # the per-run Telemetry must not leak into later run()/
                # run_rate() calls on a runner that never asked for
                # telemetry — on ANY exit path, including errors
                self._telemetry = None

    def _run_trace_measured(self, header, records, speed, replay_workers,
                            specs, on_result, warmup, trace_duration,
                            request_slos) -> Dict[str, Any]:
        resources = _ReplayResources(self, records)
        if any(r.kind == "prefill_decode" for r in records):
            # one role-labeled DisaggClient for the whole replay
            # (telemetry-free: prefill_decode sessions feed request_ms
            # SLOs per record, like unaries, so warmup sessions land
            # nothing in the per-run Telemetry)
            resources.disagg = self._make_disagg_client()
        if any(r.kind == "pipeline" for r in records):
            # one PipelineClient (own pool, arena-backed) for the whole
            # replay; per-stage latencies land in the resources and
            # surface as the result row's ``pipeline_stages`` waterfall
            resources.pipeline = self._make_pipeline_client()
        try:
            return self._run_trace_workers(
                header, records, speed, replay_workers, specs, on_result,
                warmup, trace_duration, request_slos, resources)
        finally:
            if resources.disagg is not None:
                resources.disagg.close()
            if resources.pipeline is not None:
                resources.pipeline.close()

    def _run_trace_workers(self, header, records, speed, replay_workers,
                           specs, on_result, warmup, trace_duration,
                           request_slos, resources) -> Dict[str, Any]:
        if warmup:
            # warm through a SEPARATE telemetry-free client: server-side
            # jit / model setup is what warmup exists for, and warmup
            # traffic must not land spans or SLO events in the per-run
            # Telemetry (the verdict population is exactly the trace)
            saved_telemetry = self._telemetry
            self._telemetry = None
            warm_client = self._make_client(4)
            try:
                warm_wait = getattr(warm_client, "wait_healthy", None)
                if warm_wait is not None:
                    warm_wait(timeout_s=10.0)
                self._replay_warmup(warm_client, records, resources)
            finally:
                warm_client.close()
                self._telemetry = saved_telemetry
            # warmup DAG runs must not land in the measured waterfall
            resources.pipeline_stage_s.clear()
        # capture AFTER warmup: warmup traffic is contract-checked too
        # and must not pollute the measured row's validation delta
        integrity_before = self._integrity_stats()
        client = self._make_client(replay_workers)
        try:
            # pools: let active probes mark replicas healthy BEFORE the
            # schedule starts, or the first arrivals measure probe warmup
            wait_healthy = getattr(client, "wait_healthy", None)
            if wait_healthy is not None:
                wait_healthy(timeout_s=10.0)
            if resources.disagg is not None:
                resources.disagg.wait_healthy(timeout_s=10.0)
            outcomes: List[Tuple[str, str, float, float, float,
                                 Optional[str], Optional[str],
                                 Optional[float]]] = []
            errors: List[str] = []
            stop = threading.Event()
            barrier = threading.Barrier(replay_workers + 1)
            cursor = (threading.Lock(), [0])
            t0_box = [0.0]
            workers = [
                threading.Thread(
                    target=self._replay_worker,
                    args=(client, barrier, stop, records, speed, cursor,
                          t0_box, resources, outcomes, errors, on_result),
                    daemon=True,
                )
                for _ in range(replay_workers)
            ]
            for w in workers:
                w.start()
            t0_box[0] = time.perf_counter()
            barrier.wait()
            # the join bound scales with the trace: a replay longer than a
            # fixed cap must not be silently truncated into a row that
            # reports partial counts as the verdict
            join_timeout = max(600.0, 2.0 * trace_duration / speed + 120.0)
            for w in workers:
                w.join(timeout=join_timeout)
            stop.set()
            elapsed = time.perf_counter() - t0_box[0]
            # snapshot BEFORE close(): a worker stuck past the join
            # timeout may still append when close() yanks its connection,
            # and aggregation must not iterate a list being mutated
            outcomes = list(outcomes)
            errors = list(errors)
            batch_stats = client.stats() if self.coalesce else None
            cache_stats = self._cache_stats_row(client)
            admission_stats = self._admission_stats(client)
            fed_stats = self._federation_stats(client)
        finally:
            client.close()
        return self._watch_result(self._integrity_result(
            self._federation_result(self._cache_result(
            self._admission_result(self._trace_result(
                header, records, speed, elapsed, outcomes, errors, specs,
                batch_stats, resources, request_slos), admission_stats),
            cache_stats), fed_stats), integrity_before))

    def _make_disagg_client(self):
        """The replay's disaggregated client: a DisaggClient over the
        ``--roles`` urls (role-labeled) plus any role-less ``--endpoints``
        (eligible only for the monolithic fallback path)."""
        from .disagg import DisaggClient
        from .pool import EndpointSpec

        role_by_url = {u: role for role, urls in self.roles.items()
                       for u in urls}
        urls = list(dict.fromkeys(
            [u for role_urls in self.roles.values() for u in role_urls]
            + (self.endpoints or [])))
        specs = [EndpointSpec(u, role=role_by_url.get(u)) for u in urls]
        return DisaggClient(specs, protocol=self.protocol)

    def _make_pipeline_client(self):
        """The replay's DAG executor: a PipelineClient over the replay
        endpoints (its own arena-backed pool, so intermediate handoffs
        ride cached shm registrations exactly like production runs)."""
        from .pipeline import PipelineClient

        urls = list(self.endpoints) if self.endpoints else [self.url]
        return PipelineClient(urls, self.pipeline,
                              protocol=self.protocol)

    def _replay_warmup(self, client, records, resources) -> None:
        """One best-effort dispatch per distinct (kind, model) BEFORE the
        schedule starts: the first request of each model must not bill
        its jit compile / connection setup to an SLO. Warmup sequences
        use a throwaway id (start+end in one step) so no group state is
        left behind; failures are ignored — a genuinely broken model will
        show up measured."""
        done = set()
        for rec in records:
            key = (rec.kind, rec.model)
            if key in done:
                continue
            done.add(key)
            try:
                if rec.kind == "sequence":
                    # same unwrap as _replay_dispatch: a ShardedClient
                    # types-rejects sequence kwargs, and a swallowed
                    # rejection here would silently skip the warmup
                    getattr(client, "inner", client).infer(
                        rec.model, resources.inputs_for(rec),
                        sequence_id=999979,
                        sequence_start=True, sequence_end=True)
                else:
                    self._replay_dispatch(client, rec, resources)
            except Exception:
                pass

    def _replay_worker(self, client, barrier, stop, records, speed, cursor,
                       t0_box, resources, outcomes, errors, on_result):
        from .admission import AdmissionRejected
        from .resilience import CircuitOpenError

        try:
            barrier.wait(timeout=120)
        except threading.BrokenBarrierError:
            return
        lock, idx = cursor
        while not stop.is_set():
            with lock:
                i = idx[0]
                if i >= len(records):
                    return
                idx[0] += 1
            rec = records[i]
            target = t0_box[0] + rec.at_s / speed
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            gate = (resources.seq_gates.get(rec.seq_group)
                    if rec.kind == "sequence" else None)
            ordered = True
            if gate is not None:
                with gate.cond:
                    ordered = gate.cond.wait_for(
                        lambda: gate.next >= rec.seq_index,
                        timeout=self._SEQ_GATE_TIMEOUT_S) and not gate.broken
            # lag includes sequence head-of-line blocking: the arrival was
            # scheduled at ``target`` whether or not its predecessor is done
            lag = max(0.0, time.perf_counter() - target)
            t1 = time.perf_counter()
            status = "ok"
            outcome: Any = None
            try:
                if not ordered:
                    raise RuntimeError(
                        f"sequence group {rec.seq_group} step "
                        f"{rec.seq_index}: predecessor failed or never "
                        f"completed (group abandoned)")
                outcome = self._replay_dispatch(client, rec, resources)
            except (CircuitOpenError, AdmissionRejected) as e:
                status = "shed"
                outcome = e
                errors.append(f"{rec.kind}: {e}")
            except Exception as e:  # measured as failure, replay continues
                # a sharded logical request wraps its per-shard failure in
                # ShardFailed; a breaker-open/admission cause underneath is
                # still a SHED, not an error — same classification contract
                # as the unsharded kinds
                cause = getattr(e, "cause", None)
                status = ("shed" if isinstance(
                    cause, (CircuitOpenError, AdmissionRejected))
                    else "error")
                outcome = e
                errors.append(f"{rec.kind}: {e}")
            finally:
                if gate is not None:
                    with gate.cond:
                        if status != "ok":
                            # ANY failed step (error, shed, or gate
                            # timeout) poisons the group: the server-side
                            # sequence state is now a lie, and sending
                            # later steps into it would either mis-count
                            # as independent errors or mis-accumulate and
                            # inflate the served numbers under exactly
                            # the chaos this harness measures
                            gate.broken = True
                        gate.next = max(gate.next, rec.seq_index + 1)
                        gate.cond.notify_all()
            # shed attribution rides the outcome tuple: the typed
            # rejection's reason and honest retry_after hint (possibly
            # wrapped in a sharded failure's ``cause``)
            shed_exc = (getattr(outcome, "cause", None) or outcome
                        if status == "shed" else None)
            outcomes.append(
                (rec.kind, status, time.perf_counter() - t1, lag,
                 rec.at_s / speed, getattr(rec, "tenant", None),
                 getattr(shed_exc, "reason", None),
                 getattr(shed_exc, "retry_after_s", None)))
            if on_result is not None:
                on_result(rec, outcome)

    def _replay_affinity_kw(self, rec) -> Dict[str, Any]:
        """The replay's session-key kwarg: with ``routing="affinity"``,
        every keyed record (format v3 ``content_key``) routes by its key —
        the trace-driven twin of ``--affinity-key``."""
        if (self.routing == "affinity"
                and getattr(rec, "content_key", None) is not None):
            return {"affinity_key": f"k{rec.content_key}"}
        return {}

    def _replay_tenant_kw(self, rec) -> Dict[str, Any]:
        """The replay's tenant kwarg: a tenant-attributed record (format
        v4) carries its tenant through the whole client stack — admission
        queues/quotas, cache partitions and batch compat keys all judge
        it as that tenant. Tenantless records pass no kwarg at all, so a
        mixed trace exercises both paths."""
        tenant = getattr(rec, "tenant", None)
        if tenant is not None:
            return {"tenant": tenant}
        return {}

    def _replay_dispatch(self, client, rec, resources):
        if rec.kind == "sharded":
            # the measurement client IS the ShardedClient in shard mode
            return client.infer(
                rec.model, resources.inputs_for(rec),
                model_version=rec.version,
                **self._replay_tenant_kw(rec))
        if rec.kind == "prefill_decode":
            # the disagg session runs on its own role-labeled pool; the
            # measurement client plays no part in either leg
            tokens = resources.tokens_for(
                rec.prompt_tokens, getattr(rec, "content_key", None))
            return list(resources.disagg.generate_stream(
                tokens, max_tokens=int(rec.output_tokens)))
        if rec.kind == "pipeline":
            # the DAG runs on its own arena-backed pool; the measurement
            # client plays no part in the stage dispatches
            res = resources.pipeline.run(resources.feeds_for(rec))
            resources.record_pipeline(res)
            return res
        # non-sharded kinds bypass the scatter-gather wrapper (a sharded
        # client types-rejects streams and would scatter plain unaries)
        client = getattr(client, "inner", client)
        if rec.kind == "generate_stream":
            events = []
            for event in client.generate_stream(
                    rec.model, resources.stream_payload(rec),
                    model_version=rec.version,
                    **self._replay_affinity_kw(rec),
                    **self._replay_tenant_kw(rec)):
                events.append(event)
            return events
        inputs = resources.inputs_for(rec)
        if rec.kind == "sequence":
            return client.infer(
                rec.model, inputs,
                model_version=rec.version,
                sequence_id=rec.seq_group,
                sequence_start=rec.seq_index == 0,
                sequence_end=rec.seq_index == rec.seq_len - 1,
                **self._replay_tenant_kw(rec))
        return client.infer(rec.model, inputs, model_version=rec.version,
                            **self._replay_affinity_kw(rec),
                            **self._replay_tenant_kw(rec))

    @staticmethod
    def _kind_row(samples: Dict[Tuple[str, str], List[float]],
                  counts: Dict[Tuple[str, str], int],
                  kind: str) -> Dict[str, Any]:
        return {
            "requests": counts.get((kind, "ok"), 0)
            + counts.get((kind, "error"), 0) + counts.get((kind, "shed"), 0),
            "ok": counts.get((kind, "ok"), 0),
            "errors": counts.get((kind, "error"), 0),
            "shed": counts.get((kind, "shed"), 0),
            "latency_ms": _latency_ms_row(
                sorted(samples.get((kind, "ok"), []))),
        }

    def _trace_result(self, header, records, speed, elapsed, outcomes,
                      errors, specs, batch_stats, resources,
                      request_slos=()) -> Dict[str, Any]:
        kind_counts: Dict[str, int] = {}
        counts: Dict[Tuple[str, str], int] = {}
        samples: Dict[Tuple[str, str], List[float]] = {}
        lags: List[float] = []
        all_ok_lat: List[float] = []
        arrival_window = 0.0
        # per-tenant accounting (format v4 records): status counts, ok
        # latencies and shed-reason breakdown, keyed by tenant label
        tenant_rows: Dict[str, Dict[str, Any]] = {}
        retry_hints: List[float] = []
        for (kind, status, lat_s, lag_s, at_rel_s,
             tenant, shed_reason, retry_after_s) in outcomes:
            kind_counts[kind] = kind_counts.get(kind, 0) + 1
            counts[(kind, status)] = counts.get((kind, status), 0) + 1
            samples.setdefault((kind, status), []).append(lat_s)
            if status == "ok":
                all_ok_lat.append(lat_s)
            if retry_after_s is not None:
                retry_hints.append(float(retry_after_s))
            if tenant is not None:
                row = tenant_rows.setdefault(tenant, {
                    "issued": 0, "ok": 0, "errors": 0, "shed": 0,
                    "shed_by_reason": {}, "_lat": []})
                row["issued"] += 1
                if status == "ok":
                    row["ok"] += 1
                    row["_lat"].append(lat_s)
                elif status == "shed":
                    row["shed"] += 1
                    reason = shed_reason or "unknown"
                    row["shed_by_reason"][reason] = (
                        row["shed_by_reason"].get(reason, 0) + 1)
                else:
                    row["errors"] += 1
            lags.append(lag_s)
            # actual arrival offset (scheduled + slip): the window the
            # schedule was REALLY issued over, free of the service/drain
            # tail that stretches ``elapsed``
            arrival_window = max(arrival_window, at_rel_s + lag_s)
            # request_ms SLOs: exactly ONE event per unary/sequence record
            # (caller-visible latency; errored or shed = bad) — streams
            # report through their own ttft/itl/duration metrics
            if kind != "generate_stream":
                for slo in request_slos:
                    if status == "ok":
                        slo.observe(lat_s * 1e3)
                    else:
                        slo.observe_failure()
        issued = len(outcomes)
        ok = sum(n for (_, status), n in counts.items() if status == "ok")
        shed = sum(n for (_, status), n in counts.items() if status == "shed")
        errored = issued - ok - shed
        trace_duration = records[-1].at_s if records else 0.0
        if trace_duration <= 0.0:
            # an instantaneous burst (every at_s == 0): fall back to the
            # header's declared span so offered_rate isn't a 1e9 absurdity
            # that no delivery criterion could ever satisfy
            trace_duration = float(header.get("duration_s") or 0.0)
        offered_window = max(trace_duration / speed, 1e-3)
        if arrival_window <= 1e-6:
            # matching fallback on the achieved side: an instantaneous
            # burst issued with ~zero slip must not report an arrival
            # rate of 0 (or 1e9) and flunk the delivery criterion
            arrival_window = offered_window
        lag_sorted = sorted(lags)
        lat_sorted = sorted(all_ok_lat)
        delayed = sum(1 for lag in lag_sorted if lag > 1e-3)
        # stream sessions that failed BEFORE a StreamSpan existed (e.g.
        # pool endpoint selection raising with every replica down) would
        # otherwise vanish from the span-fed ttft/duration verdicts:
        # sample=always means one span per session that got as far as the
        # frontend, so any shortfall vs issued stream records is exactly
        # the spanless failures — count each one bad, same rule as every
        # other errored request
        stream_issued = kind_counts.get("generate_stream", 0)
        if stream_issued:
            self._telemetry._fold_stream_pending()
            spans_finished = sum(
                s.value
                for s in self._telemetry.streams_total._series.values())
            for _ in range(int(max(0, stream_issued - spans_finished))):
                for slo in self._telemetry.slos():
                    if slo.metric in ("ttft_ms", "stream_duration_ms"):
                        slo.observe_failure()
        # the SLO verdicts: stream objectives from the per-run Telemetry
        # (exact bounded-window good/bad counts), request_ms objectives
        # from the per-record feed above, error-rate objectives from the
        # replay's own accounting (shed counts against capacity: a shed
        # request was not served inside SLO)
        slo_rows = self._telemetry.slo_report()
        slo_rows.extend(slo.report() for slo in request_slos)
        bad_fraction = (errored + shed) / issued if issued else 0.0
        for spec in specs:
            if spec.kind != "error_rate":
                continue
            slo_rows.append({
                "slo": spec.name,
                "metric": "error_rate",
                "limit": spec.limit,
                "value": round(bad_fraction, 6),
                "attained": bad_fraction <= spec.limit + 1e-12,
            })
        result = {
            "mode": "trace_replay",
            "protocol": self.protocol,
            "speed": speed,
            "trace": {
                "records": len(records),
                "duration_s": round(trace_duration, 3),
                "kinds": kind_counts,
                "generator": header.get("generator"),
                "spec": header.get("spec"),
                "seed": header.get("seed"),
            },
            "requests": ok,
            "issued": issued,
            "errors": errored,
            "shed": shed,
            "error_rate": round(errored / issued, 6) if issued else 0.0,
            "shed_rate": round(shed / issued, 6) if issued else 0.0,
            "error_sample": errors[0] if errors else None,
            "duration_s": round(elapsed, 3),
            "offered_rate": round(len(records) / offered_window, 1),
            "achieved_rate": round(ok / elapsed, 1) if elapsed > 0 else 0.0,
            "achieved_arrival_rate": round(issued / arrival_window, 1)
            if arrival_window > 0 else 0.0,
            "latency_ms": _latency_ms_row(lat_sorted),
            "kinds": {
                kind: self._kind_row(samples, counts, kind)
                for kind in sorted(kind_counts)
            },
            "schedule_lag_ms": _lag_ms_row(lag_sorted),
            "delayed_pct": round(100.0 * delayed / issued, 1)
            if issued else 0.0,
            "sequence_groups": len(resources.seq_gates),
            "slo": slo_rows,
            "slo_ok": all(row["attained"] for row in slo_rows),
        }
        if resources.pipeline_stage_s:
            # only when the trace carried pipeline records: the per-stage
            # latency waterfall across every measured DAG run
            result["pipeline_stages"] = {
                stage: dict(count=len(vals),
                            **_latency_ms_row(sorted(vals)))
                for stage, vals in
                sorted(resources.pipeline_stage_s.items())
            }
        if tenant_rows:
            # only when the trace carried tenant-attributed records:
            # tenantless replays keep byte-identical result rows
            result["tenants"] = {
                t: {
                    "issued": row["issued"],
                    "ok": row["ok"],
                    "errors": row["errors"],
                    "shed": row["shed"],
                    "shed_by_reason": row["shed_by_reason"],
                    "latency_ms": _latency_ms_row(sorted(row["_lat"])),
                }
                for t, row in sorted(tenant_rows.items())
            }
        if retry_hints:
            # the honest backpressure story: every shed's retry_after_s
            # hint (bucket refill eta / limiter minRTT eta), as ms
            result["shed_retry_after_ms"] = _latency_ms_row(
                sorted(retry_hints))
        return self._batch_result(self._observe_result(result), batch_stats)


class _SeqGate:
    """Per-sequence-group ordering: step *k+1* must not hit the wire until
    step *k* completed (the server-side accumulator is ordered state, and
    the pool pins the whole group to one replica). ``broken`` poisons the
    group after a gate timeout: later steps error out instead of being
    sent into state that never saw the missing step."""

    __slots__ = ("cond", "next", "broken")

    def __init__(self):
        self.cond = threading.Condition()
        self.next = 0
        self.broken = False


class _ReplayResources:
    """Shared read-only payload caches for one replay run: one tensor set
    per distinct (model, layout, content key) and one token list per
    distinct (prompt length, content key), all deterministic — keyless
    records draw from the runner's single seeded Generator, keyed records
    (the hot-key workload, format v3) from a per-key generator seeded by
    (runner seed, key) so the SAME key always replays BYTE-IDENTICAL
    bytes, record order be damned. That identity is what the
    cache/singleflight layer collapses on."""

    def __init__(self, runner: "PerfRunner", records) -> None:
        self._mod = runner._client_mod
        self._rng = runner.rng
        self._seed = runner.seed
        self._inputs: Dict[Any, list] = {}
        self._tokens: Dict[Any, list] = {}
        self.seq_gates: Dict[int, _SeqGate] = {}
        # the replay's DisaggClient (set by the runner when the trace
        # carries prefill_decode records; closed by the runner)
        self.disagg = None
        # the replay's PipelineClient + per-stage latency accumulator
        # (set by the runner when the trace carries pipeline records)
        self.pipeline = None
        self.pipeline_stage_s: Dict[str, List[float]] = {}
        self._pipeline_lock = threading.Lock()
        self._feeds: Dict[Any, Dict[str, Any]] = {}
        for rec in records:
            if rec.kind == "pipeline":
                self.feeds_for(rec)
                continue
            if rec.kind == "sequence":
                self.seq_gates.setdefault(rec.seq_group, _SeqGate())
            elif rec.kind in ("generate_stream", "prefill_decode"):
                self.tokens_for(rec.prompt_tokens,
                                getattr(rec, "content_key", None))
            if rec.shapes is not None:
                self.inputs_for(rec)

    def _rng_for(self, content_key):
        if content_key is None:
            return self._rng
        from .trace import _key_rng

        return _key_rng(self._seed, content_key)

    def inputs_for(self, rec) -> list:
        content_key = getattr(rec, "content_key", None)
        key = (rec.model, content_key,
               tuple(sorted((name, rec.dtypes[name], tuple(shape))
                            for name, shape in rec.shapes.items())))
        inputs = self._inputs.get(key)
        if inputs is None:
            rng = self._rng_for(content_key)
            inputs = []
            for name in sorted(rec.shapes):
                datatype = rec.dtypes[name]
                shape = list(rec.shapes[name])
                inp = self._mod.InferInput(name, shape, datatype)
                inp.set_data_from_numpy(
                    _random_tensor(datatype, shape, rng))
                inputs.append(inp)
            self._inputs[key] = inputs
        return inputs

    def feeds_for(self, rec) -> Dict[str, Any]:
        """One deterministic ndarray feed dict per distinct pipeline
        record layout (PipelineClient.run() takes host arrays, not
        InferInputs — the client owns the wire staging)."""
        key = (rec.model,
               tuple(sorted((name, rec.dtypes[name], tuple(shape))
                            for name, shape in rec.shapes.items())))
        feeds = self._feeds.get(key)
        if feeds is None:
            feeds = {
                name: _random_tensor(rec.dtypes[name],
                                     list(rec.shapes[name]), self._rng)
                for name in sorted(rec.shapes)}
            self._feeds[key] = feeds
        return feeds

    def record_pipeline(self, result) -> None:
        with self._pipeline_lock:
            for stage, lat_s in result.stage_latency_s.items():
                self.pipeline_stage_s.setdefault(stage, []).append(lat_s)

    def tokens_for(self, prompt_tokens: int, content_key=None) -> list:
        key = (prompt_tokens, content_key)
        tokens = self._tokens.get(key)
        if tokens is None:
            tokens = self._rng_for(content_key).integers(
                0, 256, size=max(1, prompt_tokens), dtype=np.int32).tolist()
            self._tokens[key] = tokens
        return tokens

    def stream_payload(self, rec) -> Dict[str, Any]:
        return {"TOKENS": [self.tokens_for(
                    rec.prompt_tokens, getattr(rec, "content_key", None))],
                "MAX_TOKENS": int(rec.output_tokens)}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="client_tpu.perf", description="KServe v2 load generator (perf_analyzer equivalent)"
    )
    parser.add_argument("-m", "--model-name", required=True)
    parser.add_argument("-u", "--url", default="127.0.0.1:8000")
    parser.add_argument(
        "-i", "--protocol",
        choices=("http", "grpc", "native", "native-grpc", "native-grpc-async"),
        default="http",
        help="native = the C++ client via its C API (HTTP transport)",
    )
    parser.add_argument(
        "--shared-memory", choices=("none", "system", "tpu"), default="none"
    )
    parser.add_argument(
        "--concurrency-range", default="1",
        help="start[:end[:step]] concurrency sweep (e.g. 1:8:2)",
    )
    parser.add_argument(
        "--request-rate-range", default=None,
        help="start[:end[:step]] open-loop arrival rate sweep in req/s "
             "(overrides --concurrency-range; perf_analyzer semantics)",
    )
    parser.add_argument(
        "--request-distribution", choices=("constant", "poisson"),
        default="constant",
        help="arrival process for --request-rate-range",
    )
    parser.add_argument(
        "--rate-pool-size", type=int, default=16,
        help="worker pool servicing the open-loop schedule",
    )
    parser.add_argument("--measurement-requests", type=int, default=200)
    parser.add_argument("-b", "--batch-size", type=int, default=0)
    parser.add_argument(
        "--shape", action="append", default=[],
        help="override an input shape: NAME:d1,d2,...",
    )
    parser.add_argument("-f", "--format", choices=("table", "json"), default="table")
    parser.add_argument("--warmup-requests", type=int, default=10)
    parser.add_argument(
        "--retries", type=int, default=0,
        help="arm a resilience RetryPolicy with N re-attempts on every "
             "measurement client (benchmarks the policy-path overhead)",
    )
    parser.add_argument(
        "--chaos", default=None,
        help="route measurement traffic through the in-process fault "
             "proxy: none|latency:S|reset:N|stall:N|flap:K|blackhole "
             "(none = clean proxy, for topology-identical baselines)",
    )
    parser.add_argument(
        "--endpoints", default=None,
        help="comma-separated replica urls: measurement clients become "
             "health-aware PoolClients over them (-u stays the "
             "control-plane address; see client_tpu.pool)",
    )
    parser.add_argument(
        "--hedge", action="store_true",
        help="arm hedged requests on the pool (requires --endpoints)",
    )
    parser.add_argument(
        "--hedge-delay", type=float, default=None,
        help="hedge delay in seconds (default: rolling p95 of recent "
             "latencies)",
    )
    parser.add_argument(
        "--observe", action="store_true",
        help="enable client telemetry (observe.Telemetry, sample=always) "
             "during measurement and append a client-phase p50/p99 "
             "breakdown (serialize/ttfb/recv/deserialize) to each result; "
             "with --generate-stream, also a ttft/itl breakdown "
             "(client_stream_ms)",
    )
    parser.add_argument(
        "--flight", action="store_true",
        help="attach a flight recorder (client_tpu.flight) to every "
             "measurement run and append a client_flight row "
             "(events/request, retained fraction by verdict, commit "
             "p50/p99 cost) to each result",
    )
    parser.add_argument(
        "--validate", action="store_true",
        help="append a client_integrity row to each result: this run's "
             "contract-validation delta (results checked, checks, "
             "violations by kind) plus the measured per-response "
             "validation overhead (ns p50/p99) — the A/A arm of "
             "tools/bench_integrity.py reads exactly this block",
    )
    parser.add_argument(
        "--watch", action="store_true",
        help="arm a continuous Watchtower (client_tpu.watch: multi-"
             "window SLO burn, watermark gauges, changepoint detectors) "
             "on each measurement run and append a client_watch block "
             "(alerts fired/resolved by kind, tick overhead p50/p99, "
             "changepoint trips) to every result row — closed-loop, "
             "open-loop and trace replay alike",
    )
    parser.add_argument(
        "--generate-stream", action="store_true",
        help="measure streamed generations instead of unary infers: each "
             "request drives one generate-extension SSE session to "
             "exhaustion (http protocol only; latency_ms = session e2e)",
    )
    parser.add_argument(
        "--coalesce", action="store_true",
        help="wrap measurement clients in the micro-batching dispatcher "
             "(client_tpu.batch): concurrent workers share coalesced wire "
             "requests; result rows gain achieved batch-size p50/p99",
    )
    parser.add_argument(
        "--batch-window-us", type=float, default=None,
        help="fixed coalescing window in microseconds (default: adaptive, "
             "tuned from the observed arrival rate)",
    )
    parser.add_argument(
        "--batch-max", type=int, default=32,
        help="row cap per coalesced request (size to the model's "
             "max_batch_size)",
    )
    parser.add_argument(
        "--routing", default=None,
        choices=("round_robin", "least_outstanding", "weighted",
                 "orca_weighted", "affinity"),
        help="pool routing policy (requires --endpoints); orca_weighted "
             "feeds smooth-WRR weights from the servers' ORCA "
             "endpoint-load-metrics reports, falling back to "
             "least_outstanding while loads are stale or absent; "
             "affinity rendezvous-hashes a session/prefix key "
             "(--affinity-key, or a trace record's content_key) onto a "
             "home replica with deterministic bounded-load fallback")
    parser.add_argument(
        "--cache", action="store_true",
        help="wrap measurement clients in the bounded response cache "
             "(client_tpu.cache): repeated content keys are served "
             "client-side as zero-copy arena views; result rows gain "
             "client_cache (hit rate, collapse ratio, resident bytes)")
    parser.add_argument(
        "--cache-ttl", type=float, default=30.0,
        help="response-cache TTL in seconds (with --cache)")
    parser.add_argument(
        "--singleflight", action="store_true",
        help="collapse concurrent identical infers onto one wire request "
             "(client_tpu.cache; combine with --cache for the full "
             "hot-key layer)")
    parser.add_argument(
        "--affinity-key", default=None,
        help="session key for --routing affinity on the closed/open-loop "
             "paths: 'worker' = one key per worker thread, anything else "
             "= one shared literal key; trace replay instead threads "
             "each record's content_key automatically")
    parser.add_argument(
        "--admission", action="store_true",
        help="arm the pool's adaptive admission controller "
             "(client_tpu.admission): saturated/deadline-infeasible "
             "requests are shed with a typed AdmissionRejected, counted "
             "as shed (never error) in every result row")
    parser.add_argument(
        "--admission-mode", choices=("aimd", "gradient"), default="aimd")
    parser.add_argument(
        "--admission-target-ms", type=float, default=None,
        help="SLO latency target the limiter defends (default: a minRTT "
             "EWMA tolerance band)")
    parser.add_argument(
        "--tenancy", default=None,
        help="per-tenant QoS spec for the admission controller "
             "(client_tpu.tenancy; requires --admission), e.g. "
             "'t0,rate=50,weight=2;adv0,rate=50': weighted-fair "
             "queueing + token-bucket quotas; over-quota requests shed "
             "typed over_quota with an honest retry_after. Trace replay "
             "threads each record's tenant (format v4) automatically")
    parser.add_argument(
        "--endpoint-limits", action="store_true",
        help="arm a per-endpoint adaptive concurrency limit (selection "
             "skips replicas at their limit; requires --endpoints)")
    parser.add_argument(
        "--shard-layout", default=None,
        help="scatter-gather every infer across --endpoints per this "
             "layout spec, e.g. 'TOKENS=0->LOGITS=0,NEXT_TOKEN=0' "
             "(tensor=axis pairs, 'r' = replicated, inputs->outputs; "
             "shard i pins to the i-th --endpoints url; rejects --hedge/"
             "--coalesce; also required to replay 'sharded' trace "
             "records — see client_tpu.shard / docs/sharding.md)")
    parser.add_argument(
        "--stream-prompt-tokens", type=int, default=32,
        help="prompt length for --generate-stream sessions")
    parser.add_argument(
        "--stream-output-tokens", type=int, default=16,
        help="generated tokens per --generate-stream session")
    parser.add_argument(
        "--cells", default=None, metavar="SPEC",
        help="multi-cell federation: 'a=u1+u2;b=u3' builds a "
             "FederatedClient over named cells, each its own PoolClient "
             "(routing/admission/endpoint-limit flags apply per cell); "
             "locality-first with transparent spillover "
             "(client_tpu.federation); result rows gain "
             "client_federation")
    parser.add_argument(
        "--roles", default=None, metavar="SPEC",
        help="role-labeled endpoints for disaggregated prefill/decode "
             "replay: 'prefill=u1+u2;decode=u3' builds a DisaggClient "
             "over them so 'prefill_decode' trace records (format v5) "
             "replay as two-leg sessions (client_tpu.disagg; see "
             "docs/disaggregation.md)")
    parser.add_argument(
        "--pipeline", default=None, metavar="SPEC",
        help="model-DAG spec for replaying 'pipeline' trace records "
             "(format v6) as client-orchestrated graphs with "
             "arena-resident intermediates: 'chain' (the zoo's "
             "tokenize->embed->rerank chain) or an inline graph spec "
             "(client_tpu.pipeline; see docs/pipelines.md); result rows "
             "gain per-stage latency columns under 'pipeline_stages'")
    parser.add_argument(
        "--home-cell", default=None,
        help="the locality-preferred cell (default: first in --cells)")
    parser.add_argument(
        "--shadow-cell", default=None,
        help="mirror a sampled fraction of successful infers to this "
             "cell (responses compared+counted, never returned)")
    parser.add_argument(
        "--shadow-ratio", type=float, default=0.05,
        help="sampled mirror fraction for --shadow-cell")
    parser.add_argument(
        "--canary-cell", default=None,
        help="weighted canary split to this cell with SLO-burn "
             "auto-rollback")
    parser.add_argument(
        "--canary-weight", type=float, default=0.1,
        help="canary traffic weight in [0,1]")
    parser.add_argument(
        "--canary-slo", default=None,
        help="canary burn objective, e.g. 'p95<100ms' "
             "(default p95<250ms)")
    parser.add_argument(
        "--canary-min-events", type=int, default=20,
        help="canary outcomes required before a burn may roll back")
    parser.add_argument(
        "--seed", type=int, default=0,
        help="seed for EVERY stochastic path: generated tensors, the "
             "open-loop poisson schedule, and --trace-gen traces all draw "
             "from one numpy Generator — same seed, same spec => same run")
    parser.add_argument(
        "--trace", default=None,
        help="replay a JSONL workload trace (client_tpu.trace format): "
             "arrivals are scheduled open-loop at at_s/--speed; unary, "
             "generate_stream and sequence records run concurrently")
    parser.add_argument(
        "--trace-gen", default=None,
        help="generate-and-replay a trace from a spec, e.g. "
             "'mixed:duration_s=10,rate=50,stream_fraction=0.2,"
             "seq_fraction=0.1' (generators: poisson_burst, heavy_tail, "
             "mixed; seeded by --seed)")
    parser.add_argument(
        "--speed", type=float, default=1.0,
        help="trace replay speed multiplier (2.0 = twice the offered rate)")
    parser.add_argument(
        "--replay-workers", type=int, default=32,
        help="worker pool servicing the trace replay schedule")
    parser.add_argument(
        "--slo", action="append", default=[],
        help="declare an SLO for the replay verdict (repeatable): "
             "ttft_p95<200ms, p99<50ms, itl_p99<20ms, error_rate<0.1%%")
    args = parser.parse_args(argv)

    if args.trace and args.trace_gen:
        parser.error("--trace and --trace-gen are mutually exclusive")

    parts = [int(x) for x in args.concurrency_range.split(":")]
    start = parts[0]
    end = parts[1] if len(parts) > 1 else start
    step = parts[2] if len(parts) > 2 else 1
    shape_overrides = {}
    for s in args.shape:
        name, _, dims = s.partition(":")
        shape_overrides[name] = [int(d) for d in dims.split(",")]

    runner = PerfRunner(
        args.url, args.protocol, args.model_name, args.shared_memory,
        shape_overrides, args.batch_size, seed=args.seed,
        retries=args.retries, chaos=args.chaos,
        endpoints=[u.strip() for u in args.endpoints.split(",") if u.strip()]
        if args.endpoints else None,
        hedge=args.hedge, hedge_delay_s=args.hedge_delay,
        observe=args.observe,
        generate_stream=args.generate_stream,
        stream_prompt_tokens=args.stream_prompt_tokens,
        stream_output_tokens=args.stream_output_tokens,
        coalesce=args.coalesce,
        batch_window_us=args.batch_window_us,
        batch_max=args.batch_max,
        routing=args.routing,
        admission=args.admission,
        admission_mode=args.admission_mode,
        admission_target_ms=args.admission_target_ms,
        tenancy=args.tenancy,
        endpoint_limits=args.endpoint_limits,
        shard_layout=args.shard_layout,
        cache=args.cache,
        cache_ttl_s=args.cache_ttl,
        singleflight=args.singleflight,
        affinity_key=args.affinity_key,
        flight=args.flight,
        cells=args.cells,
        home_cell=args.home_cell,
        shadow_cell=args.shadow_cell,
        shadow_ratio=args.shadow_ratio,
        canary_cell=args.canary_cell,
        canary_weight=args.canary_weight,
        canary_slo=args.canary_slo,
        canary_min_events=args.canary_min_events,
        roles=args.roles,
        pipeline=args.pipeline,
        validate=args.validate,
        watch=args.watch,
    )
    try:
        # trace mode does its own per-(kind, model) warmup inside
        # run_trace — a closed-loop warmup against --model-name here would
        # hit an unrelated model (or fail outright when the server only
        # serves the trace's models)
        if args.warmup_requests and not (args.trace or args.trace_gen):
            runner.run(1, args.warmup_requests)

        results = []
        if args.trace or args.trace_gen:
            from . import trace as trace_mod

            if args.trace:
                tr = trace_mod.load_trace(args.trace)
            else:
                tr = trace_mod.generate(args.trace_gen, seed=args.seed)
            results.append(runner.run_trace(
                tr, speed=args.speed, replay_workers=args.replay_workers,
                slos=args.slo))
        elif args.request_rate_range is not None:
            rparts = [float(x) for x in args.request_rate_range.split(":")]
            rstart = rparts[0]
            rend = rparts[1] if len(rparts) > 1 else rstart
            rstep = rparts[2] if len(rparts) > 2 else 1.0
            if rstep <= 0:
                # match the closed-loop path, where range() rejects step=0
                raise ValueError("--request-rate-range step must be > 0")
            rate = rstart
            while rate <= rend + 1e-9:
                results.append(runner.run_rate(
                    rate, args.measurement_requests,
                    distribution=args.request_distribution,
                    pool_size=args.rate_pool_size))
                rate += rstep
        else:
            for concurrency in range(start, end + 1, step):
                results.append(runner.run(concurrency, args.measurement_requests))
    finally:
        runner.close()

    if args.format == "json":
        print(json.dumps(results))
    elif args.trace or args.trace_gen:
        for r in results:
            t = r["trace"]
            print(
                f"trace replay: {t['records']} records over "
                f"{t['duration_s']}s at speed {r['speed']} "
                f"(kinds: {t['kinds']})")
            print(
                f"offered={r['offered_rate']}/s achieved="
                f"{r['achieved_rate']}/s errors={r['errors']} "
                f"shed={r['shed']} lag_p99="
                f"{r['schedule_lag_ms']['p99']}ms "
                f"lag_max={r['schedule_lag_ms']['max']}ms "
                f"late%={r['delayed_pct']}")
            print(f"{'kind':>16} {'req':>6} {'ok':>6} {'err':>5} "
                  f"{'shed':>5} {'p50 ms':>8} {'p99 ms':>8}")
            for kind, row in r["kinds"].items():
                lm = row["latency_ms"]
                print(f"{kind:>16} {row['requests']:>6} {row['ok']:>6} "
                      f"{row['errors']:>5} {row['shed']:>5} "
                      f"{lm['p50']:>8} {lm['p99']:>8}")
            stream = r.get("client_stream_ms")
            if stream:
                for metric, row in stream.items():
                    print(f"  {metric}: p50={row['p50']} p99={row['p99']}")
            for row in r["slo"]:
                verdict = "OK " if row["attained"] else "MISS"
                if row["metric"] == "error_rate":
                    print(f"  SLO {verdict} {row['slo']}: "
                          f"value={row['value']} limit={row['limit']}")
                else:
                    print(f"  SLO {verdict} {row['slo']}: good={row['good']} "
                          f"bad={row['bad']} burn={row['burn_rate']}")
            print(f"slo_ok={r['slo_ok']}")
    elif args.request_rate_range is not None:
        print(
            f"model={args.model_name} protocol={args.protocol} "
            f"shared_memory={args.shared_memory} "
            f"distribution={args.request_distribution}"
        )
        print(f"{'rate':>7} {'ach':>7} {'p50 ms':>8} {'p90 ms':>8} {'p99 ms':>8} "
              f"{'lag p99':>8} {'late%':>6} {'err':>4} {'shed':>5}")
        for r in results:
            lm = r["latency_ms"]
            print(
                f"{r['request_rate']:>7} {r['achieved_rate']:>7} {lm['p50']:>8} "
                f"{lm['p90']:>8} {lm['p99']:>8} "
                f"{r['schedule_lag_ms']['p99']:>8} {r['delayed_pct']:>6} "
                f"{r['errors']:>4} {r['shed']:>5}"
            )
    else:
        print(
            f"model={args.model_name} protocol={args.protocol} "
            f"shared_memory={args.shared_memory}"
        )
        print(f"{'conc':>5} {'infer/s':>9} {'avg ms':>8} {'p50 ms':>8} {'p90 ms':>8} {'p99 ms':>8} {'err':>4} {'shed':>5}")
        for r in results:
            lm = r["latency_ms"]
            print(
                f"{r['concurrency']:>5} {r['infer_per_sec']:>9} {lm['avg']:>8} "
                f"{lm['p50']:>8} {lm['p90']:>8} {lm['p99']:>8} {r['errors']:>4} "
                f"{r.get('shed', 0):>5}"
            )
    return 1 if any(r["errors"] and not r["requests"] for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
