"""Deprecated alias for ``tritonclient.utils.cuda_shared_memory`` — which is
unavailable on the TPU stack and raises with migration guidance."""

import warnings

warnings.simplefilter("always", DeprecationWarning)
warnings.warn(
    "The package `tritonshmutils.cuda_shared_memory` is deprecated and will "
    "be removed in a future version. Please use instead "
    "`tritonclient.utils.tpu_shared_memory`",
    DeprecationWarning,
)

import tritonclient.utils.cuda_shared_memory  # noqa: E402,F401  (raises)
