"""Deprecated alias for :mod:`tritonclient.utils.tpu_shared_memory`.

The TPU analog of the reference's ``tritonshmutils/cuda_shared_memory.py``.
"""

import warnings

warnings.simplefilter("always", DeprecationWarning)
warnings.warn(
    "The package `tritonshmutils.tpu_shared_memory` is deprecated and will "
    "be removed in a future version. Please use instead "
    "`tritonclient.utils.tpu_shared_memory`",
    DeprecationWarning,
)

from tritonclient.utils.tpu_shared_memory import *  # noqa: E402,F401,F403
