"""Deprecated alias package for the shared-memory utils.

Parity with the reference's ``tritonshmutils`` shim wheel
(reference: src/python/library/tritonshmutils/__init__.py): submodules
``shared_memory`` and ``tpu_shared_memory`` re-export the live modules
(``cuda_shared_memory`` exists but raises, as on the whole TPU stack).
"""

import warnings

warnings.simplefilter("always", DeprecationWarning)
warnings.warn(
    "The package `tritonshmutils` is deprecated and will be removed in a "
    "future version. Please use instead `tritonclient.utils`",
    DeprecationWarning,
)
