"""Deprecated alias for :mod:`tritonclient.utils.shared_memory`."""

import warnings

warnings.simplefilter("always", DeprecationWarning)
warnings.warn(
    "The package `tritonshmutils.shared_memory` is deprecated and will be "
    "removed in a future version. Please use instead "
    "`tritonclient.utils.shared_memory`",
    DeprecationWarning,
)

from tritonclient.utils.shared_memory import *  # noqa: E402,F401,F403
from tritonclient.utils.shared_memory import (  # noqa: E402,F401
    create_shared_memory_region,
    destroy_shared_memory_region,
    get_contents_as_numpy,
    mapped_shared_memory_regions,
    set_shared_memory_region,
)
