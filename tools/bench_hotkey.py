"""Generate BENCH_HOTKEY.json: hot-key serving under a zipfian workload.

The claim to prove: on a seeded zipfian trace, the client-side hot-key
layer (``client_tpu.cache``: singleflight + bounded response cache) makes
a hot key cost the fleet ~one request instead of N. Three measurements:

1. **Capacity** — bisect the max sustainable replay speed of ONE seeded
   zipfian unary trace (``hot_key_universe`` keys, zipf alpha 1.1; every
   record's payload is a pure function of its key, so equal keys are
   byte-identical requests) for two arms against a live in-process
   server: ``uncached`` (bare client) and ``cached`` (cache +
   singleflight armed). Same trace, same SLOs — the capacity ratio is
   the fleet-level win. The cached arm's row carries ``client_cache``
   (hit rate, collapse ratio, wire vs logical requests).

2. **Matched-rate latency** — both arms replayed at the UNCACHED arm's
   max sustainable speed: the p50 ratio at equal offered load (the
   "same SLOs, same load" p50 improvement headline).

3. **Miss-path overhead (A/B)** — a near-unique-key twin of the trace
   (uniform over a huge universe: almost every lookup misses) replayed
   through both arms at a modest fixed speed, plus an uncached A/A rerun
   establishing the noise floor. The cached arm's miss-path p50 penalty
   must sit inside that floor: the layer is pay-for-what-you-use.

``--check`` re-validates the committed artifact's invariants (CI'd by
``tests/test_hotkey_cache.py::test_bench_hotkey_artifact_claims``);
``tools/capacity_gate.py --hotkey`` re-runs the cached arm live against
the committed floor.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_hotkey.py [-o BENCH_HOTKEY.json]
    JAX_PLATFORMS=cpu python tools/bench_hotkey.py --check
"""

from __future__ import annotations

import argparse
import contextlib
import json
import platform
import sys
import time
from pathlib import Path
from typing import Any, Dict

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# zipfian hot-key workload: unary-only (the cache layer's target shape),
# 64-key universe at alpha 1.1 — the measured shape of production request
# distributions; payloads are per-key deterministic so equal keys are
# byte-identical wire requests
TRACE_SPEC = ("mixed:duration_s=4,rate=250,stream_fraction=0,"
              "seq_fraction=0,unary_model=batched_matmul,"
              "hot_key_universe=64,hot_key_alpha=1.1,"
              "burst_factor=3,period_s=1.0,duty=0.3")
# the miss-path twin: uniform draw over a universe far larger than the
# record count — almost every lookup is a cold miss, so the cached arm
# pays full lookup+insert machinery with ~no hits to show for it
UNIQUE_SPEC = ("mixed:duration_s=4,rate=100,stream_fraction=0,"
               "seq_fraction=0,unary_model=batched_matmul,"
               "hot_key_universe=65536,hot_key_alpha=0.0")
TRACE_SEED = 2026
SLOS = ["p95<200ms", "error_rate<1%"]
OVERHEAD_SPEED = 1.0
CACHE_TTL_S = 120.0  # longer than any probe: TTL never interferes


@contextlib.contextmanager
def arm_runner(name: str):
    """One arm — a fresh in-process server, warmed model, a PerfRunner
    with (or without) the hot-key layer armed. Shared by the capacity
    search and tools/capacity_gate.py --hotkey, so each arm has exactly
    one definition. Yields ``(runner, feature_description)``."""
    import numpy as np

    from client_tpu.http import InferenceServerClient, InferInput
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    if name not in ("uncached", "cached"):
        raise ValueError(f"unknown arm {name!r}")
    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    runner = None
    try:
        with InferenceServerClient(server.url) as client:
            x = InferInput("X", [1, 64], "FP32")
            x.set_data_from_numpy(np.zeros((1, 64), dtype=np.float32))
            client.infer("batched_matmul", [x])  # jit warm
        kwargs: Dict[str, Any] = {}
        feature = "bare client (every request pays the wire)"
        if name == "cached":
            kwargs.update(cache=True, singleflight=True,
                          cache_ttl_s=CACHE_TTL_S)
            feature = ("singleflight + bounded response cache "
                       "(client_tpu.cache): hot keys served client-side "
                       "as zero-copy arena views")
        runner = PerfRunner(server.url, "http", "batched_matmul",
                            shape_overrides={"X": [1, 64]}, **kwargs)
        yield runner, feature
    finally:
        if runner is not None:
            runner.close()
        server.stop()


def _search(runner, tr, speed_lo, speed_hi, iters, replay_workers):
    from tools.bench_capacity import bisect_capacity, sustainable

    def evaluate(speed):
        row = runner.run_trace(tr, speed=round(speed, 3),
                               replay_workers=replay_workers, slos=SLOS)
        row["delivery_ratio"] = round(
            row["achieved_arrival_rate"] / row["offered_rate"], 3) \
            if row["offered_rate"] else 1.0
        row["sustainable"] = sustainable(row)
        cc = row.get("client_cache")
        print(f"  speed={row['speed']} offered={row['offered_rate']}/s "
              f"p50={row['latency_ms']['p50']}ms errors={row['errors']} "
              f"slo_ok={row['slo_ok']} sustainable={row['sustainable']}"
              + (f" hit_rate={cc['hit_rate']} wire={cc['wire_requests']}"
                 f"/{cc['logical_requests']}" if cc else ""),
              flush=True)
        return row["sustainable"], row

    _, rows = bisect_capacity(evaluate, speed_lo, speed_hi, iters)
    # confirmation pass (same discipline as bench_capacity): the committed
    # number must be reproducible, not a lucky probe
    candidates = sorted({r["speed"] for r in rows if r["sustainable"]},
                        reverse=True)
    best_row = None
    for speed in candidates:
        ok, row = evaluate(speed)
        row["confirmation"] = True
        rows.append(row)
        if ok:
            best_row = row
            break
    return {
        "max_speed": best_row["speed"] if best_row else 0.0,
        "max_sustainable_qps": best_row["offered_rate"] if best_row else 0.0,
        "achieved_qps_at_max": best_row["achieved_rate"] if best_row else 0.0,
        "p50_ms_at_max": (best_row["latency_ms"]["p50"]
                          if best_row else None),
        "client_cache": (best_row or {}).get("client_cache"),
        "rows": rows,
    }


def _matched_rate(doc, tr, replay_workers) -> Dict[str, Any]:
    """Both arms at the SAME offered rate (the uncached arm's max): the
    honest equal-load p50 comparison."""
    speed = doc["arms"]["uncached"]["max_speed"]
    if speed <= 0:
        return {"skipped": "uncached arm found no sustainable speed"}
    out: Dict[str, Any] = {"speed": speed}
    for name in ("uncached", "cached"):
        with arm_runner(name) as (runner, _):
            row = runner.run_trace(tr, speed=speed,
                                   replay_workers=replay_workers, slos=SLOS)
        out[name] = {
            "p50_ms": row["latency_ms"]["p50"],
            "p99_ms": row["latency_ms"]["p99"],
            "errors": row["errors"],
            "slo_ok": row["slo_ok"],
            "client_cache": row.get("client_cache"),
        }
        print(f"matched-rate {name}: p50={row['latency_ms']['p50']}ms "
              f"slo_ok={row['slo_ok']}", flush=True)
    up, cp = out["uncached"]["p50_ms"], out["cached"]["p50_ms"]
    out["p50_speedup"] = round(up / cp, 2) if cp else None
    return out


OVERHEAD_WORKERS = 8


def _overhead(unique_tr, replay_workers=OVERHEAD_WORKERS,
              reps: int = 3) -> Dict[str, Any]:
    """Miss-path A/B on the near-unique-key twin: ``reps`` replays per
    arm, medians compared, with the noise floor established from the
    UNCACHED arm's own run-to-run p50 spread (a single A/A pair
    understates it on a shared-core box). A small worker pool on
    purpose: the row measures per-request miss-path cost, and a large
    idle pool only adds GIL-scheduling jitter to both arms."""

    def run_arm(arm: str):
        p50s = []
        hit_rate = None
        for _ in range(reps):
            with arm_runner(arm) as (runner, _):
                row = runner.run_trace(unique_tr, speed=OVERHEAD_SPEED,
                                       replay_workers=replay_workers,
                                       slos=SLOS)
            p50s.append(row["latency_ms"]["p50"])
            cc = row.get("client_cache")
            if cc is not None:
                hit_rate = cc.get("hit_rate") or 0.0
            print(f"overhead {arm}: p50={row['latency_ms']['p50']}ms",
                  flush=True)
        return sorted(p50s), hit_rate

    uncached_p50s, _ = run_arm("uncached")
    cached_p50s, hit_rate = run_arm("cached")
    median = lambda xs: xs[len(xs) // 2]  # noqa: E731
    noise_ms = round(uncached_p50s[-1] - uncached_p50s[0], 3)
    delta_ms = round(median(cached_p50s) - median(uncached_p50s), 3)
    return {
        "speed": OVERHEAD_SPEED,
        "replay_workers": replay_workers,
        "reps": reps,
        "p50_ms": {"uncached": uncached_p50s, "cached_misses": cached_p50s},
        "miss_path_hit_rate": hit_rate,
        "noise_floor_ms": noise_ms,
        "miss_path_delta_ms": delta_ms,
        # within noise: the cached arm's miss path costs no more than the
        # run-to-run jitter of the bare client (negative = it was faster)
        "within_noise": delta_ms <= noise_ms + 0.05,
    }


def check(doc: Dict[str, Any]) -> int:
    """Validate the committed artifact's claims; prints each verdict and
    returns the number of violations."""
    failures = 0

    def claim(name: str, ok: bool, detail: str) -> None:
        nonlocal failures
        print(f"  [{'ok' if ok else 'FAIL'}] {name}: {detail}")
        if not ok:
            failures += 1

    cached = doc["arms"]["cached"]
    uncached = doc["arms"]["uncached"]
    cc = cached.get("client_cache") or {}
    claim("collapse",
          bool(cc) and cc["wire_requests"] < cc["logical_requests"],
          f"wire {cc.get('wire_requests')} < logical "
          f"{cc.get('logical_requests')} "
          f"(collapse_ratio {cc.get('collapse_ratio')})")
    claim("hit_rate", (cc.get("hit_rate") or 0.0) >= 0.3,
          f"hit_rate {cc.get('hit_rate')} >= 0.3")
    qps_ratio = (cached["max_sustainable_qps"]
                 / uncached["max_sustainable_qps"]
                 if uncached["max_sustainable_qps"] else None)
    p50_speedup = (doc.get("matched_rate") or {}).get("p50_speedup")
    claim("2x_win",
          (qps_ratio is not None and qps_ratio >= 2.0)
          or (p50_speedup is not None and p50_speedup >= 2.0),
          f"capacity ratio {None if qps_ratio is None else round(qps_ratio, 2)}"
          f" or matched-rate p50 speedup {p50_speedup} >= 2.0")
    overhead = doc.get("overhead") or {}
    claim("miss_path_overhead", bool(overhead.get("within_noise")),
          f"miss-path p50 delta {overhead.get('miss_path_delta_ms')}ms "
          f"inside noise floor {overhead.get('noise_floor_ms')}ms")
    miss_hit_rate = overhead.get("miss_path_hit_rate")
    claim("miss_path_is_cold",
          miss_hit_rate is not None and miss_hit_rate <= 0.2,
          f"unique-key twin hit rate "
          f"{overhead.get('miss_path_hit_rate')} <= 0.2 (the A/B row "
          "measures the miss path, not hidden hits)")
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_HOTKEY.json")
    parser.add_argument("--speed-lo", type=float, default=0.5)
    parser.add_argument("--speed-hi", type=float, default=8.0)
    parser.add_argument(
        "--cached-speed-hi", type=float, default=64.0,
        help="separate bisection ceiling for the cached arm (hits are "
             "~50x cheaper than wire requests; one shared ceiling would "
             "clip the cached arm's real capacity). High enough that the "
             "ceiling probe FAILS (scheduler-bound delivery), so the "
             "bisection brackets the real limit with a ladder of "
             "confirmable candidates instead of one flaky top probe")
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--replay-workers", type=int, default=32)
    parser.add_argument("--check", action="store_true",
                        help="validate the committed artifact's claims "
                             "instead of re-measuring")
    args = parser.parse_args(argv)

    if args.check:
        doc = json.loads(Path(args.output).read_text())
        failures = check(doc)
        print("OK" if failures == 0 else f"{failures} claim(s) failed")
        return 1 if failures else 0

    from client_tpu import trace as trace_mod

    tr = trace_mod.generate(TRACE_SPEC, seed=TRACE_SEED)
    unique_tr = trace_mod.generate(UNIQUE_SPEC, seed=TRACE_SEED)
    out: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "hot-key serving on a seeded zipfian trace: capacity "
            "bisection per arm (uncached vs singleflight+cache), a "
            "matched-rate p50 comparison at the uncached arm's max "
            "sustainable speed, and a miss-path A/B overhead row on a "
            "near-unique-key twin vs the uncached A/A noise floor"
        ),
        "trace": {
            "spec": TRACE_SPEC,
            "seed": TRACE_SEED,
            "records": len(tr.records),
            "duration_s": tr.duration_s,
            "hot_keys": len({r.content_key for r in tr.records}),
        },
        "unique_trace": {
            "spec": UNIQUE_SPEC,
            "seed": TRACE_SEED,
            "records": len(unique_tr.records),
        },
        "slos": list(SLOS),
        "search": {
            "speed_lo": args.speed_lo,
            "speed_hi": args.speed_hi,
            "cached_speed_hi": args.cached_speed_hi,
            "iters": args.iters,
            "replay_workers": args.replay_workers,
            "cache_ttl_s": CACHE_TTL_S,
        },
        "arms": {},
    }
    for name in ("uncached", "cached"):
        hi = args.cached_speed_hi if name == "cached" else args.speed_hi
        with arm_runner(name) as (runner, feature):
            print(f"arm {name}: {feature}", flush=True)
            arm = _search(runner, tr, args.speed_lo, hi,
                          args.iters, args.replay_workers)
            arm["feature"] = feature
        out["arms"][name] = arm
    out["matched_rate"] = _matched_rate(out, tr, args.replay_workers)
    out["overhead"] = _overhead(unique_tr)
    out["capacity_ratio"] = (
        round(out["arms"]["cached"]["max_sustainable_qps"]
              / out["arms"]["uncached"]["max_sustainable_qps"], 2)
        if out["arms"]["uncached"]["max_sustainable_qps"] else None)

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({
        "uncached_qps": out["arms"]["uncached"]["max_sustainable_qps"],
        "cached_qps": out["arms"]["cached"]["max_sustainable_qps"],
        "capacity_ratio": out["capacity_ratio"],
        "matched_rate_p50_speedup": out["matched_rate"].get("p50_speedup"),
        "miss_path_delta_ms": out["overhead"]["miss_path_delta_ms"],
        "noise_floor_ms": out["overhead"]["noise_floor_ms"],
    }, indent=2))
    failures = check(out)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
