"""Capacity regression gate: fresh short replay vs the committed baseline.

Reads the committed ``BENCH_CAPACITY.json`` (tools/bench_capacity.py),
rebuilds the named arm (same definition — ``bench_capacity.arm_runner``)
and replays a shortened twin of the committed trace **at the committed
capacity's floor speed** — ``max_speed * (1 - tolerance)``. If the arm
can no longer attain the committed SLOs at 85% of its committed
capacity, SLO capacity has regressed >15%: exit 1. A probe is retried
(``--attempts``, default 2) before the verdict, so one scheduling
hiccup on a shared-core CI box doesn't false-fail the gate; a fresh
capacity ABOVE the committed one never fails — regenerate and commit
the artifact to ratchet the baseline up.

Probing at the floor (instead of re-bisecting) keeps the gate one-replay
cheap AND immune to the bisection grid's quantization, which near the
low end is coarser than the tolerance itself.

With ``--admission`` the gate instead re-checks the committed admission
overload proof (``BENCH_ADMISSION.json``, tools/bench_admission.py): it
re-runs BOTH overload arms at the committed 2x speed on a shortened twin
of the trace and exits 1 when the committed invariants (admitted-traffic
p99 inside the declared SLO, honest nonzero shed, delivery improved over
the un-admitted baseline) no longer hold live.

With ``--federation`` the gate re-runs the committed multi-cell
blackhole proof live (``BENCH_FEDERATION.json``,
tools/bench_federation.py): a fresh 2-cell fleet replays a shortened
twin of the committed trace with the WHOLE home cell blackholed
mid-replay, and exits 1 when the federated arm no longer spills with
~0 user-visible errors, attains its declared SLOs and delivers.

With ``--disagg`` the gate re-runs the committed disaggregated
prefill/decode decode-kill proof live (``BENCH_DISAGG.json``,
tools/bench_disagg.py): a decode replica RST mid-stream must still
recover via re-prefill with delivery 1.0, zero repeated/dropped tokens,
bit-exact vs the monolithic reference.

With ``--pipeline`` the gate re-runs the committed model-DAG
killed-stage proof live (``BENCH_PIPELINE.json``,
tools/bench_pipeline.py): the chain DAG's first stage pinned behind a
ChaosProxy is RST mid-run — armed runs must fail with a typed
StageFailed naming that stage, dependents must never dispatch, zero
arena lease bytes may leak, and the same client must recover bit-exact
after heal.

With ``--integrity`` the gate re-runs the committed byzantine-replica
quarantine proof live (``BENCH_INTEGRITY.json``,
tools/bench_integrity.py): a fresh 3-replica pool with one seeded
lying replica must deliver ZERO corrupt results and ZERO caller
errors, quarantine the lying replica (typed ``EndpointQuarantined``)
and have the doctor's rules name it as a ``byzantine_replica``
anomaly. The overhead (A/A) arm is validated from the committed
artifact by ``--check``/CI, not re-run here.

With ``--flight`` the gate proves the flight recorder is
pay-for-what-you-use: the capacity arm replayed recorder-OFF at the
standard floor must sustain (else INCONCLUSIVE — plain capacity
regressed), and the same arm recorder-ON at ``floor * (1 - 0.05)`` must
also sustain, i.e. recorder-on capacity stays within 5% of the
recorder-off floor demonstrated in the same session.

Usage::

    JAX_PLATFORMS=cpu python tools/capacity_gate.py \
        [--baseline BENCH_CAPACITY.json] [--arm baseline] \
        [--tolerance 0.15] [--duration-s 3.0] [--attempts 2]
    JAX_PLATFORMS=cpu python tools/capacity_gate.py --admission \
        [--admission-baseline BENCH_ADMISSION.json] [--duration-s 2.0]
    JAX_PLATFORMS=cpu python tools/capacity_gate.py --flight \
        [--flight-tolerance 0.05]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def compare(committed_qps: float, fresh_qps: float,
            tolerance: float = 0.15) -> Dict[str, Any]:
    """Pure verdict for number-vs-number comparisons: ``regressed`` when
    the fresh capacity falls more than ``tolerance`` below the committed
    one (0 committed never regresses — there is nothing to fall from)."""
    floor = committed_qps * (1.0 - tolerance)
    return {
        "committed_qps": committed_qps,
        "fresh_qps": fresh_qps,
        "tolerance": tolerance,
        "floor_qps": round(floor, 1),
        "ratio": round(fresh_qps / committed_qps, 3) if committed_qps else None,
        "regressed": committed_qps > 0 and fresh_qps < floor,
    }


def shortened_trace(doc: Dict[str, Any], duration_s: float,
                    arm: str = "") -> Any:
    """The committed artifact's generator spec/seed re-generated at a
    shorter duration — the same workload shape, CI-cheap. An arm that
    recorded its own ``trace_spec`` (the sharded arm replays sharded
    records, not the mixed default) gets that spec back."""
    from client_tpu import trace as trace_mod

    spec = doc["trace"]["spec"]
    if arm:
        spec = doc.get("arms", {}).get(arm, {}).get("trace_spec", spec)
    return trace_mod.generate(spec,
                              seed=int(doc["trace"]["seed"]),
                              duration_s=duration_s)


def probe_at_floor(doc: Dict[str, Any], arm: str, tolerance: float,
                   duration_s: float, replay_workers: int,
                   attempts: int) -> Dict[str, Any]:
    """Replay the shortened trace at the committed floor speed; regressed
    only if EVERY attempt misses an SLO."""
    import tools.bench_capacity as bench

    committed = doc["arms"][arm]
    floor_speed = float(committed["max_speed"]) * (1.0 - tolerance)
    result: Dict[str, Any] = {
        "arm": arm,
        "committed_max_speed": committed["max_speed"],
        "committed_qps": committed["max_sustainable_qps"],
        "tolerance": tolerance,
        "floor_speed": round(floor_speed, 3),
        "attempts": [],
    }
    if floor_speed <= 0.0:
        # a zero committed capacity has nothing to regress from
        result["regressed"] = False
        return result
    tr = shortened_trace(doc, duration_s, arm=arm)
    # an arm that committed its own SLO set (sharded: no streams, no
    # ttft objective) is re-checked against exactly that set
    slos = list(committed.get("slos", doc["slos"]))
    search = doc.get("search", {})
    min_delivery = float(search.get(
        "min_delivery_ratio", bench.MIN_DELIVERY_RATIO))
    # rebuild the arm under the SAME fault AND harness concurrency the
    # committed number was measured under — a different chaos latency is
    # a different workload, and fewer replay workers is a different
    # issuing capacity (the caller's value is only the fallback)
    chaos_latency_s = float(search.get("chaos_latency_s", 0.01))
    replay_workers = int(search.get("replay_workers", replay_workers))
    with bench.arm_runner(arm, chaos_latency_s) as (runner, feature):
        result["feature"] = feature
        # warm the measurement path the way the bench's own low-speed
        # first probe does (connections, server jit, telemetry) — a cold
        # client slammed straight at the floor speed measures startup
        # transients, not capacity
        runner.run_trace(tr, speed=min(1.0, floor_speed),
                         replay_workers=replay_workers, slos=slos)
        for _ in range(max(1, attempts)):
            row = runner.run_trace(tr, speed=round(floor_speed, 3),
                                   replay_workers=replay_workers, slos=slos)
            ok = bench.sustainable(row, min_delivery)
            result["attempts"].append({
                "offered_rate": row["offered_rate"],
                "achieved_rate": row["achieved_rate"],
                "errors": row["errors"],
                "shed": row["shed"],
                "slo_ok": row["slo_ok"],
                "sustainable": ok,
                "slo": row["slo"],
            })
            if ok:
                break
    result["regressed"] = not any(
        a["sustainable"] for a in result["attempts"])
    return result


def admission_recheck(baseline: str, duration_s: float,
                      attempts: int) -> int:
    """Live re-validation of the committed admission overload proof
    (both arm definitions live in tools/bench_admission.py)."""
    import tools.bench_admission as bench

    doc = json.loads(Path(baseline).read_text())
    verdict = bench.probe_overload(doc, duration_s=duration_s,
                                   attempts=attempts)
    adm = verdict["arms"]["admitted"]["row"]
    print(json.dumps({
        "overload_speed": doc["overload"]["speed"],
        "declared_admitted_p99_ms": doc["declared_admitted_p99_ms"],
        "fresh_admitted_p99_ms": adm["latency_ms"].get("p99"),
        "fresh_shed_rate": adm["shed_rate"],
        "problems": verdict["problems"],
    }, indent=2))
    if verdict["problems"]:
        print("FAIL: the admission overload invariants no longer hold")
        return 1
    print("OK: admission overload proof reproduces")
    return 0


def hotkey_recheck(baseline: str, tolerance: float, duration_s: float,
                   attempts: int) -> int:
    """Live re-validation of the committed hot-key serving proof (the arm
    definition lives in tools/bench_hotkey.py): the cached arm replayed
    at the committed capacity's floor speed on a shortened twin of the
    zipfian trace must still attain the SLOs, deliver the schedule, AND
    actually collapse (wire < logical, nonzero hit rate) — a layer that
    stops collapsing but still passes latency would be a silent
    regression of the whole point."""
    import tools.bench_hotkey as bench
    from client_tpu import trace as trace_mod

    doc = json.loads(Path(baseline).read_text())
    committed = doc["arms"]["cached"]
    floor_speed = round(float(committed["max_speed"]) * (1.0 - tolerance), 3)
    # the committed trace at FULL duration (it is already only a few
    # seconds): at the cached arm's floor speed the whole schedule fires
    # in a sub-second window, and shortening the trace further would
    # shrink that window until scheduler jitter — not capacity — decides
    # the delivery verdict. duration_s is accepted for signature parity
    # but only applied when it EXCEEDS the committed duration.
    gate_duration = max(duration_s, float(doc["trace"]["duration_s"]))
    tr = trace_mod.generate(doc["trace"]["spec"],
                            seed=int(doc["trace"]["seed"]),
                            duration_s=gate_duration)
    replay_workers = int(doc["search"]["replay_workers"])
    rows = []
    ok = False
    with bench.arm_runner("cached") as (runner, _):
        # same warm-first discipline as probe_at_floor: a cold client
        # slammed at the floor speed measures startup, not capacity
        runner.run_trace(tr, speed=1.0, replay_workers=replay_workers,
                         slos=bench.SLOS)
        for _ in range(max(1, attempts)):
            row = runner.run_trace(tr, speed=floor_speed,
                                   replay_workers=replay_workers,
                                   slos=bench.SLOS)
            cc = row.get("client_cache") or {}
            collapsing = (bool(cc)
                          and cc["wire_requests"] < cc["logical_requests"]
                          and (cc.get("hit_rate") or 0.0) > 0.2)
            from tools.bench_capacity import sustainable

            ok = sustainable(row) and collapsing
            rows.append({
                "speed": floor_speed,
                "offered_rate": row["offered_rate"],
                "slo_ok": row["slo_ok"],
                "hit_rate": cc.get("hit_rate"),
                "wire_requests": cc.get("wire_requests"),
                "logical_requests": cc.get("logical_requests"),
                "collapsing": collapsing,
                "ok": ok,
            })
            if ok:
                break
    print(json.dumps({
        "committed_max_speed": committed["max_speed"],
        "committed_qps": committed["max_sustainable_qps"],
        "floor_speed": floor_speed,
        "attempts": rows,
    }, indent=2))
    if not ok:
        print("FAIL: the hot-key cached arm no longer sustains its "
              "committed floor (or stopped collapsing wire requests)")
        return 1
    print("OK: hot-key serving proof reproduces")
    return 0


def flight_recheck(baseline: str, arm: str, tolerance: float,
                   duration_s: float, replay_workers: int,
                   attempts: int, flight_tolerance: float = 0.05) -> int:
    """Recorder-on capacity must stay within ``flight_tolerance``
    (default 5%) of the recorder-OFF floor, demonstrated LIVE in the
    same session so environment drift never masquerades as recorder
    cost: (1) the committed capacity arm replayed recorder-OFF at the
    standard gate floor (``max_speed * (1 - tolerance)``) must sustain —
    else the verdict is INCONCLUSIVE (exit 2: capacity itself regressed;
    that is the plain gate's business, not the recorder's); (2) the same
    arm replayed recorder-ON at ``floor * (1 - flight_tolerance)`` must
    also sustain AND actually record. An always-on forensic layer that
    costs real capacity would be a lie about being
    pay-for-what-you-use."""
    import tools.bench_capacity as bench

    doc = json.loads(Path(baseline).read_text())
    if arm not in doc["arms"]:
        print(f"arm {arm!r} not in {baseline} (has: {sorted(doc['arms'])})")
        return 2
    committed = doc["arms"][arm]
    floor_speed = round(float(committed["max_speed"]) * (1.0 - tolerance), 3)
    on_speed = round(floor_speed * (1.0 - flight_tolerance), 3)
    result: Dict[str, Any] = {
        "arm": arm,
        "committed_max_speed": committed["max_speed"],
        "committed_qps": committed["max_sustainable_qps"],
        "recorder_off_floor_speed": floor_speed,
        "flight_tolerance": flight_tolerance,
        "recorder_on_speed": on_speed,
        "off_attempts": [],
        "on_attempts": [],
    }
    if floor_speed <= 0.0:
        print(json.dumps(result, indent=2))
        print("OK: zero committed capacity has nothing to regress from")
        return 0
    tr = shortened_trace(doc, duration_s, arm=arm)
    slos = list(committed.get("slos", doc["slos"]))
    search = doc.get("search", {})
    min_delivery = float(search.get(
        "min_delivery_ratio", bench.MIN_DELIVERY_RATIO))
    chaos_latency_s = float(search.get("chaos_latency_s", 0.01))
    replay_workers = int(search.get("replay_workers", replay_workers))

    def probe(runner, speed, out_rows):
        ok = False
        for _ in range(max(1, attempts)):
            row = runner.run_trace(tr, speed=speed,
                                   replay_workers=replay_workers,
                                   slos=slos)
            fl = row.get("client_flight") or {}
            ok = bench.sustainable(row, min_delivery)
            out_rows.append({
                "speed": speed,
                "offered_rate": row["offered_rate"],
                "achieved_rate": row["achieved_rate"],
                "errors": row["errors"],
                "slo_ok": row["slo_ok"],
                "flight_requests": fl.get("requests"),
                "flight_retained": fl.get("retained_total"),
                "sustainable": ok,
            })
            if ok:
                return True
        return ok

    off_ok = on_ok = recording = False
    with bench.arm_runner(arm, chaos_latency_s) as (runner, feature):
        result["feature"] = feature
        # warm-first discipline (see probe_at_floor), recorder off
        runner.run_trace(tr, speed=min(1.0, floor_speed),
                         replay_workers=replay_workers, slos=slos)
        off_ok = probe(runner, floor_speed, result["off_attempts"])
        if off_ok:
            runner.flight = True
            on_ok = probe(runner, on_speed, result["on_attempts"])
            recording = any((r.get("flight_requests") or 0) > 0
                            for r in result["on_attempts"])
    print(json.dumps(result, indent=2))
    if not off_ok:
        print("INCONCLUSIVE: the arm no longer sustains its committed "
              "recorder-OFF floor — capacity itself regressed; run the "
              "plain capacity gate")
        return 2
    if not on_ok or not recording:
        print(f"FAIL: with the flight recorder attached, {arm} no longer "
              f"sustains {(1 - flight_tolerance) * 100:.0f}% of the "
              f"recorder-off floor it just demonstrated "
              f"(or the recorder recorded nothing)")
        return 1
    print("OK: recorder-on capacity within "
          f"{flight_tolerance * 100:.0f}% of the recorder-off floor")
    return 0


def federation_recheck(baseline: str, duration_s: float,
                       attempts: int) -> int:
    """Re-RUN the committed federation blackhole proof live
    (``BENCH_FEDERATION.json``, tools/bench_federation.py): a fresh
    2-cell fleet, a shortened twin of the committed trace, the whole
    home cell blackholed mid-replay — the federated arm must still hold
    user-visible errors at ~0, attain the declared SLOs, deliver, and
    actually spill. Retried ``attempts`` times so one scheduling hiccup
    on a shared-core CI box doesn't false-fail; the canary/baseline arms
    are validated from the committed artifact by ``--check``/CI, not
    re-run here (the blackhole arm is the availability claim)."""
    import tools.bench_federation as bench

    doc = json.loads(Path(baseline).read_text())
    problems_committed = bench.check_artifact(doc)
    if problems_committed:
        print("committed artifact already violates its invariants:")
        for p in problems_committed:
            print(f"  - {p}")
        return 1
    rows = []
    for attempt in range(max(1, attempts)):
        with bench.two_cells() as (cells, chaos):
            arm = bench.run_blackhole_arm(
                cells, chaos, federated=True, duration_s=duration_s)
        problems = []
        if arm["error_rate"] > bench.FED_MAX_ERROR_RATE:
            problems.append(
                f"error_rate {arm['error_rate']} > "
                f"{bench.FED_MAX_ERROR_RATE}")
        if not arm["slo_ok"]:
            problems.append("declared SLOs missed")
        if arm["delivery_ratio"] < bench.FED_MIN_DELIVERY:
            problems.append(
                f"delivery {arm['delivery_ratio']} < "
                f"{bench.FED_MIN_DELIVERY}")
        if arm.get("spills", 0) <= 0:
            problems.append("no spills recorded (blackhole never "
                            "exercised spillover)")
        rows.append({
            "attempt": attempt + 1,
            "delivery_ratio": arm["delivery_ratio"],
            "error_rate": arm["error_rate"],
            "slo_ok": arm["slo_ok"],
            "spills": arm.get("spills"),
            "home_breaker": arm.get("home_breaker"),
            "problems": problems,
        })
        if not problems:
            break
    print(json.dumps({"federation": rows}, indent=2))
    if rows[-1]["problems"]:
        print("FAIL: the federated blackhole arm no longer degrades "
              "gracefully:")
        for p in rows[-1]["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: cell blackhole still degrades gracefully "
          f"(delivery {rows[-1]['delivery_ratio']}, error_rate "
          f"{rows[-1]['error_rate']}, spills {rows[-1]['spills']})")
    return 0


def tenancy_recheck(duration_s: float, attempts: int) -> int:
    """Re-RUN the committed multi-tenant isolation proof live
    (``BENCH_TENANCY.json``, tools/bench_tenancy.py): both arms on a
    shortened twin of the workload — the compliant tenants must keep
    >=95% of their isolated-arm capacity under the 10x-quota adversary,
    the adversary's rejects must stay cleanly typed ``over_quota``, and
    the tenancy snapshot must still name the noisy neighbor."""
    import tools.bench_tenancy as bench

    verdict = bench.probe_isolation(duration_s=duration_s,
                                    attempts=attempts)
    print(json.dumps({"attempts": verdict["attempts"]}, indent=2))
    if verdict["problems"]:
        print("FAIL: multi-tenant isolation no longer holds live:")
        for p in verdict["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: tenant isolation proof reproduces")
    return 0


def disagg_recheck(baseline: str, attempts: int) -> int:
    """Re-RUN the committed disaggregated prefill/decode chaos proof
    live (``BENCH_DISAGG.json``, tools/bench_disagg.py): a fresh
    prefill replica + two decode replicas (one behind a ChaosProxy),
    decode RST mid-stream — every killed session must still finish via
    re-prefill recovery with delivery 1.0, zero repeated and zero
    dropped tokens, bit-exact vs the monolithic reference. Retried
    ``attempts`` times; the split/steady-state arms are validated from
    the committed artifact by ``--check``/CI, not re-run here (the
    decode-kill arm is the robustness claim)."""
    import tools.bench_disagg as bench

    doc = json.loads(Path(baseline).read_text())
    problems_committed = bench.check_doc(doc)
    if problems_committed:
        print("committed artifact already violates its invariants:")
        for p in problems_committed:
            print(f"  - {p}")
        return 1
    rows = []
    for attempt in range(max(1, attempts)):
        arm = bench.run_chaos_arm()
        problems = bench.chaos_problems(arm)
        rows.append({
            "attempt": attempt + 1,
            "delivery_ratio": arm["delivery_ratio"],
            "kills": arm["kills"],
            "repeated_tokens": arm["repeated_tokens"],
            "dropped_tokens": arm["dropped_tokens"],
            "bit_exact": arm["bit_exact"],
            "problems": problems,
        })
        if not problems:
            break
    print(json.dumps({"disagg": rows}, indent=2))
    if rows[-1]["problems"]:
        print("FAIL: mid-stream decode death no longer recovers "
              "losslessly:")
        for p in rows[-1]["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: decode-kill recovery proof reproduces "
          f"(delivery {rows[-1]['delivery_ratio']}, "
          f"kills {rows[-1]['kills']}, zero repeats/drops, bit-exact)")
    return 0


def integrity_recheck(baseline: str, attempts: int) -> int:
    """Re-RUN the committed byzantine-replica quarantine proof live
    (``BENCH_INTEGRITY.json``, tools/bench_integrity.py): a fresh
    3-replica pool with one seeded lying replica — zero corrupt results
    delivered, zero caller errors, the lying replica quarantined and
    named by the doctor's byzantine_replica rule. Retried ``attempts``
    times; the overhead (A/A) arm is validated from the committed
    artifact by ``--check``/CI, not re-run here (the quarantine arm is
    the robustness claim)."""
    import tools.bench_integrity as bench

    doc = json.loads(Path(baseline).read_text())
    problems_committed = bench.check_doc(doc)
    if problems_committed:
        print("committed artifact already violates its invariants:")
        for p in problems_committed:
            print(f"  - {p}")
        return 1
    rows = []
    for attempt in range(max(1, attempts)):
        arm = bench.run_byzantine_arm()
        problems = bench.byzantine_problems(arm)
        rows.append({
            "attempt": attempt + 1,
            "corrupt_delivered": arm["corrupt_delivered"],
            "caller_errors": arm["caller_errors"],
            "faults_injected": arm["faults_injected"],
            "quarantined_urls": arm["quarantined_urls"],
            "byzantine_url": arm["byzantine_url"],
            "doctor_named_it": any(
                a.get("url") == arm["byzantine_url"]
                for a in arm.get("doctor_anomalies") or []),
            "problems": problems,
        })
        if not problems:
            break
    print(json.dumps({"integrity": rows}, indent=2))
    if rows[-1]["problems"]:
        print("FAIL: the byzantine-replica quarantine proof no longer "
              "reproduces:")
        for p in rows[-1]["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: byzantine quarantine proof reproduces (zero corrupt "
          f"results over {rows[-1]['faults_injected']} injected faults; "
          f"{rows[-1]['byzantine_url']} quarantined and named)")
    return 0


def pipeline_recheck(baseline: str, attempts: int) -> int:
    """Re-RUN the committed model-DAG killed-stage proof live
    (``BENCH_PIPELINE.json``, tools/bench_pipeline.py): the chain DAG's
    first stage pinned behind a ChaosProxy, endpoint RST mid-run —
    every armed run must fail with a typed StageFailed naming that
    stage, dependents must never dispatch, zero arena lease bytes may
    leak, and the same client must recover bit-exact after heal.
    Retried ``attempts`` times; the exactness/dag_vs_sequential/
    steady-state arms are validated from the committed artifact by
    ``--check``/CI, not re-run here (the killed-stage arm is the
    robustness claim)."""
    import tools.bench_pipeline as bench

    doc = json.loads(Path(baseline).read_text())
    problems_committed = bench.check_doc(doc)
    if problems_committed:
        print("committed artifact already violates its invariants:")
        for p in problems_committed:
            print(f"  - {p}")
        return 1
    rows = []
    for attempt in range(max(1, attempts)):
        arm = bench.run_chaos_arm()
        problems = bench.chaos_problems(arm)
        rows.append({
            "attempt": attempt + 1,
            "typed_stage_failures": arm["typed_stage_failures"],
            "dependents_dispatched": arm["dependents_dispatched"],
            "leaked_lease_bytes": arm["leaked_lease_bytes"],
            "bit_exact": arm["bit_exact"],
            "recovered": arm["recovered"],
            "problems": problems,
        })
        if not problems:
            break
    print(json.dumps({"pipeline": rows}, indent=2))
    if rows[-1]["problems"]:
        print("FAIL: killed-stage DAG failure is no longer typed, "
              "contained, and leak-free:")
        for p in rows[-1]["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: killed-stage proof reproduces "
          f"({rows[-1]['typed_stage_failures']} typed StageFailed, "
          "zero dependents dispatched, zero leaked lease bytes, "
          "recovered bit-exact)")
    return 0


def watch_recheck(baseline: str, attempts: int) -> int:
    """Re-RUN the committed continuous-monitoring proof live
    (``BENCH_WATCH.json``, tools/bench_watch.py): the A/A soak (a fresh
    no-fault 3-replica topology under the watchtower must fire ZERO
    alerts — the false-positive bar) plus the latency detection arm (a
    mid-run 50 ms fault must be detected BY NAME inside the fault
    window). Retried ``attempts`` times; the disabled-path/tick-cost/
    kill-9 arms are validated from the committed artifact by
    ``--check``/CI, not re-run here (the live-detection and
    zero-false-positive arms are the robustness claims)."""
    import tools.bench_watch as bench

    doc = json.loads(Path(baseline).read_text())
    if bench.check(doc) != 0:
        print("committed artifact already violates its invariants")
        return 1
    rows = []
    for attempt in range(max(1, attempts)):
        aa = bench.bench_aa_soak()
        det = bench.bench_chaos_latency()
        problems = []
        if aa["alerts_fired_total"] != 0:
            problems.append(
                f"A/A soak fired {aa['alerts_fired_total']} alerts")
        if not det["detected"]:
            problems.append("latency fault never detected by name")
        elif det["detect_s"] > det["fault_duration_s"] + 1e-9:
            problems.append(
                f"detection ({det['detect_s']}s) landed outside the "
                f"fault window ({det['fault_duration_s']}s)")
        if det.get("baseline_alerts", 0) != 0:
            problems.append("alerts fired during the healthy baseline")
        rows.append({
            "attempt": attempt + 1,
            "aa_alerts": aa["alerts_fired_total"],
            "aa_ticks": aa["ticks"],
            "detected": det["detected"],
            "detect_s": det["detect_s"],
            "fault_duration_s": det["fault_duration_s"],
            "alert_kind": det["alert_kind"],
            "problems": problems,
        })
        if not problems:
            break
    print(json.dumps({"watch": rows}, indent=2))
    if rows[-1]["problems"]:
        print("FAIL: the continuous-monitoring proof no longer "
              "reproduces:")
        for p in rows[-1]["problems"]:
            print(f"  - {p}")
        return 1
    print("OK: continuous-monitoring proof reproduces (A/A zero alerts "
          f"over {rows[-1]['aa_ticks']} ticks; latency fault named in "
          f"{rows[-1]['detect_s']}s via {rows[-1]['alert_kind']})")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--baseline", default="BENCH_CAPACITY.json")
    parser.add_argument("--arm", default="baseline")
    parser.add_argument("--tolerance", type=float, default=0.15)
    parser.add_argument("--duration-s", type=float, default=3.0)
    parser.add_argument("--attempts", type=int, default=2)
    parser.add_argument("--replay-workers", type=int, default=32)
    parser.add_argument("--admission", action="store_true",
                        help="re-check the committed admission overload "
                             "proof instead of an SLO-capacity arm")
    parser.add_argument("--admission-baseline",
                        default="BENCH_ADMISSION.json")
    parser.add_argument("--hotkey", action="store_true",
                        help="re-check the committed hot-key serving "
                             "proof (BENCH_HOTKEY.json): the cached arm "
                             "at its committed floor speed must still "
                             "attain SLOs AND collapse wire requests")
    parser.add_argument("--hotkey-baseline", default="BENCH_HOTKEY.json")
    parser.add_argument("--flight", action="store_true",
                        help="re-check that recorder-ON capacity stays "
                             "within --flight-tolerance (5%%) of the "
                             "committed recorder-off floor: the capacity "
                             "arm at floor speed with a flight recorder "
                             "attached must still attain its SLOs")
    parser.add_argument("--flight-tolerance", type=float, default=0.05)
    parser.add_argument("--federation", action="store_true",
                        help="re-run the committed federation blackhole "
                             "proof live (BENCH_FEDERATION.json): a "
                             "fresh 2-cell fleet with the whole home "
                             "cell blackholed mid-replay must still "
                             "spill with ~0 user-visible errors and "
                             "attain the declared SLOs")
    parser.add_argument("--federation-baseline",
                        default="BENCH_FEDERATION.json")
    parser.add_argument("--tenancy", action="store_true",
                        help="re-run the committed multi-tenant isolation "
                             "proof live (BENCH_TENANCY.json): compliant "
                             "capacity within 5%% of isolated under the "
                             "10x-quota adversary, sheds typed over_quota, "
                             "noisy neighbor named")
    parser.add_argument("--disagg", action="store_true",
                        help="re-run the committed disaggregated "
                             "prefill/decode chaos proof live "
                             "(BENCH_DISAGG.json): a decode replica RST "
                             "mid-stream must still recover via "
                             "re-prefill with delivery 1.0 and zero "
                             "repeated/dropped tokens, bit-exact")
    parser.add_argument("--disagg-baseline", default="BENCH_DISAGG.json")
    parser.add_argument("--pipeline", action="store_true",
                        help="re-run the committed model-DAG "
                             "killed-stage proof live "
                             "(BENCH_PIPELINE.json): a pinned stage "
                             "endpoint RST mid-run must produce a typed "
                             "StageFailed naming the stage, dependents "
                             "never dispatch, zero leaked arena leases, "
                             "recovery bit-exact after heal")
    parser.add_argument("--pipeline-baseline",
                        default="BENCH_PIPELINE.json")
    parser.add_argument("--integrity", action="store_true",
                        help="re-run the committed byzantine-replica "
                             "quarantine proof live (zero corrupt "
                             "results, lying replica quarantined and "
                             "named) instead of the capacity probe")
    parser.add_argument("--integrity-baseline",
                        default="BENCH_INTEGRITY.json")
    parser.add_argument("--watch", action="store_true",
                        help="re-run the committed continuous-monitoring "
                             "proof live (BENCH_WATCH.json): the A/A "
                             "soak must fire zero alerts and a mid-run "
                             "latency fault must be detected by name "
                             "inside the fault window")
    parser.add_argument("--watch-baseline", default="BENCH_WATCH.json")
    args = parser.parse_args()

    if args.watch:
        return watch_recheck(args.watch_baseline, args.attempts)
    if args.integrity:
        return integrity_recheck(args.integrity_baseline, args.attempts)
    if args.pipeline:
        return pipeline_recheck(args.pipeline_baseline, args.attempts)
    if args.disagg:
        return disagg_recheck(args.disagg_baseline, args.attempts)
    if args.tenancy:
        return tenancy_recheck(args.duration_s, args.attempts)
    if args.federation:
        return federation_recheck(args.federation_baseline,
                                  args.duration_s, args.attempts)
    if args.flight:
        return flight_recheck(args.baseline, args.arm, args.tolerance,
                              args.duration_s, args.replay_workers,
                              args.attempts,
                              flight_tolerance=args.flight_tolerance)
    if args.hotkey:
        return hotkey_recheck(args.hotkey_baseline, args.tolerance,
                              args.duration_s, args.attempts)
    if args.admission:
        return admission_recheck(
            args.admission_baseline,
            # the admission re-check runs two arms: default to a shorter
            # twin than the capacity gate's single-arm probe
            min(args.duration_s, 2.0), args.attempts)

    doc = json.loads(Path(args.baseline).read_text())
    if args.arm not in doc["arms"]:
        print(f"arm {args.arm!r} not in {args.baseline} "
              f"(has: {sorted(doc['arms'])})")
        return 2
    verdict = probe_at_floor(doc, args.arm, args.tolerance, args.duration_s,
                             args.replay_workers, args.attempts)
    print(json.dumps(verdict, indent=2))
    if verdict["regressed"]:
        print(f"FAIL: {args.arm} no longer sustains "
              f"{(1 - args.tolerance) * 100:.0f}% of its committed "
              f"SLO capacity ({verdict['committed_qps']} QPS)")
        return 1
    print(f"OK: {args.arm} capacity within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
