#!/usr/bin/env bash
# Fast chaos validation: the resilience + pool chaos subset plus the
# observability smoke (<60 s), so a resilience- or telemetry-layer change
# can be smoke-checked without the full suite or the soak tier. The same
# tests run inside tier-1 (the chaos_smoke/observe_smoke markers are
# registered in pyproject and NOT excluded by addopts).
#
# The observability smoke (tests/test_observe.py) runs flap chaos with
# telemetry on and asserts retry/breaker counters are non-zero and no
# exported metric goes negative. The streaming-observability smoke
# (tests/test_stream_observe.py) runs flap chaos over traced streams:
# reconnect sub-spans present, TTFT recorded per attempt, no
# negative/NaN metric. The micro-batching smoke (tests/
# test_client_batching.py, batch_smoke marker) runs the coalescing
# dispatcher against retry/breaker resilience under a flapping proxy:
# every caller must still receive its exact rows. The doctor smoke
# (tests/test_dataplane_observe.py, doctor_smoke marker) runs the fleet
# snapshot against a 3-replica pool with one replica behind a latency
# fault: the decomposition must attribute the extra milliseconds to the
# network, not the server, and flag the load/latency divergence. The
# trace-replay smoke (tests/test_trace_replay.py, replay_smoke marker)
# replays a seeded mixed-kind trace (unary + SSE stream + sequence)
# open-loop against the threaded server: every record must complete,
# sequence steps in order, with SLO verdicts and slip reported. The
# shm-arena smoke (tests/test_arena.py, arena_smoke marker) runs the
# transparent arena promotion path against retry resilience under a
# flapping proxy: every request completes, no slab is double-leased,
# leased bytes return to zero, and the registration cache keeps the
# register RPCs amortized across the flaps. The admission smoke
# (tests/test_admission.py, admission_smoke marker) offers a 3-replica
# pool far more traffic than it can serve: admitted-traffic p99 must
# stay inside the declared SLO while a nonzero shed fraction is
# reported honestly in the replay row AND the Prometheus counter. The
# sharded scatter-gather smoke (tests/test_shard.py, shard_smoke
# marker) proves the one-logical-request-across-a-replica-mesh mode:
# bit-exact gather vs the single-process decoder_tp reference, a killed
# shard producing the typed ShardFailed (whole-request, zero partial
# gathers, no silent retry), and sharded trace-record replay.
#
# The hot-key smoke (tests/test_hotkey_cache.py, hotkey_smoke marker)
# proves the serving layer for zipfian traffic: affinity routing
# re-homes keys deterministically through a replica kill/heal cycle
# with zero routing-attributable errors, and a zipfian trace replayed
# through cache+singleflight issues measurably fewer wire requests
# than logical requests. The flight-recorder smoke (tests/
# test_flight.py, flight_smoke marker) runs a 3-replica pool with one
# replica behind a latency proxy: the recorder's retained slow-tail
# timelines must attribute the latency to (and NAME) the faulted
# endpoint through tail_divergence, with the retained ring staying
# bounded. The federation smoke (tests/test_federation.py,
# federation_smoke marker) runs a 2-cell fleet whose WHOLE home cell
# blackholes mid-run (one ChaosCell call): every request must still
# succeed via transparent spillover, the cell breaker must open, and
# traffic must return home after heal. The tenancy smoke (tests/
# test_tenancy.py, tenancy_smoke marker) replays an adversarial tenant
# at 10x its quota against compliant tenants through the weighted-fair
# admission controller: compliant capacity within 5% of the isolated
# baseline, zero compliant SLO breaches, the adversary's rejects all
# typed over_quota, and the noisy neighbor named in the tenancy
# snapshot. The disaggregation smoke (tests/test_disagg.py,
# disagg_smoke marker) kills a decode replica mid-stream (proxy RST)
# under disaggregated prefill/decode serving: the session must finish
# via re-prefill recovery on the surviving decode replica with zero
# repeated and zero dropped tokens, bit-exact vs the monolithic
# reference stream. The pipeline smoke (tests/test_pipeline.py,
# pipeline_smoke marker) RSTs the endpoint one DAG stage is pinned to
# mid-run: the run must fail with a typed StageFailed naming that
# stage, unstarted dependents must never dispatch, zero arena leases
# may leak, and the same client must recover after heal; the replay
# half drives v6 pipeline trace records through perf.py --pipeline
# with per-stage latency columns. The integrity smoke (tests/
# test_integrity.py, integrity_smoke marker) runs a 3-replica pool
# where one replica is a live byzantine server lying on every response
# (shape/dtype lies, truncated tails, garbage JSON): every request must
# still return CORRECT values via failover, the liar must be
# quarantined after N contract-invalid responses (EndpointQuarantined
# fired, quarantine visible in endpoint_stats/health_summary), and the
# doctor's byzantine_replica anomaly must name its url. The
# continuous-monitoring smoke (tests/test_watch.py, watch_smoke marker)
# runs a 3-replica pool with one replica behind a latency fault under a
# live fast-tick Watchtower: an alert (changepoint or SLO burn) must
# fire BEFORE the fault heals, its evidence must name the faulted
# endpoint via the flight recorder's tail divergence, and the condition
# must resolve after heal — time-to-detect < fault duration, proven on
# live traffic, with the same alert edges recoverable from the
# crash-safe black-box ring.
#
# Usage: tools/chaos_smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest -q \
    -m 'chaos_smoke or observe_smoke or stream_observe_smoke or batch_smoke or doctor_smoke or replay_smoke or arena_smoke or admission_smoke or shard_smoke or hotkey_smoke or flight_smoke or federation_smoke or tenancy_smoke or disagg_smoke or pipeline_smoke or integrity_smoke or watch_smoke' \
    -p no:cacheprovider \
    tests/test_resilience.py tests/test_pool.py tests/test_observe.py \
    tests/test_stream_observe.py tests/test_client_batching.py \
    tests/test_dataplane_observe.py tests/test_trace_replay.py \
    tests/test_arena.py tests/test_admission.py tests/test_shard.py \
    tests/test_hotkey_cache.py tests/test_flight.py \
    tests/test_federation.py tests/test_tenancy.py \
    tests/test_disagg.py tests/test_pipeline.py \
    tests/test_integrity.py tests/test_watch.py "$@"
