#!/usr/bin/env bash
# Fast chaos validation: the resilience + pool chaos subset (<60 s), so a
# resilience-layer change can be smoke-checked without the full suite or
# the soak tier. The same tests run inside tier-1 (the chaos_smoke marker
# is registered in pyproject and NOT excluded by addopts).
#
# Usage: tools/chaos_smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest -q -m chaos_smoke \
    -p no:cacheprovider tests/test_resilience.py tests/test_pool.py "$@"
