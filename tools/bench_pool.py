"""Generate BENCH_POOL.json: the pool-layer cost/benefit artifact.

Two questions, answered against live in-process servers:

1. **Armed-pool overhead at N=1** — the same workload through a bare
   ``InferenceServerClient`` vs a ``PoolClient`` wrapping ONE url (health
   prober on, breaker armed): the per-request cost of the selection /
   accounting / budget machinery when nothing is failing.
2. **Hedging under an injected slow replica** — a 2-replica pool where one
   replica sits behind a ChaosProxy ``latency`` fault: p99 with and
   without hedged requests. Round-robin sends half the requests into the
   slow replica; the hedge (fixed 5 ms delay) re-issues them to the fast
   one and takes the first success.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_pool.py [-o BENCH_POOL.json]
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_POOL.json")
    parser.add_argument("--requests", type=int, default=600)
    parser.add_argument("--slow-requests", type=int, default=300)
    parser.add_argument("--latency-s", type=float, default=0.02,
                        help="per-chunk proxy delay for the slow replica")
    parser.add_argument("--hedge-delay-s", type=float, default=0.005)
    args = parser.parse_args()

    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "armed-pool N=1 vs bare client (same server, same workload), "
            "then 2-replica pool with one replica behind a ChaosProxy "
            "latency fault: p99 with and without hedging"
        ),
    }

    server_a = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    server_b = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    proxy_b = ChaosProxy("127.0.0.1", server_b.port).start()
    try:
        # -- 1: armed-pool overhead at N=1 --------------------------------
        # bare -> pool -> bare again: the second bare run bounds the
        # container's run-to-run noise floor, so the overhead delta can be
        # read against it instead of being mistaken for signal
        def measure(endpoints=None):
            runner = PerfRunner(server_a.url, "http", "simple",
                                endpoints=endpoints)
            try:
                runner.run(1, 50)  # warmup
                return runner.run(1, args.requests)
            finally:
                runner.close()

        out["bare_client"] = measure()
        out["pool_n1"] = measure(endpoints=[server_a.url])
        out["bare_client_rerun"] = measure()

        bare_avgs = [out["bare_client"]["latency_ms"]["avg"],
                     out["bare_client_rerun"]["latency_ms"]["avg"]]
        bare_avg = sum(bare_avgs) / 2
        pool_avg = out["pool_n1"]["latency_ms"]["avg"]
        out["pool_n1_overhead_us_per_call"] = round(
            (pool_avg - bare_avg) * 1000.0, 2)
        out["ab_noise_floor_us"] = round(
            abs(bare_avgs[0] - bare_avgs[1]) * 1000.0, 2)
        out["pool_n1_overhead_pct_of_p50"] = round(
            100.0 * (pool_avg - bare_avg)
            / max(out["bare_client"]["latency_ms"]["p50"], 1e-9), 2)

        # -- 2: tail latency under a slow replica, hedged vs not ----------
        proxy_b.fault = Fault("latency", latency_s=args.latency_s)
        endpoints = [server_a.url, proxy_b.url]

        unhedged = PerfRunner(server_a.url, "http", "simple",
                              endpoints=endpoints)
        try:
            out["slow_replica_unhedged"] = unhedged.run(1, args.slow_requests)
        finally:
            unhedged.close()

        hedged = PerfRunner(server_a.url, "http", "simple",
                            endpoints=endpoints, hedge=True,
                            hedge_delay_s=args.hedge_delay_s)
        try:
            out["slow_replica_hedged"] = hedged.run(1, args.slow_requests)
        finally:
            hedged.close()

        p99_un = out["slow_replica_unhedged"]["latency_ms"]["p99"]
        p99_he = out["slow_replica_hedged"]["latency_ms"]["p99"]
        out["hedge_config"] = {
            "slow_replica_latency_s": args.latency_s,
            "hedge_delay_s": args.hedge_delay_s,
            "routing": "round_robin over [fast, slow]",
        }
        out["hedge_p99_improvement"] = {
            "unhedged_p99_ms": p99_un,
            "hedged_p99_ms": p99_he,
            "speedup_x": round(p99_un / max(p99_he, 1e-9), 2),
        }
    finally:
        proxy_b.stop()
        server_a.stop()
        server_b.stop()

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
