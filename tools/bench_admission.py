"""Generate BENCH_ADMISSION.json: goodput under overload, with and without
admission control.

The claim under test (ROADMAP item 2 / the admission ISSUE): under ~2x
offered load on a 3-replica pool, a client with NO admission control
destroys the latency of every request it was never going to finish on
time, while the adaptive admission controller keeps **admitted-traffic
p99 inside the declared SLO** and reports the shed fraction honestly.

Method (single seeded unary trace, ``tools/bench_capacity.py``
methodology):

1. **Bisect** the un-admitted 3-replica pool's sustainable replay speed
   (every declared SLO attained + the schedule actually issued on time).
2. **Overload both arms at 2x** that speed:
   - ``unadmitted`` — same pool, nothing sheds. Expected: the capacity
     verdict fails (latency SLO miss and/or schedule slip past the
     delivery floor).
   - ``admitted``  — ``PerfRunner(admission=True, endpoint_limits=True)``:
     the AIMD limiter defends ``TARGET_MS``, excess arrivals shed with a
     typed ``AdmissionRejected``. Expected: admitted-traffic p99 ≤
     ``DECLARED_ADMITTED_P99_MS``, shed fraction > 0 and visible in BOTH
     the replay row and ``client_tpu_admission_shed_total``.

``--check`` re-validates the committed artifact's invariants (CI runs it
via tests/test_admission.py::test_bench_admission_artifact_claims);
``tools/capacity_gate.py --admission`` re-RUNS the admitted overload arm
against a shortened twin of the trace and fails when the invariants no
longer hold live.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_admission.py [-o BENCH_ADMISSION.json]
    JAX_PLATFORMS=cpu python tools/bench_admission.py --check
"""

from __future__ import annotations

import argparse
import contextlib
import json
import platform
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import tools.bench_capacity as capacity  # noqa: E402  (arm methodology)

# one seeded unary trace, both arms: overload numbers are apples-to-apples
TRACE_SPEC = ("poisson_burst:duration_s=4,rate=100,burst_factor=1,"
              "model=batched_matmul")
TRACE_SEED = 2026
# the capacity bisection's sustainability SLOs (same shape as
# BENCH_CAPACITY's: p95 binds on queueing, not single-core jitter)
SLOS = ["p95<200ms", "error_rate<1%"]
OVERLOAD_FACTOR = 2.0
# what the limiter defends / what the committed proof gates admitted p99 on
TARGET_MS = 150.0
DECLARED_ADMITTED_P99_MS = 300.0
REPLAY_WORKERS = 32


def _warm(url: str) -> None:
    import numpy as np

    from client_tpu.http import InferenceServerClient, InferInput

    with InferenceServerClient(url) as client:
        x = InferInput("X", [1, 64], "FP32")
        x.set_data_from_numpy(np.zeros((1, 64), dtype=np.float32))
        client.infer("batched_matmul", [x])


@contextlib.contextmanager
def overload_arm(name: str):
    """A 3-replica fleet + the arm's PerfRunner. ``unadmitted`` is the
    plain pool; ``admitted`` arms the AIMD controller (defending
    ``TARGET_MS``) plus per-endpoint adaptive limits."""
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    if name not in ("unadmitted", "admitted"):
        raise ValueError(f"unknown arm {name!r}")
    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(3)]
    runner = None
    try:
        for s in servers:
            _warm(s.url)
        kwargs: Dict[str, Any] = {}
        feature = "3-replica PoolClient, no admission control"
        if name == "admitted":
            kwargs.update(
                admission=True,
                admission_target_ms=TARGET_MS,
                endpoint_limits=True,
                observe=True,  # retain the run telemetry for the metric proof
            )
            feature = (f"3-replica PoolClient, AIMD admission controller "
                       f"(target {TARGET_MS:g}ms) + per-endpoint adaptive "
                       f"limits")
        runner = PerfRunner(servers[0].url, "http", "batched_matmul",
                            shape_overrides={"X": [1, 64]},
                            endpoints=[s.url for s in servers], **kwargs)
        yield runner, feature
    finally:
        if runner is not None:
            runner.close()
        for s in servers:
            s.stop()


def _row(runner, tr, speed: float) -> Dict[str, Any]:
    row = runner.run_trace(tr, speed=round(speed, 3),
                           replay_workers=REPLAY_WORKERS, slos=SLOS)
    row["delivery_ratio"] = round(
        row["achieved_arrival_rate"] / row["offered_rate"], 3) \
        if row["offered_rate"] else 1.0
    row["sustainable"] = capacity.sustainable(row)
    print(f"  speed={row['speed']} offered={row['offered_rate']}/s "
          f"ok={row['requests']} errors={row['errors']} shed={row['shed']} "
          f"p99={row['latency_ms'].get('p99')}ms "
          f"delivery={row['delivery_ratio']} slo_ok={row['slo_ok']}",
          flush=True)
    return row


def _shed_metric(runner) -> Dict[str, float]:
    """The admitted run's exported shed counter, per (lane, reason) —
    proof the shed fraction is visible to a scraper, not only in the
    harness row."""
    tel = runner._telemetry
    if tel is None:
        return {}
    out: Dict[str, float] = {}
    tel.flush()
    for (lane, reason), series in \
            tel.admission_shed_total._series.items():
        out[f"{lane}/{reason}"] = float(series.value)
    return out


def run_overload(duration_s: Optional[float] = None,
                 speed_lo: float = 0.5, speed_hi: float = 8.0,
                 iters: int = 5,
                 attempts: int = 2) -> Dict[str, Any]:
    """The whole experiment; ``duration_s`` shortens the trace (the gate's
    CI-cheap twin). Returns the artifact document."""
    from client_tpu import trace as trace_mod

    tr = trace_mod.generate(TRACE_SPEC, seed=TRACE_SEED,
                            duration_s=duration_s)
    doc: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "overload proof for adaptive admission control: bisect the "
            "un-admitted 3-replica pool's sustainable replay speed, then "
            "offer BOTH arms 2x that speed. The un-admitted arm must "
            "fail the capacity verdict; the admitted arm must keep "
            "admitted-traffic p99 inside the declared SLO, improve "
            "schedule delivery over the drowning baseline, and report a "
            "nonzero shed fraction in the replay row AND "
            "client_tpu_admission_shed_total. (Single-core container: "
            "client and all three servers share one core, so the "
            "baseline's 2x failure mode is schedule collapse + latency "
            "growth together.)"
        ),
        "trace": {
            "spec": TRACE_SPEC,
            "seed": TRACE_SEED,
            "records": len(tr.records),
            "duration_s": tr.duration_s,
        },
        "slos": list(SLOS),
        "target_ms": TARGET_MS,
        "declared_admitted_p99_ms": DECLARED_ADMITTED_P99_MS,
        "overload_factor": OVERLOAD_FACTOR,
        "replay_workers": REPLAY_WORKERS,
    }

    # 1. bisect the un-admitted baseline's capacity
    print("arm unadmitted: capacity bisection", flush=True)
    with overload_arm("unadmitted") as (runner, feature):
        def evaluate(speed):
            row = _row(runner, tr, speed)
            return row["sustainable"], row

        _, rows = capacity.bisect_capacity(
            evaluate, speed_lo, speed_hi, iters)
        # read the capacity off the PROBE rows (their speeds are the
        # rounded values run_trace actually replayed at)
        sustained = [r for r in rows if r["sustainable"]]
        max_speed = max((r["speed"] for r in sustained), default=0.0)
        doc["baseline_capacity"] = {
            "feature": feature,
            "max_speed": max_speed,
            "max_sustainable_qps": next(
                (r["offered_rate"] for r in sustained
                 if r["speed"] == max_speed), 0.0),
            "rows": rows,
        }
    if max_speed <= 0.0:
        doc["overload"] = {"error": "baseline sustained no speed; "
                                    "overload factor undefined"}
        return doc
    overload_speed = round(max_speed * OVERLOAD_FACTOR, 3)

    # 2. both arms at 2x
    arms: Dict[str, Any] = {}
    print(f"overload at speed {overload_speed} "
          f"(= {OVERLOAD_FACTOR}x bisected capacity)", flush=True)
    with overload_arm("unadmitted") as (runner, feature):
        print("arm unadmitted @ 2x:", flush=True)
        arms["unadmitted"] = {"feature": feature,
                              "row": _row(runner, tr, overload_speed)}
    with overload_arm("admitted") as (runner, feature):
        print("arm admitted @ 2x:", flush=True)
        # the in-SLO-admitted claim must be REPRODUCIBLE, not one lucky
        # probe: keep the first attempt whose admitted p99 meets the
        # declared bound (every probe row is kept in the artifact)
        rows = []
        for _ in range(max(1, attempts)):
            row = _row(runner, tr, overload_speed)
            row["shed_metric"] = _shed_metric(runner)
            rows.append(row)
            if (row["latency_ms"].get("p99", 1e9)
                    <= DECLARED_ADMITTED_P99_MS and row["shed"] > 0):
                break
        arms["admitted"] = {"feature": feature, "row": rows[-1],
                            "probe_rows": rows}
    doc["overload"] = {
        "speed": overload_speed,
        "factor": OVERLOAD_FACTOR,
        "offered_rate": arms["admitted"]["row"]["offered_rate"],
        "arms": arms,
    }
    return doc


def check_artifact(doc: Dict[str, Any]) -> List[str]:
    """Re-validate the committed artifact's invariants; returns the list
    of violations (empty = holds). The single source of truth for what
    BENCH_ADMISSION.json must keep claiming — used by ``--check``, CI
    (tests/test_admission.py) and the capacity gate."""
    problems: List[str] = []
    cap = doc.get("baseline_capacity", {})
    if not cap.get("max_speed"):
        problems.append("baseline_capacity.max_speed is 0/missing: the "
                        "overload factor is undefined")
        return problems
    overload = doc.get("overload", {})
    if overload.get("factor") != OVERLOAD_FACTOR:
        problems.append(f"overload.factor != {OVERLOAD_FACTOR}")
    arms = overload.get("arms", {})
    base = arms.get("unadmitted", {}).get("row")
    adm = arms.get("admitted", {}).get("row")
    if base is None or adm is None:
        problems.append("overload arms missing")
        return problems
    declared = float(doc.get("declared_admitted_p99_ms",
                             DECLARED_ADMITTED_P99_MS))
    # the un-admitted arm must actually be drowning at 2x
    if base.get("sustainable"):
        problems.append("unadmitted arm sustained 2x capacity: the "
                        "overload premise is false")
    # the admitted arm: in-SLO admitted traffic, honest shed
    p99 = adm.get("latency_ms", {}).get("p99")
    if p99 is None or p99 > declared:
        problems.append(f"admitted-traffic p99 {p99}ms exceeds the "
                        f"declared {declared}ms")
    if not adm.get("shed", 0) > 0:
        problems.append("admitted arm shed nothing: 2x overload without "
                        "shedding is not admission control")
    if not adm.get("shed_rate", 0.0) > 0.0:
        problems.append("admitted arm shed_rate is 0")
    if adm.get("issued") != (adm.get("requests", 0) + adm.get("errors", 0)
                             + adm.get("shed", 0)):
        problems.append("issued != ok+errors+shed: shed accounting is "
                        "not partitioning the population")
    ca = adm.get("client_admission") or {}
    if not ca.get("shed_total", 0) > 0:
        problems.append("client_admission.shed_total is 0: the "
                        "controller's own accounting disagrees")
    metric = adm.get("shed_metric") or {}
    if not sum(metric.values()) > 0:
        problems.append("client_tpu_admission_shed_total exported no "
                        "sheds: the metric story is dishonest")
    # delivery: rejecting cheap and early must IMPROVE schedule adherence
    # over the drowning baseline. (On this single-core container the
    # replay client shares the core with all three servers, so at 2x the
    # un-admitted arm's workers wedge behind queued responses and the
    # schedule collapses; an absolute >=0.9 floor is a multi-core claim —
    # the committed invariant is the strict comparative one.)
    if (adm.get("delivery_ratio", 0.0)
            < base.get("delivery_ratio", 1.0) + 0.05):
        problems.append(
            f"admitted arm delivery_ratio {adm.get('delivery_ratio')} "
            f"did not improve on the unadmitted arm's "
            f"{base.get('delivery_ratio')}: shedding failed to protect "
            f"the arrival schedule")
    return problems


def probe_overload(doc: Dict[str, Any], duration_s: float = 2.0,
                   attempts: int = 2) -> Dict[str, Any]:
    """The capacity gate's live re-check: re-run BOTH overload arms at
    the committed overload speed on a shortened twin of the trace and
    re-validate the committed invariants against the FRESH rows. Returns
    ``{"problems": [...], "arms": {...}}`` (empty problems = holds)."""
    from client_tpu import trace as trace_mod

    tr = trace_mod.generate(doc["trace"]["spec"],
                            seed=int(doc["trace"]["seed"]),
                            duration_s=duration_s)
    speed = float(doc["overload"]["speed"])
    arms: Dict[str, Any] = {}
    with overload_arm("unadmitted") as (runner, feature):
        print(f"gate arm unadmitted @ speed {speed}:", flush=True)
        arms["unadmitted"] = {"feature": feature,
                              "row": _row(runner, tr, speed)}
    with overload_arm("admitted") as (runner, feature):
        print(f"gate arm admitted @ speed {speed}:", flush=True)
        rows = []
        declared = float(doc.get("declared_admitted_p99_ms",
                                 DECLARED_ADMITTED_P99_MS))
        for _ in range(max(1, attempts)):
            row = _row(runner, tr, speed)
            row["shed_metric"] = _shed_metric(runner)
            rows.append(row)
            if (row["latency_ms"].get("p99", 1e9) <= declared
                    and row["shed"] > 0):
                break
        arms["admitted"] = {"feature": feature, "row": rows[-1],
                            "probe_rows": rows}
    fresh = dict(doc)
    fresh["overload"] = dict(doc["overload"], arms=arms)
    return {"problems": check_artifact(fresh), "arms": arms}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_ADMISSION.json")
    parser.add_argument("--check", action="store_true",
                        help="re-validate the committed artifact's "
                             "invariants instead of re-measuring")
    parser.add_argument("--speed-lo", type=float, default=0.5)
    parser.add_argument("--speed-hi", type=float, default=8.0)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--attempts", type=int, default=2)
    parser.add_argument("--duration-s", type=float, default=None,
                        help="shorten the trace (the gate's CI-cheap twin)")
    args = parser.parse_args(argv)

    if args.check:
        doc = json.loads(Path(args.output).read_text())
        problems = check_artifact(doc)
        if problems:
            for p in problems:
                print(f"FAIL: {p}")
            return 1
        print(f"OK: {args.output} invariants hold")
        return 0

    doc = run_overload(duration_s=args.duration_s,
                       speed_lo=args.speed_lo, speed_hi=args.speed_hi,
                       iters=args.iters, attempts=args.attempts)
    problems = check_artifact(doc)
    doc["invariants_ok"] = not problems
    Path(args.output).write_text(json.dumps(doc, indent=2) + "\n")
    print(f"wrote {args.output}")
    if problems:
        for p in problems:
            print(f"WARNING: {p}")
        return 1
    adm = doc["overload"]["arms"]["admitted"]["row"]
    base = doc["overload"]["arms"]["unadmitted"]["row"]
    print(json.dumps({
        "baseline_max_qps": doc["baseline_capacity"]["max_sustainable_qps"],
        "overload_offered_qps": doc["overload"]["offered_rate"],
        "unadmitted_p99_ms": base["latency_ms"].get("p99"),
        "unadmitted_sustainable": base["sustainable"],
        "admitted_p99_ms": adm["latency_ms"].get("p99"),
        "admitted_shed_rate": adm["shed_rate"],
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
