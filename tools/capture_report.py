"""Render a CHIP_CAPTURE_*.json into BASELINE-ready markdown tables.

The capture artifact is the measurement of record; this makes folding it
into BASELINE.md mechanical instead of hand-transcribed (the round-3
failure mode: session numbers cited without a committed artifact, the
"provenance split"). Run on whatever capture exists:

    python tools/capture_report.py CHIP_CAPTURE_2026-XX-XX.json [-o out.md]

Sections rendered (each skipped gracefully if its capture section failed):
matmul MFU (blocked vs pipelined), flash-attention sweep best config,
decode-attention exactness + pallas/einsum crossover, LLM serving-mode
comparison (decoupled vs generate-SSE vs sequence-batched), and the
data-plane headline from the bench section.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt(value, nd=2):
    if value is None:
        return "—"
    if isinstance(value, float):
        return f"{value:.{nd}f}"
    return str(value)


def render(capture: dict) -> str:
    out = []
    sections = capture.get("sections", {})
    probe = capture.get("probe", {})
    out.append(f"## Chip capture {capture.get('captured_utc', '?')}")
    out.append("")
    platform = probe.get("platform")
    for section in sections.values():
        if section.get("ok") and isinstance(section.get("data"), dict):
            platform = section["data"].get("platform", platform)
            break
    ok_count = sum(1 for s in sections.values() if s.get("ok"))
    out.append(f"Platform: **{platform or 'unknown'}** "
               f"({ok_count}/{len(sections)} sections ok)")
    out.append("")

    cb = sections.get("chip_bench", {})
    if cb.get("ok"):
        data = cb["data"]
        peak = data.get("peak_bf16_tflops")
        out.append("### MXU matmul (bf16)")
        out.append("")
        out.append("| N | blocked ms | blocked TF/s | pipelined ms | "
                   "pipelined TF/s | MFU |")
        out.append("|---|---|---|---|---|---|")
        matmul = data.get("matmul_bf16") or []
        if isinstance(matmul, dict):
            matmul = [matmul]
        for row in matmul:
            tflops = row.get("tflops")
            mfu = (tflops / peak) if (peak and tflops) else None
            out.append(
                f"| {row.get('n')} | {_fmt(row.get('ms_per_matmul_blocked'))} "
                f"| {_fmt(row.get('tflops_blocked'), 1)} "
                f"| {_fmt(row.get('ms_per_matmul_pipelined'))} "
                f"| {_fmt(tflops, 1)} | {_fmt(mfu, 3)} |")
        out.append("")
        out.append(f"Dispatch overhead: "
                   f"{_fmt(data.get('dispatch_overhead_ms'), 3)} ms/dispatch")
        out.append("")

    fs = sections.get("flash_sweep", {})
    if fs.get("ok"):
        data = fs["data"]
        best = data.get("best") or {}
        exact = data.get("exactness") or {}
        out.append("### Flash attention block sweep")
        out.append("")
        out.append(
            f"Shape {data.get('shape')}, mosaic_compiled="
            f"{data.get('mosaic_compiled')}: best block_q×block_k = "
            f"**{best.get('block_q')}×{best.get('block_k')}** at "
            f"{_fmt(best.get('ms_per_call'), 3)} ms "
            f"({_fmt(best.get('tflops'), 2)} TF/s); exactness "
            f"max_abs_diff={_fmt(exact.get('max_abs_diff'), 6)} "
            f"(tol {exact.get('tol')}, ok={exact.get('ok')})")
        out.append("")

    da = sections.get("decode_attn", {})
    if da.get("ok"):
        data = da["data"]
        exact = data.get("exactness") or {}
        out.append("### Flash-decoding kernel (single-query KV-cache)")
        out.append("")
        out.append(f"mosaic_compiled={data.get('mosaic_compiled')}, "
                   f"exactness ok={exact.get('ok')} "
                   f"over {len(exact.get('cases', []))} cases")
        out.append("")
        out.append("| batch | heads | max_len | fill | pallas ms | "
                   "einsum ms | pallas speedup |")
        out.append("|---|---|---|---|---|---|---|")
        latency = data.get("latency") or []
        if isinstance(latency, dict):
            latency = [latency]
        for row in latency:
            out.append(
                f"| {row.get('batch')} | {row.get('heads')} "
                f"| {row.get('max_len')} | {row.get('fill')} "
                f"| {_fmt(row.get('pallas_ms'), 3)} "
                f"| {_fmt(row.get('einsum_ms'), 3)} "
                f"| {_fmt(row.get('pallas_speedup'), 2)}x |")
        out.append("")
        if latency:
            faster = [r for r in latency
                      if (r.get("pallas_speedup") or 0) > 1.0]
            out.append(
                f"Serving-default evidence: pallas faster on "
                f"{len(faster)}/{len(latency)} measured shapes → default "
                f"`attention_impl=\""
                f"{'pallas' if len(faster) > len(latency) / 2 else 'einsum'}\"`"
                f" on this platform.")
            out.append("")

    gp = sections.get("genai_perf", {})
    if gp.get("ok"):
        data = gp["data"]
        out.append("### LLM serving modes (TTFT / ITL / token throughput)")
        out.append("")
        out.append("| mode | conc | sessions | ttft p50 ms | itl p50 ms | "
                   "tok/s | req/s | err |")
        out.append("|---|---|---|---|---|---|---|---|")
        for key in sorted(data):
            row = data[key]
            mode, _, conc = key.rpartition("_c")
            out.append(
                f"| {mode} | {conc} | {row.get('sessions')} "
                f"| {_fmt(row.get('ttft_ms', {}).get('p50'))} "
                f"| {_fmt(row.get('inter_token_ms', {}).get('p50'))} "
                f"| {_fmt(row.get('output_tokens_per_sec'), 1)} "
                f"| {_fmt(row.get('requests_per_sec'))} "
                f"| {row.get('errors')} |")
        out.append("")

    bench = sections.get("bench", {})
    if bench.get("ok"):
        data = bench["data"]
        out.append("### Data-plane headline (bench.py)")
        out.append("")
        out.append(f"{data.get('metric')}: **{_fmt(data.get('value'), 3)} "
                   f"{data.get('unit')}** ({_fmt(data.get('vs_baseline'), 1)}x "
                   f"vs wire)")
        out.append("")

    failed = {name: s.get("error") for name, s in sections.items()
              if not s.get("ok")}
    if failed:
        out.append("### Failed sections")
        out.append("")
        for name, error in failed.items():
            out.append(f"- {name}: {error}")
        out.append("")
    return "\n".join(out)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("capture", help="CHIP_CAPTURE_*.json path")
    parser.add_argument("-o", "--out", default=None,
                        help="write markdown here (default stdout)")
    args = parser.parse_args()
    with open(args.capture) as f:
        capture = json.load(f)
    text = render(capture)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
