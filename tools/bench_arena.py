"""Generate BENCH_ARENA.json: the pooled-shm-arena cost-model artifact.

The A/B the arena exists for, answered against a live in-process server:

1. **Per-use-site baseline** — the pre-arena data plane: every request
   creates its input/output regions, registers them, infers, unregisters
   and destroys them (exactly what perf.py's five copy-pasted blocks and
   bench.py used to do). Counters prove the churn: ~2 region creates and
   ~2 registration RPCs per request.
2. **Arena steady state** — the same workload through ``configure_arena``:
   after a short warmup the measured window must show region
   create/destroy ops == 0 and registration RPCs == 0 while map ops keep
   growing (requests ARE flowing), with p50 no worse than the baseline.
3. **64-caller size sweep** — concurrency 64 over payloads from 4 KiB to
   4 MiB through the arena path: the size-invariance claim (CHIP_BENCH's
   flat p50) restated under high concurrency on the shm data plane.

``--check`` re-validates an existing artifact's acceptance invariants and
exits non-zero on violation (wired in CI next to the capacity gate via
tests/test_arena.py::test_bench_arena_artifact_claims).

Usage::

    JAX_PLATFORMS=cpu python tools/bench_arena.py [-o BENCH_ARENA.json]
    JAX_PLATFORMS=cpu python tools/bench_arena.py --check BENCH_ARENA.json
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import threading
import time
import uuid
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _stats(times_s):
    times = sorted(times_s)

    def pct(q):
        return round(times[min(int(len(times) * q), len(times) - 1)] * 1e3, 4)

    return {"p50_ms": pct(0.50), "p90_ms": pct(0.90), "p99_ms": pct(0.99),
            "mean_ms": round(sum(times) / len(times) * 1e3, 4),
            "requests": len(times)}


def _rpc_total(snap, op):
    return sum(v for k, v in snap["rpcs"].items()
               if k.endswith(f".{op}.ok"))


def bench_per_use_site(client, httpclient, shm, x, requests):
    """One request = the full create/register/infer/unregister/destroy
    lifecycle, per use-site — the churn the arena amortizes away."""
    from client_tpu import observe

    recorder = observe.dataplane()
    before = recorder.snapshot()
    nbytes = x.nbytes
    times = []
    for _ in range(requests):
        t0 = time.perf_counter()
        name_in = f"abench_in_{uuid.uuid4().hex[:8]}"
        name_out = f"abench_out_{uuid.uuid4().hex[:8]}"
        rin = shm.create_shared_memory_region(name_in, f"/{name_in}", nbytes)
        rout = shm.create_shared_memory_region(name_out, f"/{name_out}", nbytes)
        try:
            shm.set_shared_memory_region(rin, [x])
            client.register_system_shared_memory(name_in, f"/{name_in}", nbytes)
            client.register_system_shared_memory(name_out, f"/{name_out}", nbytes)
            inp = httpclient.InferInput("INPUT0", list(x.shape), "FP32")
            inp.set_shared_memory(name_in, nbytes)
            out = httpclient.InferRequestedOutput("OUTPUT0")
            out.set_shared_memory(name_out, nbytes)
            client.infer("identity_fp32", [inp], outputs=[out])
            shm.get_contents_as_numpy(rout, np.float32, list(x.shape))
            client.unregister_system_shared_memory(name_in)
            client.unregister_system_shared_memory(name_out)
        finally:
            shm.destroy_shared_memory_region(rin)
            shm.destroy_shared_memory_region(rout)
        times.append(time.perf_counter() - t0)
    after = recorder.snapshot()
    fam = after["families"]["system"]
    fam0 = before["families"]["system"]
    row = _stats(times)
    row["regions_created_per_request"] = round(
        (fam["created"] - fam0["created"]) / requests, 3)
    row["regions_destroyed_per_request"] = round(
        (fam["destroyed"] - fam0["destroyed"]) / requests, 3)
    row["registration_rpcs_per_request"] = round(
        (_rpc_total(after, "register") - _rpc_total(before, "register"))
        / requests, 3)
    return row


def bench_arena(client, httpclient, arena, x, requests, warmup=30):
    """One request = stage into a lease (transparent promotion), infer with
    an arena-leased output, read the zero-copy view, release."""
    from client_tpu import observe

    recorder = observe.dataplane()
    client.configure_arena(arena)

    def step():
        inp = httpclient.InferInput("INPUT0", list(x.shape), "FP32")
        inp.set_data_from_numpy(x, arena=arena)
        out = arena.request_output("OUTPUT0", x.nbytes)
        result = client.infer("identity_fp32", [inp], outputs=[out])
        view = result.as_numpy("OUTPUT0")
        assert view.shape == x.shape
        result.release_arena()
        inp.release_arena_lease()

    for _ in range(warmup):
        step()
    before = recorder.snapshot()
    astats_before = arena.stats()
    times = []
    for _ in range(requests):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    after = recorder.snapshot()
    astats = arena.stats()
    fam = after["families"]["system"]
    fam0 = before["families"]["system"]
    row = _stats(times)
    leases = astats["leases"] - astats_before["leases"]
    row["steady_state"] = {
        "requests": requests,
        # THE acceptance numbers: zero region churn, zero registration
        # RPCs over the whole measured window
        "regions_created": int(fam["created"] - fam0["created"]),
        "regions_destroyed": int(fam["destroyed"] - fam0["destroyed"]),
        "registration_rpcs": int(
            _rpc_total(after, "register") - _rpc_total(before, "register")),
        # ...while map ops keep growing (requests really flowed via shm)
        "map_writes": int(fam["map_writes"] - fam0["map_writes"]),
        "map_reads": int(fam["map_reads"] - fam0["map_reads"]),
        "lease_hit_rate": round(
            (astats["hits"] - astats_before["hits"]) / leases, 4),
        "registrations_cached": int(astats["registrations_cached"]
                                    - astats_before["registrations_cached"]),
    }
    row["residual_leased_bytes"] = arena.stats()["leased_bytes"]
    return row


def bench_concurrency(url, httpclient, arena, nbytes, callers=64,
                      iters_per_caller=8):
    """64 callers, each re-staging its tensor into the arena per request
    (lease -> write once -> infer -> zero-copy read -> release)."""
    x = np.zeros((1, nbytes // 4), dtype=np.float32)
    times = []
    times_lock = threading.Lock()
    errors = []
    barrier = threading.Barrier(callers, timeout=60)

    def worker():
        try:
            client = httpclient.InferenceServerClient(url, concurrency=1)
            client.configure_arena(arena)
            barrier.wait()
            local = []
            for _ in range(iters_per_caller):
                t0 = time.perf_counter()
                inp = httpclient.InferInput("INPUT0", list(x.shape), "FP32")
                inp.set_data_from_numpy(x, arena=arena)
                out = arena.request_output("OUTPUT0", x.nbytes)
                result = client.infer("identity_fp32", [inp], outputs=[out])
                assert result.as_numpy("OUTPUT0").shape == x.shape
                result.release_arena()
                inp.release_arena_lease()
                local.append(time.perf_counter() - t0)
            client.close()
            with times_lock:
                times.extend(local)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(str(e))

    threads = [threading.Thread(target=worker) for _ in range(callers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    if errors:
        raise RuntimeError(f"concurrency arm failed: {errors[:3]}")
    row = _stats(times)
    row["callers"] = callers
    row["payload_bytes"] = nbytes
    return row


def check(path: str) -> int:
    data = json.loads(Path(path).read_text())
    failures = []
    steady = data["arena"]["steady_state"]
    if steady["regions_created"] != 0 or steady["regions_destroyed"] != 0:
        failures.append("steady-state region churn is not zero")
    if steady["registration_rpcs"] != 0:
        failures.append("steady-state registration RPCs are not zero")
    if steady["map_writes"] <= 0:
        failures.append("no map traffic in the steady-state window")
    if data["arena"]["residual_leased_bytes"] != 0:
        failures.append("leased bytes did not return to zero")
    if data["arena"]["p50_ms"] > (data["per_use_site"]["p50_ms"]
                                  + data["noise_floor_ms"]):
        failures.append("arena p50 regressed past the per-use-site baseline")
    for msg in failures:
        print(f"CHECK FAILED: {msg}", file=sys.stderr)
    if not failures:
        print(f"{path}: all arena acceptance invariants hold")
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_ARENA.json")
    parser.add_argument("--requests", type=int, default=300)
    parser.add_argument("--payload-bytes", type=int, default=256 * 1024)
    parser.add_argument("--sweep-bytes", type=int, nargs="*",
                        default=[4 * 1024, 256 * 1024, 4 * 1024 * 1024])
    parser.add_argument("--callers", type=int, default=64)
    parser.add_argument("--check", metavar="ARTIFACT",
                        help="validate an existing artifact instead of "
                             "benchmarking")
    args = parser.parse_args()
    if args.check:
        return check(args.check)

    import client_tpu.http as httpclient
    import client_tpu.utils.shared_memory as shm
    from client_tpu import observe
    from client_tpu.arena import ShmArena
    from client_tpu.models import default_model_zoo
    from client_tpu.server import HttpInferenceServer, ServerCore

    observe.enable_dataplane()
    x = np.zeros((1, args.payload_bytes // 4), dtype=np.float32)
    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "payload_bytes": args.payload_bytes,
        "note": (
            "per-use-site create/register/destroy per request vs pooled "
            "arena (size-class slabs, cached registrations); single-host "
            "in-process threaded HTTP server, CPU container numbers"
        ),
    }
    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    try:
        client = httpclient.InferenceServerClient(server.url, concurrency=4)
        arena = ShmArena()
        try:
            # noise floor: A/A of the arena arm (two identical short runs)
            aa1 = bench_arena(client, httpclient, arena, x, args.requests // 2)
            aa2 = bench_arena(client, httpclient, arena, x, args.requests // 2)
            out["noise_floor_ms"] = round(
                abs(aa1["p50_ms"] - aa2["p50_ms"]) + 0.02, 4)
            out["per_use_site"] = bench_per_use_site(
                client, httpclient, shm, x, args.requests)
            out["arena"] = bench_arena(
                client, httpclient, arena, x, args.requests)
            sweep = {}
            for nbytes in args.sweep_bytes:
                sweep[str(nbytes)] = bench_concurrency(
                    server.url, httpclient, arena, nbytes,
                    callers=args.callers)
            out["concurrency_sweep"] = {
                "callers": args.callers, "by_payload_bytes": sweep,
                "note": (
                    "single-core CPU container: 64 callers share one core "
                    "with the in-process server, so p50 tracks the "
                    "server-side identity memcpy, not the client data "
                    "plane; the steady-state A/B rows above are the "
                    "size-independent client-side cost evidence (on TPU "
                    "hardware CHIP_BENCH's ~0.8 ms p50 size-invariance is "
                    "the matching number)"),
            }
            out["arena_stats_final"] = arena.stats()
        finally:
            client.close()
            arena.close(force=True)
    finally:
        server.close()
        observe.install_dataplane(None)
    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
