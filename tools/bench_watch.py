"""Generate BENCH_WATCH.json: the continuous-monitoring overhead and
time-to-detect proof.

Seven measurements back the watchtower's claims:

1. **Disabled path** — a process with no watchtower armed pays exactly
   one attribute-read branch on the flight commit path (``_commit_tap
   is None``) and one on the metrics scrape path (``if self._drains``).
   Both are timed in chunks; the committed medians are the
   ~nanoseconds-when-off claim.

2. **Enabled tick cost** — a populated telemetry (SLOs, stream windows,
   live registry) under a real :class:`~client_tpu.watch.Watchtower`:
   the full tick (fold + burn + gauges + changepoints + blackbox drain)
   timed over hundreds of ticks.

3. **Chaos: latency** — 3 replicas, one behind a 50 ms latency proxy
   armed mid-run: time-to-detect until an alert NAMES the faulted
   endpoint (via the flight tail divergence), detection strictly inside
   the fault window.

4. **Chaos: byzantine** — 2 honest replicas + 1 live byzantine server
   lying on every response: the quarantine watermark must fire and name
   the liar's url.

5. **Chaos: cell blackhole** — a 2-cell federation whose home cell goes
   dark mid-run: the ``cells_down`` watermark must fire and name the
   cell.

6. **A/A soak** — the same 3-replica topology with NO fault: the
   watchtower must fire ZERO alerts over the whole soak (the
   false-positive bar for the seeded detectors and burn thresholds).

7. **kill -9 reconstruction** — a child process serving live traffic
   with the black box armed is SIGKILLed mid-run (after an alert
   fired); ``doctor --blackbox`` must reconstruct timelines, metric
   snapshots and the last alert from the ring file alone.

``--check`` re-validates the committed artifact (CI'd by
``tests/test_watch.py::test_bench_watch_artifact_claims``);
``tools/capacity_gate.py --watch`` re-runs the A/A and detection arms
live.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_watch.py [-o BENCH_WATCH.json]
    JAX_PLATFORMS=cpu python tools/bench_watch.py --check [BENCH_WATCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BRANCH_OPS = 200_000
TICKS = 400
CHAOS_LATENCY_S = 0.05
FAULT_BUDGET_S = 90.0
AA_REQUESTS = 480
KILL9_TIMEOUT_S = 60.0


def _percentiles(samples_ns: List[float]) -> Dict[str, float]:
    from client_tpu.utils import sorted_percentile

    s = sorted(samples_ns)
    return {
        "p50": round(sorted_percentile(s, 0.5), 1),
        "p90": round(sorted_percentile(s, 0.9), 1),
        "p99": round(sorted_percentile(s, 0.99), 1),
    }


def _simple_inputs():
    import numpy as np

    import client_tpu.http as httpclient

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    in0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
    in0.set_data_from_numpy(a)
    in1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
    in1.set_data_from_numpy(b)
    return [in0, in1]


def bench_disabled() -> Dict[str, Any]:
    """The two branches every hot path pays when NO watchtower is armed:
    the flight commit tap check and the registry drains check."""
    from client_tpu.flight import FlightRecorder
    from client_tpu.observe import MetricsRegistry

    rec = FlightRecorder(capacity=8)
    reg = MetricsRegistry()
    assert rec._commit_tap is None and reg._drains == []
    chunk = 1000
    chunks: List[float] = []
    for _ in range(BRANCH_OPS // chunk):
        t0 = time.perf_counter_ns()
        for _ in range(chunk):
            if rec._commit_tap is not None:  # the flight-commit branch
                raise AssertionError
            if reg._drains:  # the metrics-scrape branch
                raise AssertionError
        chunks.append((time.perf_counter_ns() - t0) / chunk)
    return {
        "ops": BRANCH_OPS,
        "branch_ns": _percentiles(chunks),
        "note": "both disabled-path branches together (commit tap is "
                "None + drains list empty), per-op over 1k-op chunks",
    }


def bench_tick() -> Dict[str, Any]:
    """Full tick cost over a POPULATED telemetry: SLOs with traffic in
    their windows, stream windows feeding changepoint detectors, and a
    black-box ring draining periodic metric snapshots."""
    import random

    from client_tpu.flight import FlightRecorder
    from client_tpu.observe import Telemetry
    from client_tpu.watch import Watchtower

    rng = random.Random(0xBE9C)
    rec = FlightRecorder(rng=random.Random(1), baseline_ratio=0.1)
    tel = Telemetry(sample="off", flight=rec)
    slo_fast = tel.track_slo("req_p95", "request_ms", 50.0,
                             objective=0.95, window_s=60.0)
    slo_ttft = tel.track_slo("ttft_p99", "ttft_ms", 200.0,
                             objective=0.99, window_s=60.0)
    with tempfile.TemporaryDirectory() as tmp:
        wt = Watchtower(tel, interval_s=0.05,
                        blackbox=os.path.join(tmp, "tick.bbx"),
                        metrics_every_ticks=10)
        try:
            for _ in range(TICKS):
                for _ in range(8):  # fresh samples between ticks
                    slo_fast.observe(abs(rng.gauss(8.0, 3.0)))
                    slo_ttft.observe(abs(rng.gauss(40.0, 10.0)))
                wt.tick()
            stats = wt.stats()
        finally:
            wt.stop()
    return {
        "ticks": stats["ticks"],
        "tick_ns": stats["tick_ns"],
        "alerts_fired_total": stats["alerts_fired_total"],
        "blackbox": stats["blackbox"],
    }


def _drive(pool, wt, n: int, tick_every: int = 8) -> None:
    for i in range(n):
        pool.infer("simple", _simple_inputs())
        if i % tick_every == tick_every - 1:
            wt.tick()


def _first_named(wt, needle: str) -> Optional[Dict[str, Any]]:
    """The first firing alert whose evidence names ``needle`` (active
    alerts refresh their evidence every tick; history keeps edges)."""
    candidates = [a.as_dict() for a in wt.active_alerts()]
    candidates += list(wt.history())
    for alert in candidates:
        if alert["state"] != "firing":
            continue
        ev = alert.get("evidence") or {}
        div = ev.get("divergence") or {}
        named = " ".join(str(x) for x in (
            ev.get("moved"), div.get("dominant"),
            ev.get("urls"), ev.get("cells")))
        if needle in named:
            return alert
    return None


def bench_chaos_latency() -> Dict[str, Any]:
    """Time-to-detect a latency-faulted replica, by name."""
    import random

    from client_tpu.flight import FlightRecorder
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.pool import PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault
    from client_tpu.watch import Watchtower

    core = ServerCore(default_model_zoo())
    servers = [HttpInferenceServer(core).start() for _ in range(3)]
    proxy = ChaosProxy("127.0.0.1", servers[0].port).start()
    faulted_url = f"127.0.0.1:{proxy.port}"
    urls = [faulted_url] + [f"127.0.0.1:{s.port}" for s in servers[1:]]
    rec = FlightRecorder(rng=random.Random(1), capacity=48,
                         slow_quantile=0.8, threshold_window=96,
                         threshold_min_samples=48, baseline_ratio=0.05)
    tel = Telemetry(sample="always", flight=rec)
    tel.track_slo("req_p95", "request_ms", 50.0, objective=0.95,
                  window_s=12.0)
    wt = Watchtower(tel, interval_s=0.2, fast_window_s=4.0,
                    cusum_warmup=6, min_stream_count=4)
    pool = PoolClient(urls, protocol="http", telemetry=tel,
                      routing="round_robin", health_interval_s=None)
    named = None
    detected_s = None
    try:
        _drive(pool, wt, 96)  # healthy baseline
        baseline_fired = wt.stats()["alerts_fired_total"]
        proxy.fault = Fault("latency", latency_s=CHAOS_LATENCY_S)
        proxy.reset_active()
        fault_t0 = time.monotonic()
        while time.monotonic() - fault_t0 < FAULT_BUDGET_S:
            _drive(pool, wt, 32)
            named = _first_named(wt, faulted_url)
            if named:
                detected_s = time.monotonic() - fault_t0
                break
        fault_duration_s = time.monotonic() - fault_t0
        proxy.heal()
    finally:
        pool.close()
        wt.stop()
        proxy.stop()
        for s in servers:
            s.stop()
    return {
        "chaos_latency_ms": CHAOS_LATENCY_S * 1e3,
        "faulted_url": faulted_url,
        "baseline_alerts": baseline_fired,
        "detected": named is not None,
        "detect_s": round(detected_s, 3) if detected_s else None,
        "fault_duration_s": round(fault_duration_s, 3),
        "fault_budget_s": FAULT_BUDGET_S,
        "alert_kind": named["kind"] if named else None,
        "alert_source": named["source"] if named else None,
    }


def bench_chaos_byzantine() -> Dict[str, Any]:
    """Time-to-detect a byzantine replica: the quarantine watermark must
    fire and name the liar's url."""
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.pool import PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ByzantineHttpServer
    from client_tpu.watch import Watchtower

    honest = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
              for _ in range(2)]
    byz = ByzantineHttpServer(
        ServerCore(default_model_zoo()),
        kinds=("shape_lie", "truncate", "garbage_json"), seed=0xB12A)
    byz.start()
    byz_url = byz.url.replace("http://", "")
    tel = Telemetry(sample="off")
    wt = Watchtower(tel, interval_s=0.1, changepoint=False)
    pool = PoolClient(
        [s.url for s in honest] + [byz.url], protocol="http",
        routing="round_robin", health_interval_s=None, telemetry=tel,
        quarantine_after=3, quarantine_window_s=30.0)
    named = None
    detected_s = None
    t0 = time.monotonic()
    try:
        while time.monotonic() - t0 < FAULT_BUDGET_S:
            _drive(pool, wt, 16, tick_every=4)
            named = _first_named(wt, byz_url)
            if named:
                detected_s = time.monotonic() - t0
                break
        duration_s = time.monotonic() - t0
    finally:
        pool.close()
        wt.stop()
        byz.stop()
        for s in honest:
            s.stop()
    return {
        "byzantine_url": byz_url,
        "detected": named is not None,
        "detect_s": round(detected_s, 3) if detected_s else None,
        "fault_duration_s": round(duration_s, 3),
        "alert_kind": named["kind"] if named else None,
        "alert_source": named["source"] if named else None,
    }


def bench_chaos_blackhole() -> Dict[str, Any]:
    """Time-to-detect a blackholed home cell: the cells_down watermark
    must fire and name the cell."""
    from client_tpu.federation import FederatedClient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.resilience import CircuitBreaker
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosCell, ChaosProxy
    from client_tpu.watch import Watchtower

    cores = [ServerCore(default_model_zoo()) for _ in range(2)]
    servers = [HttpInferenceServer(c).start() for c in cores]
    proxies = [ChaosProxy("127.0.0.1", s.port).start() for s in servers]
    cell_a = ChaosCell([proxies[0]])
    tel = Telemetry(sample="off")
    wt = Watchtower(tel, interval_s=0.1, changepoint=False)
    fed = FederatedClient(
        {"a": [proxies[0].url], "b": [proxies[1].url]}, home="a",
        protocol="http", telemetry=tel,
        cell_breaker_factory=lambda: CircuitBreaker(
            min_calls=2, recovery_time_s=30.0),
        default_deadline_s=8.0, per_attempt_timeout_s=0.5,
        pool_kwargs={"health_interval_s": None})
    named = None
    detected_s = None
    try:
        for _ in range(10):  # healthy warm-up through the home cell
            fed.infer("simple", _simple_inputs(), client_timeout=8.0)
        wt.tick()
        baseline_fired = wt.stats()["alerts_fired_total"]
        cell_a.blackhole()
        t0 = time.monotonic()
        while time.monotonic() - t0 < FAULT_BUDGET_S:
            for _ in range(4):
                fed.infer("simple", _simple_inputs(), client_timeout=8.0)
                wt.tick()
            named = _first_named(wt, "a")
            if named:
                detected_s = time.monotonic() - t0
                break
        duration_s = time.monotonic() - t0
        cell_a.heal(reset_active=True)
    finally:
        fed.close()
        wt.stop()
        for p in proxies:
            p.stop()
        for s in servers:
            s.stop()
    return {
        "blackholed_cell": "a",
        "baseline_alerts": baseline_fired,
        "detected": named is not None,
        "detect_s": round(detected_s, 3) if detected_s else None,
        "fault_duration_s": round(duration_s, 3),
        "alert_kind": named["kind"] if named else None,
        "alert_source": named["source"] if named else None,
    }


def bench_aa_soak() -> Dict[str, Any]:
    """A/A: the latency-arm topology with NO fault — the watchtower must
    fire zero alerts across the whole soak."""
    import random

    from client_tpu.flight import FlightRecorder
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.pool import PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.watch import Watchtower

    core = ServerCore(default_model_zoo())
    servers = [HttpInferenceServer(core).start() for _ in range(3)]
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    rec = FlightRecorder(rng=random.Random(1), capacity=48,
                         slow_quantile=0.8, threshold_window=96,
                         threshold_min_samples=48, baseline_ratio=0.05)
    tel = Telemetry(sample="always", flight=rec)
    tel.track_slo("req_p95", "request_ms", 50.0, objective=0.95,
                  window_s=12.0)
    pool = PoolClient(urls, protocol="http", telemetry=tel,
                      routing="round_robin", health_interval_s=None)
    try:
        for _ in range(32):  # jit/connection warm-up outside the watch
            pool.infer("simple", _simple_inputs())
        wt = Watchtower(tel, interval_s=0.2, fast_window_s=4.0,
                        cusum_warmup=6, min_stream_count=4)
        t0 = time.monotonic()
        _drive(pool, wt, AA_REQUESTS)
        elapsed = time.monotonic() - t0
        stats = wt.stats()
        wt.stop()
    finally:
        pool.close()
        for s in servers:
            s.stop()
    return {
        "requests": AA_REQUESTS,
        "elapsed_s": round(elapsed, 3),
        "ticks": stats["ticks"],
        "alerts_fired_total": stats["alerts_fired_total"],
        "changepoint_trips": stats["changepoint_trips"],
    }


_KILL9_CHILD = r"""
import os, random, sys
sys.path.insert(0, {root!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import client_tpu.http as httpclient
from client_tpu.flight import FlightRecorder
from client_tpu.models import default_model_zoo
from client_tpu.observe import Telemetry
from client_tpu.pool import PoolClient
from client_tpu.server import HttpInferenceServer, ServerCore
from client_tpu.watch import Watchtower

ring = sys.argv[1]
core = ServerCore(default_model_zoo())
server = HttpInferenceServer(core).start()
rec = FlightRecorder(rng=random.Random(1), baseline_ratio=1.0)
tel = Telemetry(sample="always", flight=rec)
# an impossible objective so the burn alert fires quickly and the ring
# provably carries an alert record before the parent pulls the plug
tel.track_slo("req_p99", "request_ms", 0.01, objective=0.9, window_s=8.0)
wt = Watchtower(tel, interval_s=0.05, blackbox=ring,
                metrics_every_ticks=2, changepoint=False)
pool = PoolClient(["127.0.0.1:" + str(server.port)],
                  protocol="http", telemetry=tel, routing="round_robin",
                  health_interval_s=None)
a = np.arange(16, dtype=np.int32).reshape(1, 16)
b = np.ones((1, 16), dtype=np.int32)
i = 0
while True:  # runs until SIGKILL — no clean shutdown, ever
    in0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
    in0.set_data_from_numpy(a)
    in1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
    in1.set_data_from_numpy(b)
    pool.infer("simple", [in0, in1])
    i += 1
    if i % 4 == 0:
        wt.tick()
"""


def bench_kill9() -> Dict[str, Any]:
    """SIGKILL a child mid-replay; ``doctor --blackbox`` must rebuild
    the story from the ring file alone."""
    from client_tpu.watch import read_blackbox

    root = str(Path(__file__).resolve().parent.parent)
    with tempfile.TemporaryDirectory() as tmp:
        ring = os.path.join(tmp, "kill9.bbx")
        script = os.path.join(tmp, "child.py")
        Path(script).write_text(_KILL9_CHILD.format(root=root))
        child = subprocess.Popen(
            [sys.executable, script, ring],
            cwd=root, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        saw = set()
        t0 = time.monotonic()
        try:
            while time.monotonic() - t0 < KILL9_TIMEOUT_S:
                if child.poll() is not None:
                    raise RuntimeError("kill9 child exited prematurely")
                if os.path.exists(ring):
                    rep = read_blackbox(ring)
                    saw = {r.kind for r in rep.records}
                    if {"timeline", "metrics", "alert"} <= saw:
                        break
                time.sleep(0.25)
        finally:
            # kill -9, no shutdown hooks: the ring is all that survives
            if child.poll() is None:
                os.kill(child.pid, signal.SIGKILL)
            child.wait()
        armed = {"timeline", "metrics", "alert"} <= saw
        report = os.path.join(tmp, "report.json")
        proc = subprocess.run(
            [sys.executable, "-m", "client_tpu.doctor",
             "--blackbox", ring, "--json", report],
            cwd=root, capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        doc: Dict[str, Any] = {}
        if proc.returncode == 0 and os.path.exists(report):
            doc = json.loads(Path(report).read_text())
    return {
        "armed_before_kill": armed,
        "record_kinds": sorted(saw),
        "doctor_rc": proc.returncode,
        "reconstruction_ok": bool(doc.get("ok")),
        "timelines_recovered": doc.get("timelines_recovered", 0),
        "metrics_snapshots_recovered": doc.get(
            "metrics_snapshots_recovered", 0),
        "last_alert_kind": (doc.get("last_alert") or {}).get("kind"),
        "scan": doc.get("scan"),
    }


def check(doc: Dict[str, Any]) -> int:
    """Re-validate the committed artifact's invariants; 0 = all hold."""
    problems: List[str] = []
    disabled = doc["disabled"]
    if disabled["branch_ns"]["p50"] > 250.0:
        problems.append(
            f"disabled-path branch median {disabled['branch_ns']['p50']} "
            "ns is not the claimed one-branch cost")
    tick = doc["tick"]
    if not tick["tick_ns"] or tick["tick_ns"]["p50"] <= 0:
        problems.append("enabled tick cost was not measured")
    if tick["tick_ns"] and tick["tick_ns"]["p50"] > 5e6:
        problems.append(
            f"enabled tick median {tick['tick_ns']['p50']} ns exceeds "
            "the 5 ms budget")
    if tick["alerts_fired_total"] != 0:
        problems.append("tick-cost arm fired alerts on healthy traffic")
    for arm in ("chaos_latency", "chaos_byzantine", "chaos_blackhole"):
        row = doc[arm]
        if not row["detected"]:
            problems.append(f"{arm}: the fault was never detected by name")
            continue
        if row["detect_s"] is None \
                or row["detect_s"] > row["fault_duration_s"] + 1e-9:
            problems.append(
                f"{arm}: detection ({row['detect_s']}s) did not land "
                f"inside the fault window ({row['fault_duration_s']}s)")
    if doc["chaos_latency"].get("baseline_alerts", 0) != 0:
        problems.append("chaos_latency fired alerts during the healthy "
                        "baseline phase")
    aa = doc["aa_soak"]
    if aa["alerts_fired_total"] != 0:
        problems.append(
            f"A/A soak fired {aa['alerts_fired_total']} alerts — the "
            "zero-false-positive bar does not hold")
    if aa["ticks"] <= 0 or aa["requests"] <= 0:
        problems.append("A/A soak did not actually run")
    k9 = doc["kill9"]
    if not k9["armed_before_kill"]:
        problems.append("kill9 child never wrote timeline+metrics+alert "
                        "records before the kill")
    if k9["doctor_rc"] != 0 or not k9["reconstruction_ok"]:
        problems.append("doctor --blackbox could not reconstruct from "
                        "the ring after kill -9")
    if k9["timelines_recovered"] <= 0:
        problems.append("kill9 reconstruction recovered no timelines")
    if k9["metrics_snapshots_recovered"] <= 0:
        problems.append("kill9 reconstruction recovered no metric "
                        "snapshots")
    if not k9["last_alert_kind"]:
        problems.append("kill9 reconstruction recovered no alert")
    for p in problems:
        print(f"CHECK FAIL: {p}")
    if not problems:
        print("CHECK OK: all committed continuous-monitoring claims hold")
    return 1 if problems else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("artifact", nargs="?", default=None,
                        help="artifact path for --check (defaults to -o)")
    parser.add_argument("-o", "--output", default="BENCH_WATCH.json")
    parser.add_argument("--check", action="store_true",
                        help="validate the committed artifact instead of "
                             "re-measuring")
    args = parser.parse_args(argv)

    if args.check:
        path = args.artifact or args.output
        return check(json.loads(Path(path).read_text()))

    doc: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": {
            "python": platform.python_version(),
            "machine": platform.machine(),
            "system": platform.system(),
        },
    }
    print("1/7 disabled-path branch cost ...")
    doc["disabled"] = bench_disabled()
    print(f"    p50 {doc['disabled']['branch_ns']['p50']} ns")
    print("2/7 enabled tick cost ...")
    doc["tick"] = bench_tick()
    print(f"    tick p50 {doc['tick']['tick_ns']['p50']} ns over "
          f"{doc['tick']['ticks']} ticks")
    print("3/7 chaos: latency-faulted replica ...")
    doc["chaos_latency"] = bench_chaos_latency()
    print(f"    detected={doc['chaos_latency']['detected']} in "
          f"{doc['chaos_latency']['detect_s']}s "
          f"({doc['chaos_latency']['alert_kind']})")
    print("4/7 chaos: byzantine replica ...")
    doc["chaos_byzantine"] = bench_chaos_byzantine()
    print(f"    detected={doc['chaos_byzantine']['detected']} in "
          f"{doc['chaos_byzantine']['detect_s']}s "
          f"({doc['chaos_byzantine']['alert_source']})")
    print("5/7 chaos: cell blackhole ...")
    doc["chaos_blackhole"] = bench_chaos_blackhole()
    print(f"    detected={doc['chaos_blackhole']['detected']} in "
          f"{doc['chaos_blackhole']['detect_s']}s "
          f"({doc['chaos_blackhole']['alert_source']})")
    print("6/7 A/A soak (no fault) ...")
    doc["aa_soak"] = bench_aa_soak()
    print(f"    {doc['aa_soak']['requests']} requests, "
          f"{doc['aa_soak']['alerts_fired_total']} alerts")
    print("7/7 kill -9 reconstruction ...")
    doc["kill9"] = bench_kill9()
    print(f"    doctor rc={doc['kill9']['doctor_rc']}, timelines="
          f"{doc['kill9']['timelines_recovered']}, last alert="
          f"{doc['kill9']['last_alert_kind']}")
    rc = check(doc)
    Path(args.output).write_text(json.dumps(doc, indent=2) + "\n")
    print(f"wrote {args.output}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
