"""Generate BENCH_BATCH.json: the client-side micro-batching artifact.

Three questions, answered against live in-process servers running
``BatchedMatMulModel`` (the dynamic batcher's showcase fixture — X
FP32[-1, 64] @ W -> Y FP32[-1, 16]):

1. **Sustained QPS at high concurrency** — 64 closed-loop callers through
   a bare client vs the same callers through ``BatchingClient`` (adaptive
   window, ``batch_max_rows`` sized to the model's ``max_batch_size``).
   The acceptance bar is >=5x sustained infer/s for the coalesced arm.
2. **Open-loop sustained-rate sweep** — ``perf.py``'s
   ``--request-rate-range`` path at a ladder of offered rates, both arms:
   achieved rate, latency p99 and schedule slip at each rung (the honest
   throughput metric per arXiv:2210.04323), plus the achieved client-side
   batch-size p50/p99 per rung.
3. **Light-traffic A/B** — one closed-loop caller, bare -> coalesced ->
   bare again: the adaptive window must collapse to zero and the p50
   delta must sit inside the bare-vs-bare noise floor.

Each arm runs against its OWN fresh server so the server-side
``InferBatchStatistics`` (scraped via ``get_inference_statistics``) can
be cross-checked per arm: with client coalescing on, batch sizes > 1 must
show up on BOTH sides — the client's dispatch histogram and the server's
executed-batch distribution.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_batch.py [-o BENCH_BATCH.json]
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

SHAPE = {"X": [1, 64]}
MODEL = "batched_matmul"


def _batch_stat_summary(stats: dict) -> dict:
    """Condense a model's InferBatchStatistics into the committed row."""
    rows = stats.get("batch_stats", [])
    total_execs = sum(r["compute_infer"]["count"] for r in rows)
    total_rows = sum(
        r["batch_size"] * r["compute_infer"]["count"] for r in rows)
    gt1 = sum(r["compute_infer"]["count"] for r in rows if r["batch_size"] > 1)
    return {
        "executions": total_execs,
        "rows_executed": total_rows,
        "mean_executed_batch": (
            round(total_rows / total_execs, 2) if total_execs else 0.0),
        "executions_batch_gt1": gt1,
        "max_executed_batch": max(
            (r["batch_size"] for r in rows), default=0),
        "batch_sizes": {
            str(r["batch_size"]): r["compute_infer"]["count"] for r in rows},
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_BATCH.json")
    parser.add_argument("--concurrency", type=int, default=64)
    parser.add_argument("--requests", type=int, default=1500,
                        help="closed-loop requests for the unbatched arm")
    parser.add_argument("--coalesced-requests", type=int, default=6000,
                        help="closed-loop requests for the coalesced arm "
                             "(it finishes ~an order of magnitude faster)")
    parser.add_argument("--batch-max", type=int, default=32,
                        help="row cap per coalesced request (the model "
                             "declares max_batch_size=32)")
    parser.add_argument("--rates", default="500:1000:2000:4000:8000",
                        help="colon-separated open-loop offered rates "
                             "(req/s)")
    parser.add_argument("--ab-requests", type=int, default=400)
    args = parser.parse_args()

    from client_tpu.http import InferenceServerClient
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "model": MODEL,
        "batch_max_rows": args.batch_max,
        "note": (
            "bare client vs BatchingClient (adaptive window) on "
            "batched_matmul over the threaded HTTP frontend; each arm "
            "runs against its OWN fresh server so the server-side "
            "InferBatchStatistics cross-check is per-arm"
        ),
    }

    def runner(url: str, coalesce: bool) -> PerfRunner:
        return PerfRunner(
            url, "http", MODEL, shape_overrides=SHAPE,
            coalesce=coalesce, batch_max=args.batch_max)

    def server_batch_stats(url: str) -> dict:
        client = InferenceServerClient(url)
        try:
            stats = client.get_inference_statistics(MODEL)
        finally:
            client.close()
        return _batch_stat_summary(stats["model_stats"][0])

    # -- 1: sustained QPS at high concurrency (closed loop) ----------------
    results = {}
    for arm, coalesce, requests in (
            ("unbatched", False, args.requests),
            ("coalesced", True, args.coalesced_requests)):
        server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
        try:
            r = runner(server.url, coalesce)
            try:
                r.run(8, 64)  # warmup: jit compile + connection pools
                results[arm] = r.run(args.concurrency, requests)
            finally:
                r.close()
            results[arm + "_server_batches"] = server_batch_stats(server.url)
        finally:
            server.close()
    speedup = (results["coalesced"]["infer_per_sec"]
               / max(results["unbatched"]["infer_per_sec"], 1e-9))
    out["high_concurrency"] = {
        "concurrency": args.concurrency,
        "unbatched": results["unbatched"],
        "coalesced": results["coalesced"],
        "qps_speedup": round(speedup, 2),
        "server_batches_unbatched": results["unbatched_server_batches"],
        "server_batches_coalesced": results["coalesced_server_batches"],
    }
    print(f"closed-loop c={args.concurrency}: "
          f"{results['unbatched']['infer_per_sec']} -> "
          f"{results['coalesced']['infer_per_sec']} infer/s "
          f"({speedup:.2f}x); server mean batch "
          f"{results['unbatched_server_batches']['mean_executed_batch']} -> "
          f"{results['coalesced_server_batches']['mean_executed_batch']}")

    # -- 2: open-loop sustained-rate sweep ---------------------------------
    rates = [float(r) for r in args.rates.split(":") if r]
    sweep = []
    for arm, coalesce in (("unbatched", False), ("coalesced", True)):
        server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
        try:
            r = runner(server.url, coalesce)
            try:
                r.run(8, 64)  # warmup
                for rate in rates:
                    n = int(min(max(rate, 500), 4000))
                    row = r.run_rate(rate, n, distribution="poisson",
                                     pool_size=args.concurrency)
                    sweep.append((arm, rate, row))
                    print(f"open-loop {arm} rate={rate:g}: achieved "
                          f"{row['achieved_rate']} p99 "
                          f"{row['latency_ms']['p99']}ms late "
                          f"{row['delayed_pct']}%")
            finally:
                r.close()
        finally:
            server.close()
    out["open_loop"] = [
        {"arm": arm, "offered_rate": rate, **row}
        for arm, rate, row in sweep
    ]

    # -- 3: light-traffic A/B (1 in-flight caller) -------------------------
    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    try:
        def measure(coalesce: bool) -> dict:
            r = runner(server.url, coalesce)
            try:
                r.run(1, 50)
                return r.run(1, args.ab_requests)
            finally:
                r.close()

        bare = measure(False)
        coal = measure(True)
        bare_rerun = measure(False)
    finally:
        server.close()
    bare_p50s = [bare["latency_ms"]["p50"], bare_rerun["latency_ms"]["p50"]]
    noise_floor_ms = round(abs(bare_p50s[0] - bare_p50s[1]), 3)
    overhead_ms = round(
        coal["latency_ms"]["p50"] - sum(bare_p50s) / 2, 3)
    out["light_traffic_ab"] = {
        "note": (
            "single closed-loop caller: the adaptive window must collapse "
            "to zero (every dispatch a verbatim passthrough) and the p50 "
            "delta must sit inside the bare-vs-bare noise floor"),
        "bare": bare,
        "coalesced": coal,
        "bare_rerun": bare_rerun,
        "adaptive_window_us": coal["client_batch"]["window_us"],
        "solo_dispatch_fraction": round(
            coal["client_batch"]["solo_calls"]
            / max(coal["client_batch"]["dispatches"], 1), 3),
        "p50_overhead_ms": overhead_ms,
        "noise_floor_ms": noise_floor_ms,
        "within_noise": abs(overhead_ms) <= max(noise_floor_ms, 0.15),
    }
    print(f"light traffic: bare p50 {bare_p50s}, coalesced p50 "
          f"{coal['latency_ms']['p50']} (overhead {overhead_ms}ms vs "
          f"noise {noise_floor_ms}ms), window "
          f"{coal['client_batch']['window_us']}us")

    Path(args.output).write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {args.output}")
    ok = speedup >= 5.0 and out["light_traffic_ab"]["within_noise"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
