"""Generate BENCH_CAPACITY.json: SLO capacity curves across a feature matrix.

The one question every prior bench artifact only circles: **what QPS can
this client/fleet serve inside SLO?** This driver answers it by bisecting
the replay speed of ONE seeded mixed-kind trace (unary + generate_stream
SSE + sequences; ``client_tpu.trace``) against live in-process servers,
per feature-matrix arm:

- ``baseline``      — one server, bare HTTP client
- ``batching``      — one server, the PR 6 coalescing dispatcher armed
- ``pool3_hedge``   — 3-replica PoolClient with hedged requests
- ``pool3_chaos``   — 3-replica PoolClient, one replica behind a
  ChaosProxy latency fault, retries armed — capacity under partial failure
- ``sharded2``      — 2-replica scatter-gather fleet (client_tpu.shard):
  every logical request splits across both replicas and gathers with
  exactness asserts; replays its own ``sharded`` trace (recorded per-arm
  as ``trace_spec`` so the gate re-generates the right workload)

Every probed speed emits a full replay row (per-kind latency/TTFT/ITL
percentiles, offered-vs-achieved rate, schedule slip, shed/error
fractions, per-SLO verdicts); the bisection keeps the highest speed whose
row attains EVERY declared SLO. ``max_sustainable_qps`` is that row's
offered rate. tools/capacity_gate.py replays the same spec against the
committed artifact and fails CI on >15% regression.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_capacity.py [-o BENCH_CAPACITY.json]
"""

from __future__ import annotations

import argparse
import contextlib
import json
import platform
import sys
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# one trace, all arms: capacity numbers are apples-to-apples. The unary
# model is batched_matmul so the batching arm has rows to coalesce; short
# streams keep the CPU-backed generate path from dominating wall time.
TRACE_SPEC = ("mixed:duration_s=4,rate=60,stream_fraction=0.1,"
              "seq_fraction=0.1,unary_model=batched_matmul,"
              "prompt_mean=12,max_prompt=32,output_mean=4,max_output=6,"
              "burst_factor=3,period_s=1.0,duty=0.3")
TRACE_SEED = 2026
# p95, not p99: a 4-second probe sees a few hundred unary requests, and a
# p99 verdict over that flips on ~3 GIL-scheduling outliers — p95 binds on
# genuine queueing (17+ bad samples) instead of single-core jitter
SLOS = ["ttft_p95<500ms", "p95<200ms", "error_rate<1%"]
# the sharded arm's own workload: one-logical-request-across-the-mesh
# records (format v2) over the row-parallel matmul — 8 rows split 4+4
SHARD_TRACE_SPEC = ("sharded:duration_s=4,rate=40,model=batched_matmul,"
                    "batch=8,shards=2,burst_factor=3,period_s=1.0,"
                    "duty=0.3")
# per-arm trace specs (default: TRACE_SPEC); the artifact records each
# arm's spec as ``trace_spec`` so capacity_gate replays the right shape
ARM_TRACE_SPECS = {"sharded2": SHARD_TRACE_SPEC}
# per-arm SLO sets: the sharded trace has no streams, so a ttft objective
# would sit at 0 events and read "not attained" forever
ARM_SLOS = {"sharded2": ["p95<200ms", "error_rate<1%"]}
# a probe must also DELIVER the offered schedule: past saturation the
# replay workers self-throttle, request latency stays flattering while
# the schedule silently slips — the very failure mode the replay's
# offered-vs-achieved reporting exists to expose
MIN_DELIVERY_RATIO = 0.9


def sustainable(row: Dict[str, Any],
                min_delivery: float = MIN_DELIVERY_RATIO) -> bool:
    """One probe's verdict: every declared SLO attained AND the replay
    actually ISSUED the arrival schedule on time (achieved arrival rate ≥
    ``min_delivery`` of offered). Latency SLOs alone cannot catch
    saturation — past it the workers self-throttle and queue wait lands in
    schedule slip, not per-request latency. The arrival rate (not the
    completion rate) is the delivery metric: completions are measured over
    an elapsed that includes the post-schedule drain tail, which at high
    replay speeds would deflate a perfectly-served probe."""
    offered = row["offered_rate"]
    delivered = (row["achieved_arrival_rate"] >= min_delivery * offered
                 if offered > 0 else True)
    return bool(row["slo_ok"] and delivered)


def bisect_capacity(evaluate: Callable[[float], Tuple[bool, Dict[str, Any]]],
                    lo: float, hi: float, iters: int = 5,
                    ) -> Tuple[float, List[Dict[str, Any]]]:
    """Max sustainable replay speed by bisection. ``evaluate(speed)``
    returns ``(slo_ok, row)``; assumes ok is monotone-decreasing in speed
    (true up to measurement noise — each probe's full row is kept so a
    non-monotone flip is visible in the artifact, not silently absorbed).
    Returns ``(best_speed, rows)``; best_speed 0.0 when even ``lo`` fails."""
    rows: List[Dict[str, Any]] = []
    ok, row = evaluate(lo)
    rows.append(row)
    if not ok:
        return 0.0, rows
    best = lo
    ok, row = evaluate(hi)
    rows.append(row)
    if ok:
        return hi, rows
    for _ in range(iters):
        mid = (lo + hi) / 2.0
        ok, row = evaluate(mid)
        rows.append(row)
        if ok:
            lo = best = mid
        else:
            hi = mid
    return best, rows


def _warm(url: str) -> None:
    """Pre-compile every model the trace touches on one server: the first
    generate pays the jit trace, and a capacity probe must never bill
    compilation to the SLO."""
    import numpy as np

    from client_tpu.http import InferenceServerClient, InferInput

    with InferenceServerClient(url) as client:
        x = InferInput("X", [1, 64], "FP32")
        x.set_data_from_numpy(np.zeros((1, 64), dtype=np.float32))
        client.infer("batched_matmul", [x])
        s = InferInput("INPUT", [1, 1], "INT32")
        s.set_data_from_numpy(np.ones((1, 1), dtype=np.int32))
        client.infer("simple_sequence", [s], sequence_id=999983,
                     sequence_start=True, sequence_end=True)
        for _ in client.generate_stream(
                "tiny_lm_generate",
                {"TOKENS": [[1, 2, 3, 4]], "MAX_TOKENS": 2}):
            pass


@contextlib.contextmanager
def arm_runner(name: str, chaos_latency_s: float = 0.01):
    """Stand up one feature-matrix arm — fresh in-process servers, warmed
    models, a PerfRunner configured with the arm's knobs — and tear it
    all down on exit. Shared by the capacity search (main) and the
    regression gate (tools/capacity_gate.py), so each arm has exactly one
    definition. Yields ``(runner, feature_description)``."""
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    if name not in ("baseline", "batching", "pool3_hedge", "pool3_chaos",
                    "sharded2"):
        raise ValueError(f"unknown arm {name!r}")
    n_servers = 3 if name.startswith("pool3") else (
        2 if name == "sharded2" else 1)
    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(n_servers)]
    proxy = None
    runner = None
    try:
        for s in servers:
            _warm(s.url)
        kwargs: Dict[str, Any] = {}
        feature = "bare client, one replica"
        endpoints = None
        shapes = {"X": [1, 64]}
        if name == "sharded2":
            endpoints = [s.url for s in servers]
            kwargs.update(shard_layout="X=0->Y=0")
            shapes = {"X": [8, 64]}
            feature = ("2-replica scatter-gather fleet "
                       "(client_tpu.shard): logical requests split "
                       "across both replicas, gathered exactly")
        elif name == "batching":
            kwargs.update(coalesce=True, batch_max=32)
            feature = "coalescing dispatcher (client_tpu.batch)"
        elif name == "pool3_hedge":
            endpoints = [s.url for s in servers]
            # 100 ms: hedge genuine stragglers only — a tighter delay
            # duplicates the p90 tail, which on a shared-core fleet
            # ADDS load instead of cutting it
            kwargs.update(hedge=True, hedge_delay_s=0.1)
            feature = "3-replica PoolClient, hedged requests"
        elif name == "pool3_chaos":
            proxy = ChaosProxy("127.0.0.1", servers[-1].port).start()
            proxy.fault = Fault("latency", latency_s=chaos_latency_s)
            endpoints = [s.url for s in servers[:-1]] + [proxy.url]
            kwargs.update(retries=1)
            feature = (f"3-replica PoolClient, one replica behind a "
                       f"{chaos_latency_s * 1e3:g}ms latency "
                       f"ChaosProxy, retries=1")
        runner = PerfRunner(servers[0].url, "http", "batched_matmul",
                            shape_overrides=shapes,
                            endpoints=endpoints, **kwargs)
        yield runner, feature
    finally:
        if runner is not None:
            runner.close()
        if proxy is not None:
            proxy.stop()
        for s in servers:
            s.stop()


def _search(runner, tr, speed_lo: float, speed_hi: float, iters: int,
            replay_workers: int, slos=None) -> Dict[str, Any]:
    slos = list(SLOS) if slos is None else list(slos)

    def evaluate(speed: float) -> Tuple[bool, Dict[str, Any]]:
        row = runner.run_trace(tr, speed=round(speed, 3),
                               replay_workers=replay_workers, slos=slos)
        row["delivery_ratio"] = round(
            row["achieved_arrival_rate"] / row["offered_rate"], 3) \
            if row["offered_rate"] else 1.0
        row["sustainable"] = sustainable(row)
        print(f"  speed={row['speed']} offered={row['offered_rate']}/s "
              f"achieved={row['achieved_rate']}/s errors={row['errors']} "
              f"shed={row['shed']} lag_max={row['schedule_lag_ms']['max']}ms "
              f"slo_ok={row['slo_ok']} "
              f"sustainable={row['sustainable']}", flush=True)
        return row["sustainable"], row

    _, rows = bisect_capacity(evaluate, speed_lo, speed_hi, iters)
    # confirmation pass: a committed capacity must be REPRODUCIBLE, not a
    # lucky probe — re-evaluate the highest sustainable speed; on failure
    # fall back to the next-lower one (the gate will hold future runs to
    # 85% of this number, so an outlier-high single probe must not anchor
    # the baseline)
    candidates = sorted({r["speed"] for r in rows if r["sustainable"]},
                        reverse=True)
    best_row = None
    # walk ALL sustainable candidates, highest first: flaky confirmations
    # must anchor the baseline at the highest REPRODUCIBLE speed, never
    # silently commit 0.0 (which would disable the gate for this arm)
    for speed in candidates:
        ok, row = evaluate(speed)
        row["confirmation"] = True
        rows.append(row)
        if ok:
            best_row = row
            break
    return {
        "max_speed": best_row["speed"] if best_row else 0.0,
        "max_sustainable_qps": best_row["offered_rate"] if best_row else 0.0,
        "achieved_qps_at_max": best_row["achieved_rate"] if best_row else 0.0,
        "rows": rows,
    }


def main(argv=None, trace_override=None) -> int:
    """``trace_override``: a pre-built ``trace.Trace`` replacing the
    module-level spec — tools/capacity_gate.py passes a shortened twin of
    the committed trace so both definitions of every arm stay HERE."""
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_CAPACITY.json")
    parser.add_argument("--speed-lo", type=float, default=0.5)
    parser.add_argument("--speed-hi", type=float, default=8.0)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--replay-workers", type=int, default=32)
    parser.add_argument("--chaos-latency-s", type=float, default=0.01)
    parser.add_argument(
        "--arms", default="baseline,batching,pool3_hedge,pool3_chaos")
    args = parser.parse_args(argv)

    from client_tpu import trace as trace_mod

    tr = (trace_override if trace_override is not None
          else trace_mod.generate(TRACE_SPEC, seed=TRACE_SEED))
    out: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "max sustainable QPS per feature arm: bisection over the "
            "replay speed of one seeded mixed-kind trace (unary + SSE "
            "stream + sequence) against live in-process servers; a speed "
            "is sustainable when every declared SLO is attained over the "
            "whole replay window"
        ),
        "trace": {
            "spec": tr.header.get("spec", TRACE_SPEC),
            "seed": tr.header.get("seed", TRACE_SEED),
            "records": len(tr.records),
            "duration_s": tr.duration_s,
            "kinds": tr.kind_counts(),
        },
        "slos": list(SLOS),
        "search": {
            "speed_lo": args.speed_lo,
            "speed_hi": args.speed_hi,
            "iters": args.iters,
            "replay_workers": args.replay_workers,
            "min_delivery_ratio": MIN_DELIVERY_RATIO,
            "chaos_latency_s": args.chaos_latency_s,
        },
        "arms": {},
    }

    for name in [a.strip() for a in args.arms.split(",") if a.strip()]:
        arm_spec = ARM_TRACE_SPECS.get(name)
        if arm_spec is not None and trace_override is None:
            arm_tr = trace_mod.generate(arm_spec, seed=TRACE_SEED)
        else:
            arm_tr = tr
        arm_slos = ARM_SLOS.get(name)
        with arm_runner(name, args.chaos_latency_s) as (runner, feature):
            print(f"arm {name}: {feature}", flush=True)
            arm = _search(runner, arm_tr, args.speed_lo, args.speed_hi,
                          args.iters, args.replay_workers, slos=arm_slos)
            arm["feature"] = feature
            if arm_spec is not None and trace_override is None:
                # the gate re-generates per-arm workloads from this; an
                # override replay measured a DIFFERENT workload, so
                # stamping the arm spec would point the gate at a trace
                # the committed number never saw
                arm["trace_spec"] = arm_spec
            if arm_slos is not None:
                arm["slos"] = list(arm_slos)
        out["arms"][name] = arm

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    summary = {name: arm["max_sustainable_qps"]
               for name, arm in out["arms"].items()}
    print("max_sustainable_qps:", json.dumps(summary, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
