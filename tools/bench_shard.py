"""Generate BENCH_SHARD.json: the sharded scatter-gather proof artifact.

Four arms over 2 in-process replica servers (the same topology every other
bench in this repo uses — CPU container numbers, honest about it):

- **exactness**: a batch of prompts scattered across N
  ``decoder_lm_tp_prefill`` replicas (client_tpu.shard) and gathered must
  be BIT-identical to the single-process reference model
  (``decoder_lm_prefill``, tp step bit-equal by models/decoder_tp.py's
  guarantee) on every request.
- **scatter_vs_single**: latency + closed-loop capacity of the sharded
  fleet vs ONE replica serving the full batch, over the non-TP prefill
  (each replica scores half the rows; in-process TP replicas would
  serialize on the virtual-device lock and hide the win).
- **steady_state**: sharded infers through the shm-arena fast path —
  after warmup, region creates and registration RPCs per request must be
  ZERO (slabs reused, registrations cached per (endpoint, region)).
- **chaos**: one replica RSTs mid-run; every affected logical request
  must fail with the typed ShardFailed naming the dead shard/endpoint,
  and every success must stay bit-exact (zero partial gathers).

``--check`` re-validates an existing artifact's acceptance invariants and
exits nonzero on violation (tests/test_shard.py pins the same claims):

    JAX_PLATFORMS=cpu python tools/bench_shard.py [-o BENCH_SHARD.json]
    JAX_PLATFORMS=cpu python tools/bench_shard.py --check BENCH_SHARD.json
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _percentiles(samples_s):
    xs = sorted(samples_s)
    n = len(xs)
    if not n:
        return {}
    pick = lambda q: xs[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
    return {
        "avg": round(1e3 * sum(xs) / n, 3),
        "p50": round(1e3 * pick(0.50), 3),
        "p90": round(1e3 * pick(0.90), 3),
        "p99": round(1e3 * pick(0.99), 3),
    }


def check(path: str) -> int:
    data = json.loads(Path(path).read_text())
    failures = []
    if data["exactness"]["bit_exact"] is not True:
        failures.append("scatter-gather is not bit-exact vs the "
                        "single-process reference")
    if data["exactness"]["requests"] <= 0:
        failures.append("exactness arm measured no requests")
    steady = data["steady_state"]
    if steady["requests"] <= 0:
        failures.append("steady-state arm measured no requests")
    if steady["region_creates_per_request"] != 0:
        failures.append("steady-state sharded infers created regions")
    if steady["registration_rpcs_per_request"] != 0:
        failures.append("steady-state sharded infers issued "
                        "registration RPCs")
    chaos = data["chaos"]
    if chaos["affected_requests"] <= 0:
        failures.append("chaos arm affected no requests")
    if chaos["shard_failed_typed"] != chaos["affected_requests"]:
        failures.append(
            "a killed shard did not produce typed ShardFailed on 100% "
            "of affected logical requests")
    if chaos["partial_gathers"] != 0:
        failures.append("chaos arm produced partial gathers")
    if chaos["failed_shard_named"] is not True:
        failures.append("ShardFailed did not name the killed "
                        "shard/endpoint")
    if chaos.get("recovered_after_heal", 0) <= 0:
        failures.append("no logical request succeeded after the killed "
                        "shard healed")
    for msg in failures:
        print(f"CHECK FAILED: {msg}", file=sys.stderr)
    if not failures:
        print(f"{path}: all sharded scatter-gather acceptance "
              "invariants hold")
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_SHARD.json")
    parser.add_argument("--exact-requests", type=int, default=15)
    parser.add_argument("--latency-requests", type=int, default=40)
    parser.add_argument("--steady-requests", type=int, default=200)
    parser.add_argument("--chaos-requests", type=int, default=40)
    parser.add_argument("--rows", type=int, default=8)
    parser.add_argument("--prompt-tokens", type=int, default=8)
    parser.add_argument("--check", metavar="ARTIFACT",
                        help="validate an existing artifact instead of "
                             "benchmarking")
    args = parser.parse_args()
    if args.check:
        return check(args.check)

    import client_tpu.http as httpclient
    from client_tpu.arena import ShmArena
    from client_tpu.models import default_model_zoo
    from client_tpu.models.decoder_prefill import PrefillDecoderModel
    from client_tpu.pool import PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.shard import ShardFailed, ShardLayout, ShardedClient
    from client_tpu.testing import ChaosProxy, Fault

    rng = np.random.default_rng(0xC11E)
    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(2)]
    proxies = [ChaosProxy("127.0.0.1", s.port).start() for s in servers]
    direct_urls = [f"127.0.0.1:{s.port}" for s in servers]
    proxy_urls = [p.url for p in proxies]

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "replicas": 2,
        "note": (
            "client-driven scatter-gather (client_tpu.shard) over 2 "
            "in-process replica servers; decoder_lm_tp_prefill exactness "
            "vs single-process reference, non-TP prefill for the "
            "latency/capacity comparison (in-process TP replicas "
            "serialize on the virtual-device lock), shm-arena staging "
            "for the steady-state arm; CPU container numbers"
        ),
    }

    def sharded_client(urls, model_inputs, arena=None):
        layout = ShardLayout(urls, inputs=model_inputs["inputs"],
                             outputs=model_inputs["outputs"])
        pool = PoolClient(urls, protocol="http", health_interval_s=None,
                          shm_arena=arena)
        return ShardedClient(pool, layout)

    try:
        # -- exactness: decoder_tp replicas vs single-process reference --
        tokens = rng.integers(
            0, 256, size=(max(2, args.rows // 2), args.prompt_tokens),
            dtype=np.int32)
        reference = PrefillDecoderModel(tp=False).execute(
            {"TOKENS": tokens}, {})
        tp_layout = {"inputs": {"TOKENS": 0},
                     "outputs": {"LOGITS": 0, "NEXT_TOKEN": 0}}
        client = sharded_client(direct_urls, tp_layout)
        exact, lats = True, []
        try:
            for _ in range(args.exact_requests):
                inp = httpclient.InferInput(
                    "TOKENS", list(tokens.shape),
                    "INT32").set_data_from_numpy(tokens)
                t0 = time.perf_counter()
                res = client.infer("decoder_lm_tp_prefill", [inp])
                lats.append(time.perf_counter() - t0)
                exact = exact and np.array_equal(
                    res.as_numpy("LOGITS"), reference["LOGITS"]) \
                    and np.array_equal(res.as_numpy("NEXT_TOKEN"),
                                       reference["NEXT_TOKEN"])
        finally:
            client.close()
        out["exactness"] = {
            "model": "decoder_lm_tp_prefill",
            "batch": list(tokens.shape),
            "requests": args.exact_requests,
            "bit_exact": bool(exact),
            "sharded_latency_ms": _percentiles(lats),
        }
        print("exactness:", out["exactness"])

        # -- scatter-gather vs single replica: latency + capacity --------
        tokens2 = rng.integers(0, 256, size=(args.rows,
                                             args.prompt_tokens),
                               dtype=np.int32)
        pf_layout = {"inputs": {"TOKENS": 0},
                     "outputs": {"LOGITS": 0, "NEXT_TOKEN": 0}}

        def drive(infer, n):
            samples = []
            for _ in range(n):
                inp = httpclient.InferInput(
                    "TOKENS", list(tokens2.shape),
                    "INT32").set_data_from_numpy(tokens2)
                t0 = time.perf_counter()
                infer(inp)
                samples.append(time.perf_counter() - t0)
            return samples

        single = httpclient.InferenceServerClient(direct_urls[0])
        try:
            single.infer("decoder_lm_prefill", [httpclient.InferInput(
                "TOKENS", list(tokens2.shape),
                "INT32").set_data_from_numpy(tokens2)])  # jit warmup
            single_lat = drive(
                lambda inp: single.infer("decoder_lm_prefill", [inp]),
                args.latency_requests)
        finally:
            single.close()
        client = sharded_client(direct_urls, pf_layout)
        try:
            drive(lambda inp: client.infer("decoder_lm_prefill", [inp]), 2)
            sharded_lat = drive(
                lambda inp: client.infer("decoder_lm_prefill", [inp]),
                args.latency_requests)
        finally:
            client.close()
        single_row = _percentiles(single_lat)
        sharded_row = _percentiles(sharded_lat)
        out["scatter_vs_single"] = {
            "model": "decoder_lm_prefill",
            "batch": list(tokens2.shape),
            "requests": args.latency_requests,
            "single_replica_latency_ms": single_row,
            "sharded_latency_ms": sharded_row,
            "p50_speedup": round(single_row["p50"]
                                 / max(sharded_row["p50"], 1e-9), 2),
            "throughput_single_rps": round(
                len(single_lat) / sum(single_lat), 1),
            "throughput_sharded_rps": round(
                len(sharded_lat) / sum(sharded_lat), 1),
        }
        print("scatter_vs_single:", out["scatter_vs_single"])

        # -- steady state: arena fast path, 0 region/registration ops ----
        arena = ShmArena(name_prefix="bench_shard")
        x = rng.standard_normal((args.rows, 64)).astype(np.float32)
        client = sharded_client(
            direct_urls, {"inputs": {"X": 0}, "outputs": {"Y": 0}},
            arena=arena)
        try:
            for _ in range(10):  # warmup: carve slabs, cache registrations
                client.infer("batched_matmul", [httpclient.InferInput(
                    "X", list(x.shape), "FP32").set_data_from_numpy(x)]
                ).release()
            before = arena.stats()
            t0 = time.perf_counter()
            for _ in range(args.steady_requests):
                res = client.infer(
                    "batched_matmul", [httpclient.InferInput(
                        "X", list(x.shape),
                        "FP32").set_data_from_numpy(x)])
                res.as_numpy("Y")
                res.release()
            elapsed = time.perf_counter() - t0
            after = arena.stats()
        finally:
            client.close()
        out["steady_state"] = {
            "model": "batched_matmul",
            "requests": args.steady_requests,
            "region_creates_per_request": (
                after["regions_created"] - before["regions_created"])
            / args.steady_requests,
            "registration_rpcs_per_request": (
                after["registrations_issued"]
                - before["registrations_issued"]) / args.steady_requests,
            "arena_hit_rate": after["hit_rate"],
            "residual_leased_bytes": after["leased_bytes"],
            "throughput_rps": round(args.steady_requests / elapsed, 1),
        }
        print("steady_state:", out["steady_state"])

        # -- chaos: kill one shard mid-run -------------------------------
        layout = ShardLayout(proxy_urls, inputs={"X": 0},
                             outputs={"Y": 0})
        pool = PoolClient(proxy_urls, protocol="http",
                          health_interval_s=None)
        client = ShardedClient(pool, layout)
        ref = httpclient.InferenceServerClient(direct_urls[0])
        try:
            want = ref.infer("batched_matmul", [httpclient.InferInput(
                "X", list(x.shape),
                "FP32").set_data_from_numpy(x)]).as_numpy("Y")
            ok = affected = typed = partial = recovered = 0
            named = True
            kill_at = args.chaos_requests // 3
            heal_at = 2 * args.chaos_requests // 3
            for i in range(args.chaos_requests):
                if i == kill_at:
                    proxies[1].fault = Fault("reset", after_bytes=0)
                    proxies[1].reset_active()
                if i == heal_at:
                    proxies[1].heal()
                    # the killed shard's breaker opened during the fault
                    # window (that is the fail-fast contract: a pinned
                    # shard with an open breaker fails the logical
                    # request in microseconds, it does not hang); wait
                    # out recovery so the arm also proves post-heal
                    # requests succeed again
                    deadline = time.monotonic() + 15.0
                    while time.monotonic() < deadline:
                        try:
                            client.infer(
                                "batched_matmul",
                                [httpclient.InferInput(
                                    "X", list(x.shape),
                                    "FP32").set_data_from_numpy(x)],
                                client_timeout=5.0)
                            break
                        except Exception:
                            time.sleep(0.25)
                inp = httpclient.InferInput(
                    "X", list(x.shape), "FP32").set_data_from_numpy(x)
                try:
                    res = client.infer("batched_matmul", [inp],
                                       client_timeout=10.0)
                except ShardFailed as e:
                    affected += 1
                    typed += 1
                    named = named and e.url == proxy_urls[1] \
                        and e.shard == 1
                except Exception:
                    affected += 1  # un-typed failure: the check flags it
                else:
                    ok += 1
                    if i >= heal_at:
                        recovered += 1
                    if not np.array_equal(res.as_numpy("Y"), want):
                        partial += 1
                time.sleep(0.01)
        finally:
            ref.close()
            client.close()
        out["chaos"] = {
            "model": "batched_matmul",
            "requests": args.chaos_requests,
            "ok": ok,
            "affected_requests": affected,
            "shard_failed_typed": typed,
            "failed_shard_named": bool(named),
            "partial_gathers": partial,
            "recovered_after_heal": recovered,
        }
        print("chaos:", out["chaos"])
    finally:
        for p in proxies:
            p.stop()
        for s in servers:
            s.stop()

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(f"wrote {args.output}")
    return check(args.output)


if __name__ == "__main__":
    sys.exit(main())
