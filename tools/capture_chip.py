"""One-command opportunistic chip capture (VERDICT-r3 #2).

The axon tunnel is green in windows; rounds 1-3 lost those windows to
piecemeal inline probing, leaving headline numbers (60%-MFU pipelined
matmul, kernel TF/s, LLM TTFT) without a committed artifact. This tool is
the single command to run the moment a window opens:

    python tools/capture_chip.py [--out PATH] [--quick]

Stages (each its own subprocess + timeout, so one mid-run tunnel stall
costs that section, not the capture):

  1. probe        — staged tunnel probe (tools/tpu_probe.py); gates the rest
  2. chip_bench   — MXU matmul (blocked + pipelined), flash attention,
                    densenet family with corrected full-batch MFU,
                    dispatch-overhead RTT floor (tools/chip_bench.py)
  3. decode_attn  — flash-decoding kernel under real Mosaic: exactness vs
                    dense + latency crossover curve (tools/decode_attn_chip.py)
  4. flash_sweep  — flash-attention block_q×block_k sweep with MFU + bf16
                    exactness at the best config (tools/flash_sweep.py)
  5. genai_perf   — LLM TTFT / inter-token latency / token throughput over
                    the live GRPC stream, decoupled + sequence-batched modes
  6. bench        — the full data-plane matrix (bench.py; skipped by --quick)

Everything lands in ONE timestamped JSON (default CHIP_CAPTURE_<UTC>.json
at the repo root) with per-section ok/seconds/error, replacing the
"provenance split" of round 3 — every headline number cites this file.

Reference parity: this is perf_analyzer's role for the TPU stack
(SURVEY §2.5; the reference tool moved out-of-repo, perf_analyzer/README.md).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_GENAI_CHILD = r"""
import json, sys
sys.path.insert(0, %(root)r)
from client_tpu.genai_perf import GenAiPerfRunner
from client_tpu.models.decoder_batched import BatchedDecoderModel
from client_tpu.models.generate import TinyGenerateModel
from client_tpu.server import GrpcInferenceServer, HttpInferenceServer, ServerCore

out = {}
core = ServerCore([TinyGenerateModel(), BatchedDecoderModel(seed=0, slots=8)])
with GrpcInferenceServer(core) as grpc_server, \
        HttpInferenceServer(core) as http_server:
    for mode, url, model, sessions in (
        ("decoupled", grpc_server.url, "tiny_lm_generate", 8),
        ("generate", http_server.url, "tiny_lm_generate", 8),
        ("sequence", grpc_server.url, "decoder_lm_batched", 8),
    ):
        runner = GenAiPerfRunner(url, model, mode,
                                 prompt_tokens=16, output_tokens=16)
        for conc in (1, 4):
            out[f"{mode}_c{conc}"] = runner.run(conc, sessions)
print("RESULT " + json.dumps(out), flush=True)
"""


def _run_section(name, argv, timeout_s, parse="json_out", env=None):
    """Run one capture section in a child process. parse: 'json_out' reads
    a tempfile the child wrote via --json-out; 'last_line'/'result_line'
    parse stdout."""
    started = time.monotonic()
    section = {"ok": False}
    tmp = None
    try:
        if parse == "json_out":
            fd, tmp = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            argv = argv + ["--json-out", tmp]
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            cwd=ROOT, env=env,
        )
        if parse == "json_out":
            with open(tmp) as f:
                text = f.read().strip()
            if not text:
                raise ValueError(
                    f"rc={proc.returncode}, no JSON written; stderr tail: "
                    + (proc.stderr or "")[-400:])
            section["data"] = json.loads(text)
            section["ok"] = True
        else:
            marker = "RESULT " if parse == "result_line" else ""
            lines = [ln for ln in (proc.stdout or "").splitlines()
                     if ln.startswith(marker) and ln.strip()]
            if not lines:
                raise ValueError(
                    f"rc={proc.returncode}, no output line; stderr tail: "
                    + (proc.stderr or "")[-400:])
            section["data"] = json.loads(lines[-1][len(marker):])
            section["ok"] = True
        if proc.returncode != 0:
            section["rc"] = proc.returncode  # partial data, e.g. exactness fail
    except subprocess.TimeoutExpired:
        section["error"] = f"section timed out after {timeout_s}s"
    except Exception as e:
        section["error"] = f"{type(e).__name__}: {e}"[:600]
    finally:
        if tmp and os.path.exists(tmp):
            os.unlink(tmp)
    section["seconds"] = round(time.monotonic() - started, 1)
    print(json.dumps({"section": name, "ok": section["ok"],
                      "seconds": section["seconds"],
                      **({"error": section["error"]} if "error" in section
                         else {})}),
          file=sys.stderr, flush=True)
    return section


def watch(args):
    """VERDICT-r4 #2: probe on a loop; on the first green window run the
    full capture and exit 0. Every probe attempt is appended to a JSONL
    log so a round with no window still ends with committed evidence that
    the tunnel was watched (not just waited on by a busy human).

    Exit codes: 0 = window found and capture written; 1 = watch window
    expired with no green probe (the log is the deliverable)."""
    from tools.tpu_probe import probe

    log_path = args.watch_log or os.path.join(ROOT, "CHIP_WATCH_r05.jsonl")
    deadline = time.monotonic() + args.watch_max_hours * 3600.0
    interval_s = args.watch * 60.0
    attempt = 0

    def log(entry: dict, utc: str = "") -> None:
        entry = {
            "utc": utc or datetime.datetime.now(datetime.timezone.utc)
            .isoformat(timespec="seconds"),
            **entry,
        }
        with open(log_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        print(json.dumps(entry), file=sys.stderr, flush=True)

    while True:
        attempt += 1
        t0 = time.monotonic()
        # stamp when the attempt STARTED (a hung probe returns ~2 min later)
        started_utc = datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds")
        # single attempt per cycle: the loop IS the retry policy
        res = probe(attempts=1)
        log({
            "attempt": attempt,
            "ok": bool(res.get("ok")),
            "probe_seconds": round(time.monotonic() - t0, 1),
            **{key: res[key]
               for key in ("platform", "hung_at", "failed_at", "error")
               if key in res},
        }, utc=started_utc)
        if res.get("ok"):
            rc = run_capture(args, probe_result=res)
            log({"event": "capture_done", "rc": rc})
            return rc
        if time.monotonic() >= deadline:
            log({"event": "watch_expired", "attempts": attempt})
            print(json.dumps({"ok": False, "reason": "watch expired",
                              "attempts": attempt, "log": log_path}))
            return 1
        time.sleep(max(0.0, interval_s - (time.monotonic() - t0)))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="output path (default CHIP_CAPTURE_<UTC>.json)")
    parser.add_argument("--quick", action="store_true",
                        help="skip the full bench.py matrix (slowest section)")
    parser.add_argument("--skip-probe", action="store_true",
                        help="assume the chip is reachable (rerun mid-window)")
    parser.add_argument("--smoke", action="store_true",
                        help="off-chip pipeline check: CPU backend, tiny "
                             "shapes, no probe, no bench matrix")
    parser.add_argument("--watch", type=float, default=0, metavar="MINUTES",
                        help="watcher mode: staged probe every N minutes; "
                             "on the first green window run the full capture "
                             "and exit (VERDICT-r4 #2)")
    parser.add_argument("--watch-log", default=None,
                        help="JSONL probe log (default CHIP_WATCH_r05.jsonl)")
    parser.add_argument("--watch-max-hours", type=float, default=11.0,
                        help="give up watching after this many hours")
    args = parser.parse_args()
    if args.watch > 0:
        return watch(args)
    return run_capture(args)


def run_capture(args, probe_result=None):
    stamp = datetime.datetime.now(datetime.timezone.utc)
    out_path = args.out or os.path.join(
        ROOT, f"CHIP_CAPTURE_{stamp.date().isoformat()}.json")
    result = {
        "captured_utc": stamp.isoformat(timespec="seconds"),
        "sections": {},
    }

    env = None
    small = []
    if args.smoke:
        # PYTHONPATH= skips the axon sitecustomize (whose dead tunnel hangs
        # even env-pinned "cpu" jax); children add the repo root themselves
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}
        small = ["--small"]
        args.skip_probe = True
        args.quick = True

    if probe_result is not None:
        # watcher already probed green this cycle; don't burn the window
        result["probe"] = probe_result
    elif not args.skip_probe:
        from tools.tpu_probe import probe

        t0 = time.monotonic()
        probe_result = probe()
        result["probe"] = probe_result
        print(json.dumps({"section": "probe", "ok": probe_result.get("ok"),
                          "seconds": round(time.monotonic() - t0, 1)}),
              file=sys.stderr, flush=True)
        if not probe_result.get("ok"):
            with open(out_path, "w") as f:
                json.dump(result, f, indent=1)
            print(json.dumps({"ok": False, "reason": "probe failed",
                              "out": out_path}))
            return 1

    py = sys.executable
    sections = result["sections"]
    sections["chip_bench"] = _run_section(
        "chip_bench", [py, "tools/chip_bench.py"] + small, 1500, env=env)
    sections["decode_attn"] = _run_section(
        "decode_attn", [py, "tools/decode_attn_chip.py"] + small, 1200,
        env=env)
    sections["flash_sweep"] = _run_section(
        "flash_sweep", [py, "tools/flash_sweep.py"] + small, 1800, env=env)
    sections["genai_perf"] = _run_section(
        "genai_perf", [py, "-c", _GENAI_CHILD % {"root": ROOT}], 900,
        parse="result_line", env=env)
    if not args.quick:
        sections["bench"] = _run_section(
            "bench", [py, "bench.py"], 2400, parse="last_line", env=env)

    ok_count = sum(1 for s in sections.values() if s.get("ok"))
    result["ok_sections"] = ok_count
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"ok": ok_count > 0, "ok_sections": ok_count,
                      "total_sections": len(sections), "out": out_path}))
    # partial success exits 0 on purpose: a mid-capture tunnel stall still
    # produced committable sections, and the artifact records what failed
    return 0 if ok_count else 1


if __name__ == "__main__":
    sys.exit(main())
