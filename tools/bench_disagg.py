"""Generate BENCH_DISAGG.json: the disaggregated prefill/decode proof.

Three arms over in-process replica servers (the same topology every other
bench in this repo uses — CPU container numbers, honest about it):

- **ttft_itl**: TTFT/ITL split of disaggregated sessions (prefill on a
  prefill-role endpoint, decode streamed from a decode-role endpoint via
  the verified KV handoff) vs the monolithic ``tiny_lm_generate`` path on
  one replica — and every disagg session's token stream must be
  BIT-identical to the monolithic reference (the two paths share the zoo
  decoder's weights; models/disagg.py).
- **steady_state**: after warmup, N handoffs through the shared arena
  must issue ZERO region creates and ZERO registration RPCs — the KV
  slab is leased from cached slabs and both endpoints' registrations are
  cached per (endpoint, region).
- **chaos**: a decode replica is RST mid-stream (ChaosProxy) while a
  second decode replica stays healthy; every killed session must finish
  via re-prefill recovery (delivery 1.0) with ZERO repeated and ZERO
  dropped tokens (indices contiguous, stream bit-exact vs monolithic),
  and at least one actual mid-stream kill must have happened.

``--check`` re-validates an existing artifact's acceptance invariants and
exits nonzero on violation (tests/test_disagg.py pins the same claims);
``tools/capacity_gate.py --disagg`` re-RUNS the chaos arm live:

    JAX_PLATFORMS=cpu python tools/bench_disagg.py [-o BENCH_DISAGG.json]
    JAX_PLATFORMS=cpu python tools/bench_disagg.py --check BENCH_DISAGG.json
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

PROMPT_TOKENS = 12
MAX_TOKENS = 24


def _percentiles(samples_s):
    xs = sorted(samples_s)
    n = len(xs)
    if not n:
        return {}
    pick = lambda q: xs[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
    return {
        "avg": round(1e3 * sum(xs) / n, 3),
        "p50": round(1e3 * pick(0.50), 3),
        "p90": round(1e3 * pick(0.90), 3),
        "p99": round(1e3 * pick(0.99), 3),
    }


def _drive_session(stream):
    """Iterate one token stream; returns (tokens, indices, ttft_s, itls_s)."""
    tokens, indices, itls = [], [], []
    t0 = time.perf_counter()
    ttft = None
    last = t0
    for event in stream:
        now = time.perf_counter()
        if ttft is None:
            ttft = now - t0
        else:
            itls.append(now - last)
        last = now
        tokens.append(int(event["NEXT_TOKEN"]))
        indices.append(int(event["INDEX"]))
    return tokens, indices, ttft, itls


def monolithic_tokens(url, prompt, max_tokens):
    """The monolithic reference stream (``tiny_lm_generate``) for a
    prompt: the bit-exactness baseline every disagg session is held to."""
    from client_tpu.pool import PoolClient

    pool = PoolClient([url], protocol="http", health_interval_s=None)
    try:
        return _drive_session(pool.generate_stream(
            "tiny_lm_generate",
            {"TOKENS": [list(prompt)], "MAX_TOKENS": int(max_tokens)}))
    finally:
        pool.close()


def session_problems(tokens, indices, want_tokens, max_tokens):
    """Per-session token-integrity verdict: (repeated, dropped, exact)."""
    repeated = sum(1 for i, idx in enumerate(indices) if idx in indices[:i])
    dropped = max(0, max_tokens - len(tokens))
    exact = tokens == want_tokens and indices == list(range(max_tokens))
    return repeated, dropped, exact


def run_chaos_arm(sessions: int = 8, prompt_tokens: int = PROMPT_TOKENS,
                  max_tokens: int = MAX_TOKENS, kill_after: int = 5,
                  seed: int = 0xD15A):
    """The mid-stream decode-kill proof, self-contained so
    ``capacity_gate.py --disagg`` can re-run it live: one prefill
    replica, one decode replica behind a ChaosProxy, one direct decode
    replica. Every even session arms a mid-stream RST of the proxied
    decode leg once its stream is provably flowing through the proxy;
    the session must finish via re-prefill + resumed decode elsewhere."""
    from client_tpu.disagg import DisaggClient
    from client_tpu.models import default_model_zoo
    from client_tpu.pool import EndpointSpec
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    rng = np.random.default_rng(seed)
    prompt = rng.integers(0, 256, size=prompt_tokens, dtype=np.int32).tolist()
    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(3)]
    proxy = ChaosProxy("127.0.0.1", servers[1].port).start()
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    want, _, _, _ = monolithic_tokens(urls[0], prompt, max_tokens)
    client = DisaggClient(
        [EndpointSpec(urls[0], role="prefill"),
         EndpointSpec(proxy.url, role="decode"),
         EndpointSpec(urls[2], role="decode")],
        protocol="http", health_interval_s=None, routing="round_robin")
    row = {"sessions": sessions, "max_tokens": max_tokens,
           "completed": 0, "kills": 0, "repeated_tokens": 0,
           "dropped_tokens": 0, "bit_exact": True, "abandoned": 0}
    try:
        for i in range(sessions):
            arm_kill = i % 2 == 0
            conns_before = proxy.stats["connections"]
            tokens, indices, killed = [], [], False
            try:
                for event in client.generate_stream(
                        prompt, max_tokens=max_tokens):
                    tokens.append(int(event["NEXT_TOKEN"]))
                    indices.append(int(event["INDEX"]))
                    if (arm_kill and not killed and len(tokens) == kill_after
                            and proxy.stats["connections"] > conns_before):
                        # the decode stream is provably on the proxied
                        # replica: kill it mid-stream and keep it dead so
                        # recovery MUST land elsewhere
                        proxy.fault = Fault("reset", after_bytes=0)
                        proxy.reset_active()
                        killed = True
            except Exception:
                row["abandoned"] += 1
            else:
                row["completed"] += 1
            if killed:
                row["kills"] += 1
                proxy.heal()
            repeated, dropped, exact = session_problems(
                tokens, indices, want, max_tokens)
            row["repeated_tokens"] += repeated
            row["dropped_tokens"] += dropped
            row["bit_exact"] = row["bit_exact"] and exact
    finally:
        client.close()
        proxy.stop()
        for s in servers:
            s.stop()
    row["delivery_ratio"] = round(row["completed"] / sessions, 4)
    return row


def chaos_problems(row) -> list:
    """The chaos arm's acceptance invariants (shared by --check and the
    live capacity_gate --disagg re-run)."""
    problems = []
    if row["sessions"] <= 0:
        problems.append("chaos arm ran no sessions")
    if row["kills"] <= 0:
        problems.append("no decode replica was actually killed mid-stream")
    if row["delivery_ratio"] != 1.0:
        problems.append(
            f"delivery {row['delivery_ratio']} != 1.0: a killed decode "
            "leg lost whole sessions instead of recovering via re-prefill")
    if row["repeated_tokens"] != 0:
        problems.append(f"{row['repeated_tokens']} repeated tokens "
                        "delivered across the decode handover")
    if row["dropped_tokens"] != 0:
        problems.append(f"{row['dropped_tokens']} tokens dropped across "
                        "the decode handover")
    if row["bit_exact"] is not True:
        problems.append("recovered streams are not bit-exact vs the "
                        "monolithic reference")
    if row.get("abandoned", 0) != 0:
        problems.append(f"{row['abandoned']} sessions abandoned")
    return problems


def check_doc(data) -> list:
    failures = []
    split = data["ttft_itl"]
    if split["sessions"] <= 0:
        failures.append("ttft_itl arm measured no sessions")
    if split["bit_exact"] is not True:
        failures.append("disagg sessions are not bit-exact vs the "
                        "monolithic reference")
    for arm in ("monolithic", "disagg"):
        if not split[arm].get("ttft_ms") or not split[arm].get("itl_ms"):
            failures.append(f"ttft_itl arm missing {arm} percentiles")
    steady = data["steady_state"]
    if steady["handoffs"] <= 0:
        failures.append("steady-state arm measured no handoffs")
    if steady["region_creates_per_handoff"] != 0:
        failures.append("steady-state handoffs created shm regions")
    if steady["registration_rpcs_per_handoff"] != 0:
        failures.append("steady-state handoffs issued registration RPCs")
    failures.extend(chaos_problems(data["chaos"]))
    return failures


def check(path: str) -> int:
    failures = check_doc(json.loads(Path(path).read_text()))
    for msg in failures:
        print(f"CHECK FAILED: {msg}", file=sys.stderr)
    if not failures:
        print(f"{path}: all disaggregated prefill/decode acceptance "
              "invariants hold")
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_DISAGG.json")
    parser.add_argument("--split-sessions", type=int, default=20)
    parser.add_argument("--steady-sessions", type=int, default=30)
    parser.add_argument("--chaos-sessions", type=int, default=8)
    parser.add_argument("--prompt-tokens", type=int, default=PROMPT_TOKENS)
    parser.add_argument("--max-tokens", type=int, default=MAX_TOKENS)
    parser.add_argument("--check", metavar="ARTIFACT",
                        help="validate an existing artifact instead of "
                             "benchmarking")
    args = parser.parse_args()
    if args.check:
        return check(args.check)

    from client_tpu.disagg import DisaggClient
    from client_tpu.models import default_model_zoo
    from client_tpu.pool import EndpointSpec, PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore

    rng = np.random.default_rng(0xD15A)
    prompt = rng.integers(0, 256, size=args.prompt_tokens,
                          dtype=np.int32).tolist()
    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(2)]
    urls = [f"127.0.0.1:{s.port}" for s in servers]

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "disaggregated prefill/decode (client_tpu.disagg) over "
            "in-process replica servers: prefill-role KV export, "
            "digest-verified shared-arena handoff, decode-role streamed "
            "resume; monolithic baseline is tiny_lm_generate on one "
            "replica (same zoo decoder weights => bit-exactness is "
            "checkable); CPU container numbers"
        ),
        "prompt_tokens": args.prompt_tokens,
        "max_tokens": args.max_tokens,
    }

    try:
        # -- ttft/itl split + bit-exactness ------------------------------
        want, _, _, _ = monolithic_tokens(urls[0], prompt, args.max_tokens)
        mono = PoolClient([urls[0]], protocol="http",
                          health_interval_s=None)
        mono_ttft, mono_itl = [], []
        try:
            payload = {"TOKENS": [list(prompt)],
                       "MAX_TOKENS": int(args.max_tokens)}
            _drive_session(mono.generate_stream(
                "tiny_lm_generate", payload))  # jit warmup
            for _ in range(args.split_sessions):
                _, _, ttft, itls = _drive_session(mono.generate_stream(
                    "tiny_lm_generate", payload))
                mono_ttft.append(ttft)
                mono_itl.extend(itls)
        finally:
            mono.close()
        client = DisaggClient(
            [EndpointSpec(urls[0], role="prefill"),
             EndpointSpec(urls[1], role="decode")],
            protocol="http", health_interval_s=None)
        dis_ttft, dis_itl, exact = [], [], True
        try:
            _drive_session(client.generate_stream(
                prompt, max_tokens=args.max_tokens))  # jit warmup
            for _ in range(args.split_sessions):
                tokens, indices, ttft, itls = _drive_session(
                    client.generate_stream(
                        prompt, max_tokens=args.max_tokens))
                dis_ttft.append(ttft)
                dis_itl.extend(itls)
                _, _, ok = session_problems(
                    tokens, indices, want, args.max_tokens)
                exact = exact and ok

            out["ttft_itl"] = {
                "sessions": args.split_sessions,
                "bit_exact": bool(exact),
                "monolithic": {"ttft_ms": _percentiles(mono_ttft),
                               "itl_ms": _percentiles(mono_itl)},
                "disagg": {"ttft_ms": _percentiles(dis_ttft),
                           "itl_ms": _percentiles(dis_itl)},
            }
            print("ttft_itl:", json.dumps(out["ttft_itl"]))

            # -- steady state: 0 region creates / registration RPCs ------
            arena = client.arena()
            before = arena.stats()
            t0 = time.perf_counter()
            for _ in range(args.steady_sessions):
                _drive_session(client.generate_stream(
                    prompt, max_tokens=args.max_tokens))
            elapsed = time.perf_counter() - t0
            after = arena.stats()
            out["steady_state"] = {
                "handoffs": args.steady_sessions,
                "region_creates_per_handoff": (
                    after["regions_created"] - before["regions_created"])
                / args.steady_sessions,
                "registration_rpcs_per_handoff": (
                    after["registrations_issued"]
                    - before["registrations_issued"])
                / args.steady_sessions,
                "arena_hit_rate": after["hit_rate"],
                "residual_leased_bytes": after["leased_bytes"],
                "sessions_per_s": round(args.steady_sessions / elapsed, 1),
            }
            print("steady_state:", json.dumps(out["steady_state"]))
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()

    # -- chaos: decode replica killed mid-stream (own stack) -------------
    out["chaos"] = run_chaos_arm(sessions=args.chaos_sessions,
                                 prompt_tokens=args.prompt_tokens,
                                 max_tokens=args.max_tokens)
    print("chaos:", json.dumps(out["chaos"]))

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(f"wrote {args.output}")
    return check(args.output)


if __name__ == "__main__":
    sys.exit(main())
