"""Generate BENCH_INTEGRITY.json: the end-to-end response-integrity proof.

Two arms over in-process replica servers (the same topology every other
bench in this repo uses — CPU container numbers, honest about it):

- **overhead**: the A/A cost story for always-on contract validation.
  Closed-loop perf against one honest replica, three runs: validation
  OFF twice (their p50 delta IS the measurement noise floor — same
  binary, same arm, nothing changed) and validation ON once. The claim
  is that the ON/OFF p50 delta sits within the A/A noise floor — plus
  the directly-measured per-response validation cost (ns p50/p99 from
  the ``client_integrity`` row ``perf.py --validate`` appends), which is
  the honest number the latency delta merely bounds from above.
- **byzantine**: a 3-replica pool where one replica LIES (seeded
  deterministic corruption: shape lies, dtype lies, truncations, wrong
  request ids — ``client_tpu.testing.byzantine``). Every response is
  value-checked against the known ``simple`` sum/diff contract. The
  claims: ZERO corrupt results delivered to the caller, ZERO
  caller-visible errors (failover absorbed every lie), the byzantine
  replica is NAMED — quarantined by the pool mid-replay (typed
  ``EndpointQuarantined``) and flagged as a ``byzantine_replica``
  anomaly by the doctor's rules.

``bit_flip`` is deliberately absent from the byzantine arm's fault mix:
a same-size payload bit-flip with consistent headers is invisible to
any client-side structural check (docs/integrity.md "detectability") —
putting it in would either deliver corrupt values (failing the claim
for a documented reason) or require value redundancy the wire protocol
does not carry. The contract layer's claim is every STRUCTURAL lie.

``--check`` re-validates an existing artifact's acceptance invariants
and exits nonzero on violation (tests/test_integrity.py pins the same
claims); ``tools/capacity_gate.py --integrity`` re-RUNS the byzantine
arm live:

    JAX_PLATFORMS=cpu python tools/bench_integrity.py [-o BENCH_INTEGRITY.json]
    JAX_PLATFORMS=cpu python tools/bench_integrity.py --check BENCH_INTEGRITY.json
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BYZANTINE_KINDS = ("shape_lie", "dtype_lie", "truncate", "wrong_id",
                   "garbage_json")


def run_overhead_arm(requests: int = 300, concurrency: int = 4):
    """A/A: validation-off twice (noise floor), validation-on once."""
    from client_tpu import integrity
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    srv = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    policy = integrity.default_policy()
    rows = {}
    try:
        url = srv.url
        # one discarded warmup run: server-side jit + connection setup
        # must not land in ANY arm (it would drown the comparison)
        PerfRunner(url, model_name="simple").run(
            concurrency=concurrency, measurement_requests=requests // 2)
        for arm, contract in (("off_a", False), ("off_b", False),
                              ("on", True)):
            policy.contract = contract
            row = PerfRunner(
                url, model_name="simple", validate=contract,
            ).run(concurrency=concurrency, measurement_requests=requests)
            rows[arm] = {
                "requests": row["requests"],
                "errors": row["errors"],
                "latency_ms": row["latency_ms"],
                "infer_per_sec": row["infer_per_sec"],
            }
            if contract:
                rows[arm]["client_integrity"] = row.get("client_integrity")
    finally:
        policy.contract = True  # never leave the process default off
        srv.stop()
        srv.close()
    noise_ms = abs(rows["off_a"]["latency_ms"]["p50"]
                   - rows["off_b"]["latency_ms"]["p50"])
    delta_ms = abs(rows["on"]["latency_ms"]["p50"]
                   - rows["off_a"]["latency_ms"]["p50"])
    # within-noise criterion: the ON arm's p50 shift must not exceed the
    # A/A floor by more than the floor itself again (2x) plus a 250 us
    # absolute guard for CPU-container scheduler jitter — generous, but
    # the directly-measured ns cost below is the number that matters
    within = delta_ms <= max(2.0 * noise_ms, 0.25)
    return {
        "requests_per_arm": requests,
        "concurrency": concurrency,
        "arms": rows,
        "aa_noise_floor_ms": round(noise_ms, 4),
        "on_off_delta_ms": round(delta_ms, 4),
        "within_noise_floor": bool(within),
        "validation_overhead_ns": (rows["on"].get("client_integrity") or {}
                                   ).get("overhead_ns"),
    }


def run_byzantine_arm(requests: int = 40, seed: int = 0xB12A,
                      quarantine_after: int = 3):
    """The quarantine proof, self-contained so ``capacity_gate.py
    --integrity`` can re-run it live: two honest replicas plus one
    byzantine replica in a round-robin pool; every result value-checked
    against the known sum/diff contract."""
    from client_tpu import doctor, integrity
    from client_tpu._tensor import InferInput
    from client_tpu.models import default_model_zoo
    from client_tpu.pool import EndpointQuarantined, PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing.byzantine import ByzantineHttpServer

    honest = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
              for _ in range(2)]
    byz = ByzantineHttpServer(
        ServerCore(default_model_zoo()),
        kinds=BYZANTINE_KINDS, seed=seed)
    byz.start()
    stats_before = integrity.global_stats().snapshot()
    events = []
    pool = PoolClient(
        [s.url for s in honest] + [byz.url], protocol="http",
        health_interval_s=None, routing="round_robin",
        quarantine_after=quarantine_after,
        on_event=events.append)
    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    row = {
        "requests": requests,
        "replicas": 3,
        "byzantine_url": byz.url,
        "fault_kinds": list(BYZANTINE_KINDS),
        "corrupt_delivered": 0,
        "caller_errors": 0,
    }
    try:
        for i in range(requests):
            i0 = InferInput("INPUT0", [1, 16], "INT32")
            i0.set_data_from_numpy(a)
            i1 = InferInput("INPUT1", [1, 16], "INT32")
            i1.set_data_from_numpy(b)
            try:
                result = pool.infer("simple", [i0, i1],
                                    request_id=f"byz-{i}")
                out0 = result.as_numpy("OUTPUT0")
                out1 = result.as_numpy("OUTPUT1")
                if (not np.array_equal(out0, a + b)
                        or not np.array_equal(out1, a - b)):
                    row["corrupt_delivered"] += 1
            except Exception:
                row["caller_errors"] += 1
        stats = pool.endpoint_stats()
        quarantined = [url for url, s in stats.items()
                       if s.get("quarantined")]
        row["quarantined_urls"] = quarantined
        row["byzantine_invalid_total"] = stats.get(
            byz.url, {}).get("invalid_total", 0)
        row["quarantine_events"] = sum(
            1 for e in events if isinstance(e, EndpointQuarantined))
        summary = pool.health_summary()
        row["health_summary"] = {
            k: summary.get(k)
            for k in ("quarantined", "invalid_total", "quarantine_dominated")}
        # the doctor's anomaly rules over exactly this pool state: the
        # byzantine replica must be NAMED, not just counted
        flags = doctor._anomalies(
            {"endpoints": [], "endpoint_stats": stats},
            churn_threshold_ops_s=1e9, skew_warn_ms=1e9)
        row["doctor_anomalies"] = [
            f for f in flags if f["flag"] == "byzantine_replica"]
    finally:
        pool.close()
        byz.stop()
        byz.close()
        for s in honest:
            s.stop()
            s.close()
    plan_stats = byz.plan.stats()
    row["faults_injected"] = plan_stats["corrupted"]
    after = integrity.global_stats().snapshot()
    row["violations_recorded"] = (after["violations"]
                                  - stats_before["violations"])
    return row


def byzantine_problems(row) -> list:
    """The byzantine arm's acceptance invariants (shared by --check and
    the live capacity_gate --integrity re-run)."""
    problems = []
    if row["requests"] <= 0:
        problems.append("byzantine arm ran no requests")
    if row.get("faults_injected", 0) <= 0:
        problems.append("the byzantine replica never actually corrupted "
                        "a response")
    if row["corrupt_delivered"] != 0:
        problems.append(f"{row['corrupt_delivered']} corrupt results "
                        "were delivered to the caller")
    if row["caller_errors"] != 0:
        problems.append(f"{row['caller_errors']} requests surfaced "
                        "errors instead of failing over to an honest "
                        "replica")
    if row.get("byzantine_url") not in (row.get("quarantined_urls") or []):
        problems.append("the byzantine replica was not quarantined")
    if row.get("quarantine_events", 0) <= 0:
        problems.append("no typed EndpointQuarantined event fired")
    if row.get("violations_recorded", 0) <= 0:
        problems.append("no integrity violations were recorded")
    anomalies = row.get("doctor_anomalies") or []
    if not any(a.get("url") == row.get("byzantine_url")
               for a in anomalies):
        problems.append("doctor rules did not name the byzantine "
                        "replica (byzantine_replica anomaly missing)")
    return problems


def check_doc(data) -> list:
    failures = []
    overhead = data["overhead"]
    if overhead["requests_per_arm"] <= 0:
        failures.append("overhead arm measured no requests")
    for arm in ("off_a", "off_b", "on"):
        arm_row = overhead["arms"].get(arm) or {}
        if arm_row.get("errors", 1) != 0:
            failures.append(f"overhead arm {arm} had request errors")
    if overhead.get("within_noise_floor") is not True:
        failures.append(
            f"validation ON p50 delta {overhead.get('on_off_delta_ms')} ms "
            f"exceeds the A/A noise floor "
            f"{overhead.get('aa_noise_floor_ms')} ms")
    ns = overhead.get("validation_overhead_ns") or {}
    if not ns.get("samples"):
        failures.append("overhead arm carries no measured per-response "
                        "validation cost (client_integrity.overhead_ns)")
    failures.extend(byzantine_problems(data["byzantine"]))
    return failures


def check(path: str) -> int:
    failures = check_doc(json.loads(Path(path).read_text()))
    for msg in failures:
        print(f"CHECK FAILED: {msg}", file=sys.stderr)
    if not failures:
        print(f"{path}: all response-integrity acceptance invariants hold")
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_INTEGRITY.json")
    parser.add_argument("--overhead-requests", type=int, default=300)
    parser.add_argument("--byzantine-requests", type=int, default=40)
    parser.add_argument("--check", metavar="ARTIFACT",
                        help="validate an existing artifact instead of "
                             "benchmarking")
    args = parser.parse_args()
    if args.check:
        return check(args.check)

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "end-to-end response integrity (client_tpu.integrity) over "
            "in-process replica servers on CPU: contract-validation "
            "overhead vs an A/A noise floor, and the byzantine-replica "
            "quarantine proof (client_tpu.testing.byzantine) — zero "
            "corrupt results delivered, the lying replica named by the "
            "pool's quarantine and the doctor's anomaly rules"),
    }
    print("running overhead (A/A) arm ...", flush=True)
    out["overhead"] = run_overhead_arm(requests=args.overhead_requests)
    print(json.dumps(out["overhead"], indent=2))
    print("running byzantine quarantine arm ...", flush=True)
    out["byzantine"] = run_byzantine_arm(requests=args.byzantine_requests)
    print(json.dumps(out["byzantine"], indent=2))

    failures = check_doc(out)
    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(f"wrote {args.output}")
    for msg in failures:
        print(f"ACCEPTANCE FAILED: {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
