"""Instrumented grpc_stream soak: answer the growth question for good.

VERDICT-r4 #4: the 1800 s SOAK_r04 capture left "is grpc_stream RSS growth
bounded?" open (raw tail slope 125.3 KB/min, steeper than the whole-run
48.9). This tool instruments the loop itself instead of re-measuring the
symptom:

  - every 30 s: raw RSS, post-``malloc_trim`` RSS, ``mallinfo2`` (in-use
    heap / free-but-unreturned / mmapped), and the ``tracemalloc`` traced
    total — so Python-level reachable growth, glibc retention, and OS-view
    RSS are separated in ONE trace;
  - an A/B at the process level: the same loop re-run with
    ``MALLOC_ARENA_MAX=1`` in the same artifact, pinning (or refuting) the
    arena theory.

Usage (writes SOAK_STREAM_r05.json at the repo root):

    python tools/soak_stream_probe.py [--seconds 3600] [--ab-seconds 1800]

The client loop runs in a child process per variant (the parent holds the
server), exactly like tests/test_soak_slope.py's topology so numbers are
comparable with SOAK_r0*.json.

Reference role: memory_leak_test.cc's long-loop leak hunting
(/root/reference/src/c++/tests/memory_leak_test.cc), with the attribution
instrumentation the reference leaves to external tooling (valgrind massif).
"""

from __future__ import annotations

import argparse
import ctypes
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SAMPLE_EVERY_S = 30.0


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


class _Mallinfo2(ctypes.Structure):
    _fields_ = [(n, ctypes.c_size_t) for n in (
        "arena", "ordblks", "smblks", "hblks", "hblkhd", "usmblks",
        "fsmblks", "uordblks", "fordblks", "keepcost")]


def _mallinfo() -> dict:
    try:
        libc = ctypes.CDLL("libc.so.6")
        libc.mallinfo2.restype = _Mallinfo2
        mi = libc.mallinfo2()
        return {
            "in_use_kb": mi.uordblks // 1024,
            "free_unreturned_kb": mi.fordblks // 1024,
            "arena_kb": mi.arena // 1024,
            "mmapped_kb": mi.hblkhd // 1024,
        }
    except Exception:
        return {}


def _malloc_trim() -> None:
    try:
        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except Exception:
        pass


def _fit_kb_per_min(samples, key):
    import numpy as np

    pts = [(s["t"], s[key]) for s in samples if key in s]
    if len(pts) < 3:
        return 0.0
    t = np.array([p[0] for p in pts], dtype=np.float64)
    v = np.array([p[1] for p in pts], dtype=np.float64)
    return float(np.polyfit(t - t[0], v, 1)[0] * 60.0)


def _slopes(samples, key):
    tail = [s for s in samples if s["t"] >= samples[-1]["t"] - 300.0]
    return {
        "overall_kb_per_min": round(_fit_kb_per_min(samples, key), 1),
        "tail300_kb_per_min": round(_fit_kb_per_min(tail, key), 1),
    }


def child_loop(url: str, seconds: float) -> dict:
    """The grpc_stream loop with in-loop instrumentation (child process)."""
    import threading
    import tracemalloc

    import numpy as np

    import client_tpu.grpc as grpcclient

    tracemalloc.start(10)
    payload = np.random.default_rng(7).integers(
        0, 1000, (1, 65536)).astype(np.int32)
    samples: list = []
    t_start = time.monotonic()

    with grpcclient.InferenceServerClient(url) as client:
        got = threading.Semaphore(0)
        errors: list = []

        def callback(result, error):
            if error is not None:
                errors.append(str(error))
            got.release()

        client.start_stream(callback)
        deadline = t_start + seconds
        next_sample = t_start  # sample immediately for a t=0 baseline
        iters = 0
        try:
            while time.monotonic() < deadline and not errors:
                inp = grpcclient.InferInput("INPUT0", [1, 65536], "INT32")
                inp.set_data_from_numpy(payload)
                client.async_stream_infer("custom_identity_int32", [inp])
                assert got.acquire(timeout=30)
                iters += 1
                now = time.monotonic()
                if now >= next_sample:
                    import gc

                    gc.collect()
                    entry = {"t": round(now - t_start, 1),
                             "rss_raw_kb": _rss_kb()}
                    entry.update({f"malloc_{k}": v
                                  for k, v in _mallinfo().items()})
                    traced, _peak = tracemalloc.get_traced_memory()
                    entry["tracemalloc_kb"] = traced // 1024
                    _malloc_trim()
                    entry["rss_trimmed_kb"] = _rss_kb()
                    samples.append(entry)
                    next_sample = now + SAMPLE_EVERY_S
        finally:
            client.stop_stream()

    # where do the surviving Python allocations live? (flat totals with a
    # growing site would still be a churn hotspot worth naming)
    top = tracemalloc.take_snapshot().statistics("lineno")[:5]
    return {
        "iters": iters,
        "seconds": seconds,
        "errors": errors[:3],
        "arena_max": os.environ.get("MALLOC_ARENA_MAX", "default"),
        "samples": samples,
        "tracemalloc_top": [
            {"site": str(stat.traceback), "kb": stat.size // 1024,
             "count": stat.count}
            for stat in top
        ],
        "slopes": {
            key: _slopes(samples, key)
            for key in ("rss_raw_kb", "rss_trimmed_kb", "malloc_in_use_kb",
                        "malloc_free_unreturned_kb", "tracemalloc_kb")
            if samples and key in samples[0]
        },
    }


_SERVER_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
from client_tpu.models import default_model_zoo
from client_tpu.server import GrpcInferenceServer, ServerCore
import time
g = GrpcInferenceServer(ServerCore(default_model_zoo())).start()
print("PORT", g.port, flush=True)
time.sleep(86400)
"""


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seconds", type=float, default=3600.0,
                        help="default-arena instrumented run length")
    parser.add_argument("--ab-seconds", type=float, default=1800.0,
                        help="MALLOC_ARENA_MAX=1 comparison run length "
                             "(0 skips the A/B)")
    parser.add_argument("--out", default=os.path.join(
        ROOT, "SOAK_STREAM_r05.json"))
    parser.add_argument("--child", action="store_true",
                        help="internal: run the client loop")
    parser.add_argument("--url")
    parser.add_argument("--json-out")
    args = parser.parse_args()

    if args.child:
        result = child_loop(args.url, args.seconds)
        with open(args.json_out, "w") as f:
            json.dump(result, f)
        return 0

    env = dict(os.environ)
    env["PYTHONPATH"] = ""  # skip axon sitecustomize (dead tunnel hangs jax)
    env["JAX_PLATFORMS"] = "cpu"
    server = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SCRIPT.format(repo=ROOT)],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        line = server.stdout.readline().strip()
        assert line.startswith("PORT"), line
        url = f"127.0.0.1:{line.split()[1]}"

        out = {"url": url, "sample_every_s": SAMPLE_EVERY_S}
        plan = [("default_arenas", args.seconds, None)]
        if args.ab_seconds > 0:
            plan.append(("arena_max_1", args.ab_seconds, "1"))
        for name, seconds, arena_max in plan:
            child_env = dict(env)
            if arena_max is not None:
                child_env["MALLOC_ARENA_MAX"] = arena_max
            # beside the artifact, pid-suffixed: a pytest smoke run and a
            # real long capture must never read each other's child output
            tmp = os.path.join(
                os.path.dirname(os.path.abspath(args.out)) or ROOT,
                f".soak_child_{name}_{os.getpid()}.json")
            print(json.dumps({"phase": name, "seconds": seconds}),
                  file=sys.stderr, flush=True)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 "--url", url, "--seconds", str(seconds), "--json-out", tmp],
                env=child_env, timeout=seconds + 300,
            )
            if proc.returncode == 0 and os.path.exists(tmp):
                with open(tmp) as f:
                    out[name] = json.load(f)
                os.unlink(tmp)
            else:
                out[name] = {"error": f"child rc={proc.returncode}"}
            print(json.dumps({"phase": name,
                              "slopes": out[name].get("slopes")}),
                  file=sys.stderr, flush=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({"ok": True, "out": args.out}))
        return 0
    finally:
        server.terminate()
        server.wait(timeout=10)


if __name__ == "__main__":
    sys.exit(main())
