"""Spawn an inference server in its OWN process for cross-process tests
and benches (shared by bench.py and tests/test_tpu_shm_xproc.py).

The child always runs with the axon sitecustomize stripped and the cpu
backend pinned: a wedged TPU tunnel hangs any jax init it touches, and on
a single-chip host the accelerator must stay with the measuring client —
two processes cannot both own the TPU.
"""

from __future__ import annotations

import os
import select
import subprocess
import sys

IDENTITY_SERVER_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
from client_tpu.models.simple import IdentityModel
from client_tpu.server import HttpInferenceServer, ServerCore
import time
core = ServerCore([IdentityModel("identity_fp32", "FP32", delay_s=0.0)])
h = HttpInferenceServer(core).start()
print("PORT", h.port, flush=True)
time.sleep(86400)
"""

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class XprocServer:
    """A server subprocess announcing ``PORT <n>`` on stdout.

    The handshake validates the announcement line and tears the child down
    on ANY startup failure (crash before PORT, stray stdout line, timeout) —
    a half-started child sleeping 24h must never outlive its spawner.
    """

    def __init__(self, script: str = IDENTITY_SERVER_SCRIPT, timeout_s: float = 120.0):
        env = dict(os.environ)
        env["PYTHONPATH"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        self._proc = subprocess.Popen(
            [sys.executable, "-c", script.format(repo=_REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        try:
            ready, _, _ = select.select([self._proc.stdout], [], [], timeout_s)
            if not ready:
                raise RuntimeError(f"server subprocess did not start in {timeout_s:.0f}s")
            line = self._proc.stdout.readline().strip()
            if not line.startswith("PORT "):
                err = ""
                if self._proc.poll() is not None:
                    err = (self._proc.stderr.read() or "")[-500:]
                raise RuntimeError(
                    f"server subprocess announced {line!r} instead of 'PORT <n>'"
                    + (f"; stderr tail: {err}" if err else "")
                )
            self.port = int(line.split()[1])
            self.url = f"127.0.0.1:{self.port}"
        except BaseException:
            self.stop()
            raise

    def stop(self) -> None:
        self._proc.terminate()
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait(timeout=10)

    def __enter__(self) -> "XprocServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
