"""Compute-bound chip benchmark: MXU sustained rate, Pallas flash attention,
and the densenet model family, with MFU estimates.

VERDICT-r2 #10 ("compute-bound chip benchmark … infer/sec + an MFU
estimate"). Methodology matters on tunneled chips: per-dispatch wall-clock
through the axon tunnel is unreliable for sub-ms ops (completion
notifications are decoupled from device completion — a 8192^3 matmul
"measured" 75 PFLOP/s dispatched one-at-a-time), so every measurement here
chains N iterations INSIDE one jitted computation (`lax.fori_loop` /
unrolled chain) and divides one dispatch's wall time by N. First compile is
excluded by a warmup dispatch.

Prints one JSON object; run on the chip via
    python tools/chip_bench.py [--json-out PATH]

Reference parity: perf_analyzer's concurrency/throughput role for the
compute-bound regime (the reference publishes no numbers — BASELINE.md §1);
MFU framing follows the public scaling-book convention (achieved FLOPs /
peak FLOPs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bf16 peak TFLOP/s per chip generation (public spec sheets); device_kind
# strings as PJRT reports them
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5": 459.0,  # v5p
    "TPU v6 lite": 918.0,  # v6e/Trillium
}


def _peak_for(kind: str):
    for prefix, peak in sorted(PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return None


def _timed_single_dispatch(fn, *args, iters_inside: int, repeats: int = 5):
    """Median wall time of one dispatch that runs ``iters_inside`` steps.

    The shared timing primitive for every chip tool (decode_attn_chip,
    flash_sweep import it) — methodology changes here change all numbers
    together, keeping them comparable."""
    fn(*args).block_until_ready()  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        times.append((time.perf_counter() - t0) / iters_inside)
    return sorted(times)[len(times) // 2]


def bench_dispatch_overhead(jax, jnp, np, repeats=9):
    """Median wall time of a trivial synchronous dispatch — on a tunneled
    chip this is the per-dispatch RTT floor every blocked measurement pays
    (measured ~60 ms on the 2026-07-29 axon tunnel; sub-ms on a local
    host). Subtract it mentally from any single-dispatch number."""
    one = jnp.ones((8,), jnp.float32)
    f = jax.jit(lambda x: x + 1.0)
    dt = _timed_single_dispatch(f, one, iters_inside=1, repeats=repeats)
    return round(dt * 1000, 3)


def bench_matmul(jax, jnp, np, n=4096, chain=16, pipeline=8):
    """Sustained MXU rate: ``chain`` dependent n^3 bf16 matmuls per dispatch.

    Two timings: ``blocked`` (block every dispatch — includes one full
    dispatch RTT, the honest end-to-end number) and ``pipelined``
    (``pipeline`` dispatches in flight, block the last — amortizes the RTT,
    the best estimate of the device-side rate; 2026-07-29 tunnel: 28 vs
    119 TFLOP/s, the 91 TFLOP/s gap being ~60 ms RTT per blocked call)."""
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n), dtype=np.float32),
                    dtype=jnp.bfloat16)

    @jax.jit
    def chained(x):
        # pure dependent chain: each matmul needs the previous result, so
        # nothing can be elided or reordered; XLA does not rewrite
        # (x@a)@a -> x@(a@a). A tanh between steps (tried first) adds ~4 ms
        # of VPU transcendental per step and corrupts the MXU number.
        for _ in range(chain):
            x = x @ a
        return x

    dt_blocked = _timed_single_dispatch(chained, a, iters_inside=chain)

    chained(a).block_until_ready()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = None
        for _ in range(pipeline):
            out = chained(a)
        out.block_until_ready()
        times.append((time.perf_counter() - t0) / (pipeline * chain))
    dt_pipelined = sorted(times)[len(times) // 2]

    flops = 2 * n**3
    return {"n": n, "chain": chain,
            "ms_per_matmul_blocked": round(dt_blocked * 1000, 3),
            "tflops_blocked": round(flops / dt_blocked / 1e12, 3),
            "ms_per_matmul_pipelined": round(dt_pipelined * 1000, 3),
            "tflops": round(flops / dt_pipelined / 1e12, 3)}


def bench_flash_attention(jax, jnp, np, batch=4, seq=2048, heads=8, dim=128,
                          steps=10):
    """Pallas flash attention under real Mosaic, chained in one dispatch."""
    from client_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    shape = (batch, seq, heads, dim)

    def mk():
        return jnp.asarray(rng.standard_normal(shape, dtype=np.float32),
                           dtype=jnp.bfloat16)

    q, k, v = mk(), mk(), mk()

    @jax.jit
    def chained(q, k, v):
        def body(_, acc):
            o = flash_attention(q, k, v)
            # full-output reduction: a scalar slice would let XLA narrow
            # the computation (it can't see into pallas_call, but keep the
            # protocol uniform with bench_densenet where slicing bit)
            return acc + jnp.sum(o.astype(jnp.float32))

        return jax.lax.fori_loop(0, steps, body, jnp.float32(0))

    dt = _timed_single_dispatch(chained, q, k, v, iters_inside=steps)
    flops = 4 * batch * heads * seq * seq * dim  # QK^T + PV, 2*S*S*D each
    return {"batch": batch, "seq": seq, "heads": heads, "dim": dim,
            "ms_per_call": round(dt * 1000, 3),
            "tflops": round(flops / dt / 1e12, 3)}


def _flax_model_flops(width, stages, num_classes):
    """Forward-pass FLOPs for models/vision.py's DenseNetish at 224x224 via
    XLA's own cost analysis (exact for the compiled graph)."""
    import jax
    import jax.numpy as jnp

    from client_tpu.models.vision import _build_flax_model

    module = _build_flax_model(num_classes, width, stages)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 224, 224, 3), jnp.bfloat16))
    lowered = jax.jit(module.apply).lower(
        params, jnp.zeros((1, 224, 224, 3), jnp.bfloat16))
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost.get("flops", 0.0)), module, params


def bench_densenet(jax, jnp, np, width, arch, steps=20, batch=8):
    """On-device forward rate for the densenet family at serving batch."""
    from client_tpu.models.vision import DenseNetModel

    flops1, module, params = _flax_model_flops(
        width, DenseNetModel.ARCHS[arch], 1000)
    rng = np.random.default_rng(2)
    x = jnp.asarray(
        rng.standard_normal((batch, 224, 224, 3), dtype=np.float32),
        dtype=jnp.bfloat16)

    @jax.jit
    def chained(params, x):
        def body(_, carry):
            out = module.apply(params, x)
            # sum over the WHOLE batch: carrying out[0, 0] alone let XLA
            # slice the conv stack to batch=1 (measured "MFU" 1.28 — the
            # impossible number that exposed it)
            return carry + jnp.sum(out.astype(jnp.float32))

        return jax.lax.fori_loop(0, steps, body, jnp.float32(0))

    dt = _timed_single_dispatch(chained, params, x, iters_inside=steps)
    flops = flops1 * batch  # cost_analysis counted the batch=1 graph
    return {"width": width, "arch": arch, "batch": batch,
            "ms_per_batch": round(dt * 1000, 3),
            "images_per_sec": round(batch / dt, 1),
            "gflops_per_image": round(flops1 / 1e9, 2),
            "tflops": round(flops / dt / 1e12, 2)}


def bench_generate(jax, jnp, np, prompt=32, k=64):
    """Autoregressive decode rate for the tiny_lm_generate fixture.

    Two numbers: per-token dispatch (each step blocked — the chunk=1
    streaming-serving latency, paying one dispatch RTT per token) and the
    lax.scan chunked path (K tokens inside ONE XLA dispatch — the
    dispatch-amortized device decode rate). Their ratio is the tunnel/RTT
    amortization the scan-in-XLA design buys (genai-perf's ITL regime)."""
    from client_tpu.models.generate import TinyGenerateModel

    model = TinyGenerateModel()
    model._ensure_built()
    dec = model._decoder
    rng = np.random.default_rng(3)
    toks = rng.integers(0, dec.VOCAB, size=prompt)

    caches, pos = dec._fresh_cache(), 0
    logits = None
    for t in toks:
        logits, caches = dec._step_fn(dec._params, caches, int(t), pos)
        pos += 1
    first = int(np.asarray(logits).argmax())

    k = min(k, dec.MAX_LEN - pos - 1)
    chunk_fn = model._chunk_fn(k)

    def chunked(token, p):
        out, _ = chunk_fn(dec._params, caches, token, p)
        return out

    dt_chunked = _timed_single_dispatch(chunked, first, pos, iters_inside=k)

    # per-token: block every step — the feed-back loop round-trips the
    # host for the argmax, so serving really does pay this per token
    def one_step(token, p):
        return dec._step_fn(dec._params, caches, token, p)[0]

    dt_token = _timed_single_dispatch(
        one_step, first, pos, iters_inside=1, repeats=7)

    return {
        "prompt_tokens": int(prompt), "chunk": int(k),
        "ms_per_token_dispatch": round(dt_token * 1000, 3),
        "tokens_per_sec_dispatch": round(1.0 / dt_token, 1),
        "ms_per_token_chunked": round(dt_chunked * 1000, 3),
        "tokens_per_sec_chunked": round(1.0 / dt_chunked, 1),
        "chunk_amortization": round(dt_token / dt_chunked, 1),
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--json-out", default=None)
    parser.add_argument(
        "--small", action="store_true",
        help="tiny shapes: verifies the full pipeline off-chip in seconds")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    device = jax.devices()[0]
    peak = _peak_for(device.device_kind)
    result = {
        "platform": jax.default_backend(),
        "device_kind": device.device_kind,
        "peak_bf16_tflops": peak,
    }

    result["dispatch_overhead_ms"] = bench_dispatch_overhead(jax, jnp, np)
    if args.small:
        mm = bench_matmul(jax, jnp, np, n=256, chain=4, pipeline=2)
        fa = bench_flash_attention(
            jax, jnp, np, batch=1, seq=256, heads=2, dim=64, steps=2)
        gen = bench_generate(jax, jnp, np, prompt=8, k=8)
        dn_specs = ((8, "lite", 1),)
    else:
        mm = bench_matmul(jax, jnp, np)
        fa = bench_flash_attention(jax, jnp, np)
        gen = bench_generate(jax, jnp, np)
        dn_specs = ((96, "lite", 8), (256, "lite", 8), (64, "121", 8))
    result["matmul_bf16"] = mm
    result["flash_attention"] = fa
    result["llm_decode"] = gen
    dn = {}
    for width, arch, batch in dn_specs:
        key = f"w{width}_{arch}"
        try:
            dn[key] = bench_densenet(jax, jnp, np, width, arch, batch=batch)
        except Exception as e:  # keep partial results on tunnel flakes
            dn[key] = {"error": f"{type(e).__name__}: {e}"[:200]}
    result["densenet"] = dn

    if peak:
        result["mfu"] = {
            "matmul": round(mm["tflops"] / peak, 3),
            "flash_attention": round(fa["tflops"] / peak, 3),
            **{
                f"densenet_{k}": round(v["tflops"] / peak, 3)
                for k, v in dn.items() if "tflops" in v
            },
        }
        impossible = [k for k, v in result["mfu"].items() if v > 1.0]
        if impossible:
            # a >1.0 "MFU" is physically impossible: through the tunnel the
            # readiness signal can fire before device completion, so flag
            # rather than publish a wrong number (2026-07-29: densenet-121
            # rows read 1.24 while matmul in the same process read 0.60)
            result["mfu_caveat"] = (
                f"rows {impossible} exceed 1.0 — timing signal fired before "
                "device completion (tunnel artifact); trust relative "
                "images/sec ordering, not these absolute MFU rows")

    text = json.dumps(result, indent=1)
    print(text)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
