"""Generate BENCH_FLIGHT.json: the flight recorder's overhead proof.

Four measurements back the "always-on" claim (tail-based retention means
full forensic detail for exactly the requests worth explaining, at a
per-event cost the hot path can afford):

1. **Per-event record cost** — ``flight.note()`` with an active scratch
   (the enabled path: one contextvar read + ``perf_counter_ns`` + one
   bounded list append) and with none (the disabled path: one contextvar
   read + one branch). The committed medians are the ≤1 µs/event and
   one-branch-when-disabled claims.

2. **Commit cost, retained vs dropped** — the per-REQUEST settle: the
   verdict, the rolling-threshold update, and (retained only) the
   timeline build + ring append.

3. **Steady-state memory bound** — a 64-caller zipfian replay against a
   live in-process server with the recorder attached (the ring must end
   ≤ capacity), plus a 16-thread all-retained soak at 8x the ring
   capacity: the ring stays exactly at capacity, the overflow is counted
   as evicted, and process RSS growth over the soak stays bounded.

4. **Chaos attribution** — a 3-replica pool with ONE replica behind a
   50 ms latency proxy: the retained slow-tail timelines' per-layer/
   per-endpoint attribution must NAME the faulted endpoint (the
   ``tail_divergence`` detector's dominant key carries its url).

``--check`` re-validates the committed artifact's invariants (CI'd by
``tests/test_flight.py::test_bench_flight_artifact_claims``);
``tools/capacity_gate.py --flight`` proves live that recorder-on
capacity stays within 5% of the committed recorder-off floor.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_flight.py [-o BENCH_FLIGHT.json]
    JAX_PLATFORMS=cpu python tools/bench_flight.py --check
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path
from typing import Any, Dict, List

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RECORD_EVENTS = 200_000
DISABLED_EVENTS = 500_000
COMMIT_REQUESTS = 20_000
SOAK_THREADS = 16
SOAK_REQUESTS_PER_THREAD = 2_000
SOAK_CAPACITY = 256
CHAOS_LATENCY_S = 0.05
CHAOS_REQUESTS = 600
ZIPF_TRACE = ("mixed:duration_s=3,rate=300,stream_fraction=0,"
              "seq_fraction=0,unary_model=batched_matmul,"
              "hot_key_universe=64,hot_key_alpha=1.1")
ZIPF_SEED = 2026
ZIPF_WORKERS = 64


def _percentiles(samples_ns: List[float]) -> Dict[str, float]:
    from client_tpu.utils import sorted_percentile

    s = sorted(samples_ns)
    return {
        "p50": round(sorted_percentile(s, 0.5), 1),
        "p90": round(sorted_percentile(s, 0.9), 1),
        "p99": round(sorted_percentile(s, 0.99), 1),
    }


def bench_record() -> Dict[str, Any]:
    """Per-event note() cost, enabled (active scratch) vs disabled."""
    from client_tpu import flight

    recorder = flight.FlightRecorder(capacity=64, max_events=RECORD_EVENTS + 8)
    # enabled: one scratch, RECORD_EVENTS appends, timed in chunks of 1k
    # so the per-event figure is a median over many samples rather than
    # one long-run mean hiding allocator pauses
    scratch = recorder.begin("bench", "m")
    assert scratch is not None
    chunks: List[float] = []
    chunk = 1000
    for _ in range(RECORD_EVENTS // chunk):
        t0 = time.perf_counter_ns()
        for _ in range(chunk):
            flight.note("bench", "event", attempt=1)
        chunks.append((time.perf_counter_ns() - t0) / chunk)
    recorder.commit(scratch)
    enabled = _percentiles(chunks)

    # disabled: no active scratch — the one-branch path every layer pays
    # when nothing is being recorded
    chunks = []
    for _ in range(DISABLED_EVENTS // chunk):
        t0 = time.perf_counter_ns()
        for _ in range(chunk):
            flight.note("bench", "event", attempt=1)
        chunks.append((time.perf_counter_ns() - t0) / chunk)
    disabled = _percentiles(chunks)
    return {
        "events": RECORD_EVENTS,
        "enabled_ns": enabled,
        "disabled_ns": disabled,
        "note": "per-event medians over 1k-event chunks; enabled = "
                "contextvar read + perf_counter_ns + bounded list append "
                "(+ one attr dict); disabled = contextvar read + branch",
    }


def bench_commit() -> Dict[str, Any]:
    """Per-request commit cost: retained (baseline_ratio=1 -> every
    request builds a timeline and lands in the ring) vs dropped
    (baseline_ratio=0, no threshold -> verdict says drop wholesale)."""
    from client_tpu import flight

    out: Dict[str, Any] = {"requests": COMMIT_REQUESTS}
    for label, ratio in (("retained", 1.0), ("dropped", 0.0)):
        recorder = flight.FlightRecorder(
            capacity=256, baseline_ratio=ratio,
            threshold_min_samples=10**9)  # never learns a slow threshold
        for _ in range(COMMIT_REQUESTS):
            scratch = recorder.begin("bench", "m")
            flight.note("pool", "route", url="u")
            flight.note("span", "finish", ms=1.0)
            recorder.commit(scratch)
        stats = recorder.stats()
        out[label + "_ns"] = stats[f"commit_{label}_ns"]
        out[label + "_count"] = (stats["retained_total"]
                                 if label == "retained"
                                 else stats["dropped"])
    return out


def _rss_kb() -> int:
    for line in open("/proc/self/status"):
        if line.startswith("VmRSS:"):
            return int(line.split()[1])
    return 0


def bench_soak() -> Dict[str, Any]:
    """16 threads x 2000 all-retained requests against a 256-slot ring:
    the ring must stay exactly at capacity (oldest evicted, counted) and
    RSS growth must stay bounded — the committed memory-bound claim."""
    import threading

    from client_tpu import flight

    recorder = flight.FlightRecorder(capacity=SOAK_CAPACITY,
                                     baseline_ratio=1.0, max_events=32)
    rss_before = _rss_kb()

    def worker() -> None:
        for i in range(SOAK_REQUESTS_PER_THREAD):
            scratch = recorder.begin("bench", "m")
            for _ in range(8):
                flight.note("pool", "route", url="u", attempt=i)
            recorder.commit(scratch)

    threads = [threading.Thread(target=worker) for _ in range(SOAK_THREADS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    stats = recorder.stats()
    ring_events = sum(len(t.events) for t in recorder.retained())
    return {
        "threads": SOAK_THREADS,
        "requests": stats["requests"],
        "elapsed_s": round(elapsed, 3),
        "requests_per_s": round(stats["requests"] / elapsed, 1),
        "capacity": stats["capacity"],
        "ring": stats["ring"],
        "evicted": stats["evicted"],
        "ring_events": ring_events,
        "rss_before_kb": rss_before,
        "rss_after_kb": _rss_kb(),
        "rss_growth_kb": _rss_kb() - rss_before,
    }


def bench_zipf_replay() -> Dict[str, Any]:
    """A 64-caller zipfian replay against a live in-process server with
    the recorder attached: the committed steady-state bound is the
    replay row's ring <= capacity (drop-wholesale kept memory flat while
    thousands of requests flowed)."""
    from client_tpu import trace as trace_mod
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(f"127.0.0.1:{server.port}", "http",
                            "batched_matmul", flight=True)
        tr = trace_mod.generate(ZIPF_TRACE, seed=ZIPF_SEED)
        row = runner.run_trace(tr, speed=1.0, replay_workers=ZIPF_WORKERS)
    fl = row["client_flight"]
    return {
        "trace": ZIPF_TRACE,
        "seed": ZIPF_SEED,
        "replay_workers": ZIPF_WORKERS,
        "offered_rate": row["offered_rate"],
        "achieved_rate": row["achieved_rate"],
        "errors": row["errors"],
        "client_flight": fl,
    }


def bench_chaos() -> Dict[str, Any]:
    """3 replicas, one behind a 50 ms latency proxy: the retained tail
    must name the faulted endpoint through per-timeline attribution."""
    import numpy as np

    import client_tpu.http as httpclient
    from client_tpu.flight import FlightRecorder
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.pool import PoolClient
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    core = ServerCore(default_model_zoo())
    servers = [HttpInferenceServer(core).start() for _ in range(3)]
    proxy = ChaosProxy("127.0.0.1", servers[0].port).start()
    proxy.fault = Fault("latency", latency_s=CHAOS_LATENCY_S)
    faulted_url = f"127.0.0.1:{proxy.port}"
    urls = [faulted_url] + [f"127.0.0.1:{s.port}" for s in servers[1:]]
    recorder = FlightRecorder(capacity=512, slow_quantile=0.9,
                              threshold_min_samples=64,
                              baseline_ratio=0.05)
    tel = Telemetry(sample="off", flight=recorder)
    pool = PoolClient(urls, protocol="http", telemetry=tel,
                      routing="round_robin", health_interval_s=None)
    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    try:
        for _ in range(CHAOS_REQUESTS):
            in0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
            in0.set_data_from_numpy(a)
            in1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
            in1.set_data_from_numpy(b)
            pool.infer("simple", [in0, in1])
    finally:
        pool.close()
        proxy.stop()
        for s in servers:
            s.stop()
    stats = recorder.stats()
    divergence = recorder.tail_divergence()
    # every retained slow-tail timeline's dominant attribution key
    slow = [t for t in recorder.retained()
            if t.verdict in ("slow", "slo_breach")]
    dominants: Dict[str, int] = {}
    for t in slow:
        key = t.attribution()["dominant"]
        dominants[key] = dominants.get(key, 0) + 1
    named = bool(divergence
                 and divergence["dominant"].endswith(faulted_url))
    return {
        "requests": CHAOS_REQUESTS,
        "chaos_latency_ms": CHAOS_LATENCY_S * 1e3,
        "faulted_url": faulted_url,
        "retained": stats["retained"],
        "slow_tail_count": len(slow),
        "slow_tail_dominants": dominants,
        "tail_divergence": divergence,
        "named_faulted_endpoint": named,
    }


def check(doc: Dict[str, Any]) -> int:
    """Re-validate the committed artifact's invariants; 0 = all hold."""
    problems: List[str] = []
    record = doc["record"]
    if record["enabled_ns"]["p50"] > 1000.0:
        problems.append(
            f"per-event record median {record['enabled_ns']['p50']} ns "
            "exceeds the 1 µs/event target")
    if record["disabled_ns"]["p50"] > 500.0:
        problems.append(
            f"disabled-path median {record['disabled_ns']['p50']} ns is "
            "not a one-branch cost")
    if record["disabled_ns"]["p50"] > record["enabled_ns"]["p50"]:
        problems.append("disabled path costs more than enabled path")
    commit = doc["commit"]
    if commit["retained_count"] != commit["requests"]:
        problems.append("retained-commit arm did not retain every request")
    if commit["dropped_count"] != commit["requests"]:
        problems.append("dropped-commit arm did not drop every request")
    soak = doc["soak"]
    if soak["ring"] != soak["capacity"]:
        problems.append(
            f"soak ring {soak['ring']} != capacity {soak['capacity']}")
    if soak["evicted"] <= 0:
        problems.append("soak never evicted: the bound was not exercised")
    expected = soak["threads"] * SOAK_REQUESTS_PER_THREAD
    if soak["requests"] != expected:
        problems.append(
            f"soak lost requests: {soak['requests']} != {expected}")
    if soak["rss_growth_kb"] > 64 * 1024:
        problems.append(
            f"soak RSS grew {soak['rss_growth_kb']} kB (> 64 MB): the "
            "ring is not the memory bound it claims to be")
    replay = doc["zipf_replay"]
    fl = replay["client_flight"]
    if fl["ring"] > fl["capacity"]:
        problems.append("zipfian replay overflowed the retained ring")
    if fl["requests"] <= 0:
        problems.append("zipfian replay recorded no requests")
    if fl["retained_fraction"] >= 0.5:
        problems.append(
            f"zipfian replay retained {fl['retained_fraction']:.0%} of "
            "requests — tail-based retention is not dropping the healthy "
            "majority")
    chaos = doc["chaos"]
    if not chaos["named_faulted_endpoint"]:
        problems.append(
            "chaos run: the retained tail's attribution did not name the "
            "latency-faulted endpoint")
    if chaos["slow_tail_count"] <= 0:
        problems.append("chaos run retained no slow-tail timelines")
    for p in problems:
        print(f"CHECK FAIL: {p}")
    if not problems:
        print("CHECK OK: all committed flight-recorder claims hold")
    return 1 if problems else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_FLIGHT.json")
    parser.add_argument("--check", action="store_true",
                        help="validate the committed artifact instead of "
                             "re-measuring")
    args = parser.parse_args(argv)

    if args.check:
        return check(json.loads(Path(args.output).read_text()))

    doc: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": {
            "python": platform.python_version(),
            "machine": platform.machine(),
            "system": platform.system(),
        },
    }
    print("1/5 per-event record cost ...")
    doc["record"] = bench_record()
    print(f"    enabled p50 {doc['record']['enabled_ns']['p50']} ns, "
          f"disabled p50 {doc['record']['disabled_ns']['p50']} ns")
    print("2/5 commit cost (retained vs dropped) ...")
    doc["commit"] = bench_commit()
    print(f"    retained p50 {doc['commit']['retained_ns']['p50']} ns, "
          f"dropped p50 {doc['commit']['dropped_ns']['p50']} ns")
    print("3/5 16-thread all-retained soak ...")
    doc["soak"] = bench_soak()
    print(f"    ring {doc['soak']['ring']}/{doc['soak']['capacity']}, "
          f"evicted {doc['soak']['evicted']}, "
          f"rss +{doc['soak']['rss_growth_kb']} kB")
    print("4/5 64-caller zipfian replay ...")
    doc["zipf_replay"] = bench_zipf_replay()
    fl = doc["zipf_replay"]["client_flight"]
    print(f"    {fl['requests']} requests, ring {fl['ring']}/"
          f"{fl['capacity']}, retained {fl['retained_fraction']:.1%}")
    print("5/5 3-replica chaos attribution ...")
    doc["chaos"] = bench_chaos()
    print(f"    slow tail {doc['chaos']['slow_tail_count']}, named="
          f"{doc['chaos']['named_faulted_endpoint']}")
    rc = check(doc)
    Path(args.output).write_text(json.dumps(doc, indent=2) + "\n")
    print(f"wrote {args.output}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
