"""Flash-attention block-size sweep on the real chip (VERDICT-r3 #4).

The round-3 kernel measured 9.6 TF/s (4.9% MFU) at the benched shape
4×2048×8×128. Roofline first: per head the kernel does 4·S²·D FLOPs over
8·S·D bytes of HBM traffic → arithmetic intensity S/2 ≈ 1024 FLOP/byte at
S=2048 — two orders of magnitude past the v5e ridge point (~240), so the
shape is COMPUTE-bound and low MFU is kernel inefficiency, not bandwidth.
The two levers this tool measures:

- operand dtype: the round-4 kernel issues bf16×bf16→f32 dots (full-rate
  MXU) instead of pre-cast f32×f32 (~4x slower) — the expected dominant
  term;
- block_q × block_k: bigger blocks amortize grid/scratch overhead and the
  per-block VPU work (exp + running-max bookkeeping) against more MXU
  FLOPs per invocation.

Sweeps the block grid at the benched shape, reports TF/s + MFU per config,
and runs the bf16 exactness tier (vs dense fp32 reference) for the best
config. One JSON; designed to be embedded by tools/capture_chip.py.

    python tools/flash_sweep.py [--json-out PATH] [--small]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.chip_bench import _peak_for, _timed_single_dispatch  # noqa: E402


def sweep(jax, jnp, np, interpret, small):
    from client_tpu.ops.flash_attention import flash_attention

    if small:
        batch, seq, heads, dim, steps = 1, 256, 2, 64, 2
        blocks = [(128, 128)]
    else:
        batch, seq, heads, dim, steps = 4, 2048, 8, 128, 10
        blocks = [(bq, bk)
                  for bq in (128, 256, 512, 1024)
                  for bk in (128, 256, 512, 1024)]

    rng = np.random.default_rng(1)
    shape = (batch, seq, heads, dim)

    def mk():
        return jnp.asarray(rng.standard_normal(shape, dtype=np.float32),
                           dtype=jnp.bfloat16)

    q, k, v = mk(), mk(), mk()
    flops = 4 * batch * heads * seq * seq * dim  # QK^T + PV

    rows = []
    for bq, bk in blocks:
        row = {"block_q": bq, "block_k": bk}
        try:
            def chained(q, k, v, _bq=bq, _bk=bk):
                def body(_, acc):
                    # carry-dependent cast-preserving perturbation: stops
                    # XLA hoisting the loop-invariant call (cheap vs S²D)
                    qq = (q * (1.0 + 0.0 * acc)).astype(q.dtype)
                    o = flash_attention(qq, k, v, block_q=_bq, block_k=_bk,
                                        interpret=interpret)
                    return acc + jnp.sum(o.astype(jnp.float32))

                return jax.lax.fori_loop(0, steps, body, jnp.float32(0))

            dt = _timed_single_dispatch(jax.jit(chained), q, k, v, iters_inside=steps)
            row["ms_per_call"] = round(dt * 1000, 3)
            row["tflops"] = round(flops / dt / 1e12, 2)
        except Exception as e:
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        rows.append(row)

    ok_rows = [r for r in rows if "tflops" in r]
    best = max(ok_rows, key=lambda r: r["tflops"]) if ok_rows else None

    result = {"shape": list(shape), "rows": rows, "best": best}

    if best:
        # bf16 exactness tier at the winning config (vs dense fp32)
        qs, ks, vs = q[:1, :512], k[:1, :512], v[:1, :512]
        out = flash_attention(
            qs, ks, vs, block_q=min(best["block_q"], 512),
            block_k=min(best["block_k"], 512), interpret=interpret
        ).astype(jnp.float32)
        qf, kf, vf = (t.astype(jnp.float32) for t in (qs, ks, vs))
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * (dim ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
        diff = float(jnp.max(jnp.abs(out - ref)))
        result["exactness"] = {"max_abs_diff": diff, "tol": 5e-2,
                               "ok": diff < 5e-2}
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--interpret", action="store_true")
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()

    import jax

    if args.interpret or os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # see decode_attn_chip.py
    import jax.numpy as jnp
    import numpy as np

    interpret = args.interpret or jax.default_backend() not in ("tpu", "axon")
    device = jax.devices()[0]
    peak = _peak_for(device.device_kind)
    result = {
        "platform": jax.default_backend(),
        "device_kind": device.device_kind,
        "peak_bf16_tflops": peak,
        "mosaic_compiled": not interpret,
    }
    result.update(sweep(jax, jnp, np, interpret, args.small))
    if peak and result.get("best"):
        result["best_mfu"] = round(result["best"]["tflops"] / peak, 3)

    text = json.dumps(result, indent=1)
    print(text)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
