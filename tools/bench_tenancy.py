"""Generate BENCH_TENANCY.json: multi-tenant isolation under an
adversarial neighbor.

The claim to prove (the tenancy ISSUE): with per-tenant quotas and
weighted-fair admission armed (``client_tpu.tenancy``), an adversarial
tenant offering **10x its declared quota** costs the compliant tenants
less than 5% of their capacity and zero SLO breaches — and every one of
the adversary's rejected requests is a *typed* ``over_quota`` shed with
an honest ``retry_after_s`` hint, never an error and never a
breaker/retry signal.

Method (two arms, ONE compliant workload):

1. **isolated** — a seeded ``multi_tenant`` trace with only the
   compliant tenants (``t0``, ``t1``), replayed through an
   admission+tenancy-armed pool. This is the compliant tenants'
   baseline: ok counts, latencies, per-tenant SLO windows.
2. **adversarial** — the SAME spec plus one adversary (``adv0``)
   offering ``ADVERSARY_FACTOR``x the per-tenant rate against a quota of
   exactly that rate. The generator draws each tenant's arrivals (and
   payload keys) from its own child rng, so the compliant records in
   this arm are byte-identical to the isolated arm's — the adversary is
   the ONLY delta.

The invariants (``check``):

- ``compliant_capacity``: compliant ok-count in the adversarial arm >=
  ``MIN_COMPLIANT_CAPACITY_RATIO`` (95%) of the isolated arm's.
- ``compliant_slo``: zero compliant SLO-window breaches and zero
  compliant sheds/errors in the adversarial arm (the per-tenant burn
  windows come from the controller's tenancy snapshot).
- ``adversary_typed``: the adversary's rejects are 100% ``over_quota``
  sheds (no errors — a quota denial is policy, not failure) and its
  excess actually shed (>= half its offered traffic).
- ``noisy_neighbor_named``: the tenancy snapshot's noisy-neighbor
  verdict names ``adv0`` — what ``client_tpu.doctor`` flags.
- ``retry_after_honest``: shed rows carry positive ``retry_after_s``
  hints (the token bucket's refill eta), surfaced in the replay row.

``--check`` re-validates the committed artifact (CI:
``tests/test_tenancy.py::test_bench_tenancy_artifact_claims``);
``tools/capacity_gate.py --tenancy`` re-RUNS both arms on a shortened
twin of the trace and fails when the isolation no longer holds live.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_tenancy.py [-o BENCH_TENANCY.json]
    JAX_PLATFORMS=cpu python tools/bench_tenancy.py --check
"""

from __future__ import annotations

import argparse
import contextlib
import json
import platform
import sys
import time
from pathlib import Path
from typing import Any, Dict

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# per-compliant-tenant offered rate (req/s) and the adversary's multiple
# of ITS OWN quota; the compliant load is sized well under one replica's
# capacity so any compliant loss in the adversarial arm is attributable
# to the adversary, not to saturation
RATE = 30.0
TENANTS = 2
ADVERSARY_FACTOR = 10.0
DURATION_S = 6.0
TRACE_SEED = 2026
# compliant tenants: quota at 2x their offered rate (they never hit it),
# a 250ms/99% SLO window; adversary: quota exactly RATE, so its offered
# ADVERSARY_FACTOR x RATE is 10x quota and ~90% of it must shed typed
COMPLIANT_SLO_MS = 250.0
TENANCY_SPEC = (
    f"t0,rate={2 * RATE:g},burst={2 * RATE:g},weight=1,"
    f"slo_ms={COMPLIANT_SLO_MS:g},slo_objective=0.99;"
    f"t1,rate={2 * RATE:g},burst={2 * RATE:g},weight=1,"
    f"slo_ms={COMPLIANT_SLO_MS:g},slo_objective=0.99;"
    f"adv0,rate={RATE:g},burst={RATE:g}"
)
_BASE = (f"tenants={TENANTS},rate={RATE:g},duration_s={DURATION_S:g},"
         f"model=simple,hot_key_universe=16,hot_key_alpha=1.1")
ISOLATED_SPEC = f"multi_tenant:{_BASE},adversaries=0"
ADVERSARIAL_SPEC = (f"multi_tenant:{_BASE},adversaries=1,"
                    f"adversary_factor={ADVERSARY_FACTOR:g}")
COMPLIANT = tuple(f"t{i}" for i in range(TENANTS))
ADVERSARY = "adv0"
MIN_COMPLIANT_CAPACITY_RATIO = 0.95
MIN_ADVERSARY_SHED_FRACTION = 0.5
REPLAY_WORKERS = 32


@contextlib.contextmanager
def arm_runner():
    """A fresh in-process server + a PerfRunner with the tenancy-armed
    admission controller (both arms use the SAME runner config; the arm
    is the trace). Shared with ``tools/capacity_gate.py --tenancy`` so
    the gate re-runs exactly this definition."""
    import numpy as np

    from client_tpu.http import InferenceServerClient, InferInput
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    runner = None
    try:
        with InferenceServerClient(server.url) as client:
            inputs = []
            for name in ("INPUT0", "INPUT1"):
                inp = InferInput(name, [1, 16], "INT32")
                inp.set_data_from_numpy(np.zeros((1, 16), dtype=np.int32))
                inputs.append(inp)
            client.infer("simple", inputs)  # jit warm
        runner = PerfRunner(
            server.url, "http", "simple",
            endpoints=[server.url],
            admission=True,
            tenancy=TENANCY_SPEC,
        )
        feature = ("1-replica PoolClient, admission controller with "
                   "per-tenant weighted-fair queues + token-bucket "
                   "quotas (client_tpu.tenancy)")
        yield runner, feature
    finally:
        if runner is not None:
            runner.close()
        server.stop()


def _tenant_rows(row: Dict[str, Any]) -> Dict[str, Any]:
    return row.get("tenants") or {}


def _policy_rows(row: Dict[str, Any]) -> Dict[str, Any]:
    """The controller's own per-tenant story (quota tokens, SLO burn
    windows, noisy-neighbor verdicts) out of the replay row's
    ``client_admission`` snapshot."""
    return (row.get("client_admission") or {}).get("tenancy") or {}


def run_arm(runner, tr, name: str) -> Dict[str, Any]:
    row = runner.run_trace(tr, speed=1.0, replay_workers=REPLAY_WORKERS)
    tenants = _tenant_rows(row)
    policy = _policy_rows(row)
    out = {
        "records": len(tr.records),
        "issued": row["issued"],
        "ok": row["requests"],
        "errors": row["errors"],
        "shed": row["shed"],
        "tenants": tenants,
        "shed_retry_after_ms": row.get("shed_retry_after_ms"),
        "tenancy": policy,
    }
    compliant_ok = sum(tenants.get(t, {}).get("ok", 0) for t in COMPLIANT)
    print(f"arm {name}: ok={row['requests']} shed={row['shed']} "
          f"errors={row['errors']} compliant_ok={compliant_ok}"
          + (f" noisy={[v['tenant'] for v in policy.get('noisy_neighbors', [])]}"
             if policy else ""),
          flush=True)
    return out


def _compliant_ok(arm: Dict[str, Any]) -> int:
    return sum(arm["tenants"].get(t, {}).get("ok", 0) for t in COMPLIANT)


def check(doc: Dict[str, Any]) -> int:
    """Validate the committed artifact's claims; prints each verdict and
    returns the number of violations."""
    failures = 0

    def claim(name: str, ok: bool, detail: str) -> None:
        nonlocal failures
        print(f"  [{'ok' if ok else 'FAIL'}] {name}: {detail}")
        if not ok:
            failures += 1

    iso = doc["arms"]["isolated"]
    adv = doc["arms"]["adversarial"]
    iso_ok, adv_ok = _compliant_ok(iso), _compliant_ok(adv)
    ratio = adv_ok / iso_ok if iso_ok else 0.0
    claim("compliant_capacity",
          iso_ok > 0 and ratio >= MIN_COMPLIANT_CAPACITY_RATIO,
          f"compliant ok {adv_ok}/{iso_ok} = {ratio:.3f} >= "
          f"{MIN_COMPLIANT_CAPACITY_RATIO}")

    policy_tenants = (adv.get("tenancy") or {}).get("tenants") or {}
    breaches = {t: policy_tenants.get(t, {}).get("slo_breaches_total")
                for t in COMPLIANT}
    compliant_clean = all(
        adv["tenants"].get(t, {}).get("shed", 1) == 0
        and adv["tenants"].get(t, {}).get("errors", 1) == 0
        for t in COMPLIANT)
    claim("compliant_slo",
          compliant_clean and all(b == 0 for b in breaches.values()),
          f"zero compliant sheds/errors and SLO breaches {breaches} all 0")

    adv_row = adv["tenants"].get(ADVERSARY) or {}
    reasons = adv_row.get("shed_by_reason") or {}
    offered = adv_row.get("issued", 0)
    claim("adversary_typed",
          offered > 0
          and adv_row.get("errors", 1) == 0
          and set(reasons) == {"over_quota"}
          and adv_row.get("shed", 0)
          >= MIN_ADVERSARY_SHED_FRACTION * offered,
          f"adversary {adv_row.get('shed', 0)}/{offered} shed, reasons "
          f"{reasons}, errors {adv_row.get('errors')}")

    noisy = [v.get("tenant")
             for v in (adv.get("tenancy") or {}).get("noisy_neighbors", [])]
    claim("noisy_neighbor_named", ADVERSARY in noisy,
          f"noisy-neighbor verdicts {noisy} name {ADVERSARY!r} "
          f"(what client_tpu.doctor flags)")

    retry = adv.get("shed_retry_after_ms") or {}
    claim("retry_after_honest", (retry.get("p50") or 0.0) > 0.0,
          f"shed retry_after hints present, p50={retry.get('p50')}ms")
    return failures


def probe_isolation(duration_s: float, attempts: int) -> Dict[str, Any]:
    """Re-run both arms on a shortened twin of the workload and re-judge
    the isolation invariants live — the ``capacity_gate --tenancy``
    body. Returns ``{"arms": ..., "problems": [...]}``."""
    from client_tpu import trace as trace_mod

    problems: list = []
    verdict: Dict[str, Any] = {"attempts": []}
    for attempt in range(max(1, attempts)):
        iso_tr = trace_mod.generate(ISOLATED_SPEC, seed=TRACE_SEED,
                                    duration_s=duration_s)
        adv_tr = trace_mod.generate(ADVERSARIAL_SPEC, seed=TRACE_SEED,
                                    duration_s=duration_s)
        arms = {}
        with arm_runner() as (runner, _):
            arms["isolated"] = run_arm(runner, iso_tr, "isolated")
        with arm_runner() as (runner, _):
            arms["adversarial"] = run_arm(runner, adv_tr, "adversarial")
        doc = {"arms": arms}
        problems = []
        iso_ok, adv_ok = (_compliant_ok(arms["isolated"]),
                          _compliant_ok(arms["adversarial"]))
        if not iso_ok or adv_ok / iso_ok < MIN_COMPLIANT_CAPACITY_RATIO:
            problems.append(
                f"compliant capacity {adv_ok}/{iso_ok} under "
                f"{MIN_COMPLIANT_CAPACITY_RATIO}")
        adv_row = arms["adversarial"]["tenants"].get(ADVERSARY) or {}
        if adv_row.get("errors", 1) != 0 or set(
                adv_row.get("shed_by_reason") or {}) - {"over_quota"}:
            problems.append(
                f"adversary sheds not cleanly typed: "
                f"errors={adv_row.get('errors')} "
                f"reasons={adv_row.get('shed_by_reason')}")
        noisy = [v.get("tenant") for v in (arms["adversarial"].get("tenancy")
                                           or {}).get("noisy_neighbors", [])]
        if ADVERSARY not in noisy:
            problems.append(f"noisy-neighbor verdict missing: {noisy}")
        verdict["attempts"].append({
            "attempt": attempt + 1,
            "compliant_ok": {"isolated": iso_ok, "adversarial": adv_ok},
            "problems": list(problems),
        })
        verdict["arms"] = doc["arms"]
        if not problems:
            break
    verdict["problems"] = problems
    return verdict


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_TENANCY.json")
    parser.add_argument("--check", action="store_true",
                        help="validate the committed artifact's claims "
                             "instead of re-measuring")
    args = parser.parse_args(argv)

    if args.check:
        doc = json.loads(Path(args.output).read_text())
        failures = check(doc)
        print("OK" if failures == 0 else f"{failures} claim(s) failed")
        return 1 if failures else 0

    from client_tpu import trace as trace_mod

    iso_tr = trace_mod.generate(ISOLATED_SPEC, seed=TRACE_SEED)
    adv_tr = trace_mod.generate(ADVERSARIAL_SPEC, seed=TRACE_SEED)
    out: Dict[str, Any] = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "multi-tenant isolation: the same compliant workload replayed "
            "with and without an adversarial tenant offering "
            f"{ADVERSARY_FACTOR:g}x its quota; per-tenant weighted-fair "
            "queues + token-bucket quotas (client_tpu.tenancy) must keep "
            "the compliant tenants' capacity within "
            f"{(1 - MIN_COMPLIANT_CAPACITY_RATIO) * 100:g}% and their SLO "
            "windows clean while the adversary's excess sheds typed "
            "over_quota with honest retry_after hints"
        ),
        "trace": {
            "isolated_spec": ISOLATED_SPEC,
            "adversarial_spec": ADVERSARIAL_SPEC,
            "seed": TRACE_SEED,
            "duration_s": DURATION_S,
            "isolated_records": len(iso_tr.records),
            "adversarial_records": len(adv_tr.records),
        },
        "tenancy_spec": TENANCY_SPEC,
        "compliant_tenants": list(COMPLIANT),
        "adversary": ADVERSARY,
        "adversary_factor": ADVERSARY_FACTOR,
        "limits": {
            "min_compliant_capacity_ratio": MIN_COMPLIANT_CAPACITY_RATIO,
            "min_adversary_shed_fraction": MIN_ADVERSARY_SHED_FRACTION,
            "compliant_slo_ms": COMPLIANT_SLO_MS,
        },
        "search": {"replay_workers": REPLAY_WORKERS},
        "arms": {},
    }
    with arm_runner() as (runner, feature):
        print(f"arm isolated: {feature}", flush=True)
        arm = run_arm(runner, iso_tr, "isolated")
        arm["feature"] = feature
        out["arms"]["isolated"] = arm
    with arm_runner() as (runner, feature):
        print(f"arm adversarial: {feature}", flush=True)
        arm = run_arm(runner, adv_tr, "adversarial")
        arm["feature"] = feature
        out["arms"]["adversarial"] = arm
    iso_ok, adv_ok = (_compliant_ok(out["arms"]["isolated"]),
                      _compliant_ok(out["arms"]["adversarial"]))
    out["compliant_capacity_ratio"] = (round(adv_ok / iso_ok, 4)
                                       if iso_ok else None)

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({
        "compliant_ok_isolated": iso_ok,
        "compliant_ok_adversarial": adv_ok,
        "compliant_capacity_ratio": out["compliant_capacity_ratio"],
        "adversary_shed": (out["arms"]["adversarial"]["tenants"]
                           .get(ADVERSARY, {}).get("shed")),
    }, indent=2))
    failures = check(out)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
