"""Stage-split TPU accelerator probe (VERDICT r2 #1).

The axon PJRT tunnel has been down for two full rounds and the old probe
("device init + first compute hung >120s") taught nothing about WHERE it
hung. This tool splits initialization into four stages, each with its OWN
timeout, and streams the child's progress markers live so a hang (or a
crash) is attributed to the exact stage that never completed:

  1. import    — `import jax` + PJRT plugin discovery (axon sitecustomize)
  2. devices   — `jax.devices()` (backend init: tunnel socket + handshake)
  3. device_put— first host->device transfer
  4. jit       — first XLA compile + execute on the chip

Run it directly for a human-readable trace, or import `probe()` for the
structured result bench.py embeds in BENCH_r*.json.

Env knobs:
  BENCH_TPU_INIT_BUDGET_S  — PER-STAGE budget (default 120)
  BENCH_TPU_TOTAL_BUDGET_S — per-attempt overall cap (default 2x stage budget)
  BENCH_TPU_ATTEMPTS       — attempts with 15 s backoff (default 2)

Reference parity: the reference client benches assume a live tritonserver
on GPU; this is the tpu-native analog of "is the accelerator reachable".
"""

from __future__ import annotations

import json
import os
import selectors
import subprocess
import sys
import tempfile
import time

STAGES = ("import", "devices", "device_put", "jit")

_CHILD = r"""
import json, time, sys
stages = []
def mark(name, t0, **extra):
    stages.append({"stage": name, "seconds": round(time.time() - t0, 2), **extra})
    print("STAGE " + json.dumps(stages[-1]), flush=True)

t0 = time.time()
import jax
mark("import", t0, version=jax.__version__)

t0 = time.time()
devs = jax.devices()
mark("devices", t0, platform=devs[0].platform, count=len(devs))

t0 = time.time()
import jax.numpy as jnp
x = jax.device_put(jnp.ones((256, 256), jnp.float32))
x.block_until_ready()
mark("device_put", t0)

t0 = time.time()
y = jax.jit(lambda a: a @ a)(x)
y.block_until_ready()
mark("jit", t0)

print("DONE " + json.dumps({"platform": devs[0].platform, "stages": stages}), flush=True)
"""


def _run_attempt(stage_timeout_s: float, total_timeout_s: float) -> dict:
    """One staged probe in a throwaway subprocess (the tunnel can wedge any
    in-process jax compute — axon sitecustomize pins the backend).

    stdout is consumed line-by-line as STAGE markers arrive, so each stage
    gets its own `stage_timeout_s` deadline; stderr goes to a tempfile (no
    pipe to fill) and its tail is kept on EVERY failure path — the PJRT
    plugin's connect/retry errors are exactly the diagnostics we want.
    """
    stages: list[dict] = []
    result: dict = {"ok": False, "stages": stages}

    def _expected() -> str:
        # "finalize": all four stages completed but the DONE line never
        # arrived (child killed/OOM'd between 'jit' and DONE) — keep the
        # attribution meaningful instead of reporting stage 'None'.
        return STAGES[len(stages)] if len(stages) < len(STAGES) else "finalize"

    total_deadline = time.monotonic() + total_timeout_s
    with tempfile.TemporaryFile(mode="w+", errors="replace") as errf:
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", _CHILD],
            stdout=subprocess.PIPE, stderr=errf,
        )
        # Raw non-blocking fd + our own line buffer: mixing select() with a
        # buffered readline() can strand lines in the Python-level buffer
        # (select sees an empty fd, the stage timer expires, attribution is
        # wrong or a buffered DONE is missed entirely).
        fd = proc.stdout.fileno()
        os.set_blocking(fd, False)
        sel = selectors.DefaultSelector()
        sel.register(fd, selectors.EVENT_READ)
        pending = b""
        stage_started = time.monotonic()
        hung = False
        eof = False
        try:
            while not eof:
                budget = min(stage_started + stage_timeout_s,
                             total_deadline) - time.monotonic()
                if budget <= 0:
                    hung = True
                    break
                if not sel.select(timeout=max(budget, 0.05)):
                    continue
                try:
                    chunk = os.read(fd, 65536)
                except BlockingIOError:
                    continue
                if not chunk:  # EOF: child exited (crash or done)
                    eof = True
                pending += chunk
                while b"\n" in pending:
                    raw, pending = pending.split(b"\n", 1)
                    line = raw.decode("utf-8", "replace")
                    if line.startswith("STAGE "):
                        stages.append(json.loads(line[len("STAGE "):]))
                        stage_started = time.monotonic()
                    elif line.startswith("DONE "):
                        done = json.loads(line[len("DONE "):])
                        result.update(ok=True, platform=done["platform"])
        finally:
            sel.close()
            if proc.poll() is None:
                proc.kill()
            proc.wait()
            errf.seek(0)
            stderr_tail = errf.read()[-800:].strip()

        if result["ok"]:
            return result
        failed_at = _expected()
        reached = stages[-1]["stage"] if stages else None
        if hung:
            result["hung_at"] = failed_at
            result["error"] = (
                f"stage '{failed_at}' did not complete within its "
                f"{stage_timeout_s:.0f}s budget (last completed: "
                f"{reached or 'none — jax import itself hung'})"
            )
        else:
            result["failed_at"] = failed_at
            result["error"] = (
                f"child exited rc={proc.returncode} during stage '{failed_at}' "
                f"(last completed: {reached or 'none'})"
            )
        if stderr_tail:
            result["stderr_tail"] = stderr_tail
        return result


def probe(attempts: int | None = None, stage_timeout_s: float | None = None) -> dict:
    """Staged accelerator probe. Returns a dict with ok/platform/stages and,
    on failure, hung_at/failed_at + error naming the exact stage, plus the
    child's stderr tail (PJRT/tunnel diagnostics)."""
    attempts = attempts or int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    stage_timeout_s = stage_timeout_s or float(
        os.environ.get("BENCH_TPU_INIT_BUDGET_S", "120"))
    # Overall cap per attempt so a slowly-progressing tunnel can't stretch
    # one attempt to 4x the stage budget (the old probe's total semantics).
    total_timeout_s = float(
        os.environ.get("BENCH_TPU_TOTAL_BUDGET_S", str(stage_timeout_s * 2)))
    last: dict = {}
    for attempt in range(attempts):
        last = _run_attempt(stage_timeout_s, total_timeout_s)
        last["attempt"] = attempt + 1
        if last["ok"]:
            return last
        print(json.dumps({"note": "tpu probe attempt failed", **{k: v for k, v in last.items() if k != "stages"}}), file=sys.stderr)
        if attempt + 1 < attempts:
            time.sleep(15)
    return last


def main() -> int:
    res = probe()
    print(json.dumps(res))
    return 0 if res.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
