"""Generate BENCH_OBSERVE.json: the telemetry cost + join-proof artifact.

Three questions, answered against live in-process servers:

1. **Hot-path overhead, microbenchmarked** — the per-call cost of the
   telemetry span lifecycle in isolation (begin + 4 phase marks + finish,
   metrics on, tracer on the slow-only path so the ring never writes),
   versus the disabled path (the single attribute check every frontend
   performs when no telemetry is configured). This is the honest
   <2 µs/call acceptance number, decoupled from network noise.
2. **End-to-end A/B** — the same HTTP workload through a bare client,
   through a telemetry-armed client (sample=slow: metrics on, tracer off
   the hot path), and through a bare client again (the rerun bounds the
   container's run-to-run noise floor, so the delta can be read against
   it instead of being mistaken for signal).
3. **Trace join proof** — one traced request per frontend pair (HTTP
   sync, GRPC sync) showing the client span's phases and the server-side
   access record joined on the same trace id.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_observe.py [-o BENCH_OBSERVE.json]
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def bench_hot_path(n: int = 20_000, repeats: int = 12) -> dict:
    """µs/call of the enabled telemetry span lifecycle vs the disabled
    attribute check. min-of-repeats: the container's scheduler noise is
    bigger than the thing being measured, so the minimum is the honest
    estimate of the code's cost."""
    import timeit

    from client_tpu.observe import Telemetry

    # enabled, sampling off the slow path: the trace ring is written only
    # for requests slower than the threshold, finished spans queue on a
    # lock-free deque and fold into the histograms on the SCRAPER's thread
    tel = Telemetry(sample="slow", slow_threshold_s=3600.0)
    perf_ns = time.perf_counter_ns
    g = {"tel": tel, "perf_ns": perf_ns}

    def best(stmt: str) -> float:
        out = []
        for _ in range(repeats):
            out.append(timeit.Timer(stmt, globals=g).timeit(n) / n * 1e6)
            tel._pending.clear()  # keep the backlog fold out of the lane
        return min(out)

    # the per-request instrumentation: begin + 4 phase marks + finish.
    # Timestamps are pre-captured (the sync frontends already capture
    # RequestTimers for InferStat with telemetry OFF, so they are not a
    # marginal cost there); the fresh-timestamp variant prices the aio
    # frontends, which capture ns only when telemetry is on.
    enabled_us = best(
        "s = tel.begin('http', 'simple')\n"
        "s.phase('serialize', 1, 2)\n"
        "s.phase('ttfb', 1, 2)\n"
        "s.phase('recv', 1, 2)\n"
        "s.phase('deserialize', 1, 2)\n"
        "tel.finish(s)")
    enabled_fresh_ts_us = best(
        "s = tel.begin('http', 'simple')\n"
        "t = perf_ns()\n"
        "s.phase('serialize', t, perf_ns())\n"
        "s.phase('ttfb', t, perf_ns())\n"
        "s.phase('recv', t, perf_ns())\n"
        "s.phase('deserialize', t, perf_ns())\n"
        "tel.finish(s)")
    with_traceparent_us = best(
        "s = tel.begin('http', 'simple')\n"
        "h = s.traceparent()\n"
        "s.phase('serialize', 1, 2)\n"
        "s.phase('ttfb', 1, 2)\n"
        "s.phase('recv', 1, 2)\n"
        "s.phase('deserialize', 1, 2)\n"
        "tel.finish(s)")

    # scrape-side fold cost (runs on the scraper's thread, not the request
    # path): fill a backlog, time one flush
    tel._pending.clear()
    fold_n = min(n, 20_000)  # stay under the inline-fold backlog bound
    for _ in range(fold_n):
        s = tel.begin("http", "simple")
        s.phase("serialize", 1, 2)
        s.phase("ttfb", 1, 2)
        s.phase("recv", 1, 2)
        s.phase("deserialize", 1, 2)
        tel.finish(s)
    t0 = time.perf_counter()
    tel.flush()
    fold_us = (time.perf_counter() - t0) / fold_n * 1e6

    # the disabled path every frontend runs with no telemetry configured:
    # one attribute load + None check, then nothing
    class _Client:
        _telemetry = None

        def _obs_begin(self, frontend, model):
            t = self._telemetry
            if t is None:
                return None
            return t.begin(frontend, model)

    g["client"] = _Client()
    disabled_us = best("client._obs_begin('http', 'simple')")

    return {
        "calls_per_repeat": n,
        "repeats": repeats,
        "enabled_us_per_call": round(enabled_us, 4),
        "enabled_fresh_timestamps_us_per_call": round(
            enabled_fresh_ts_us, 4),
        "enabled_with_traceparent_us_per_call": round(
            with_traceparent_us, 4),
        "scrape_side_fold_us_per_record": round(fold_us, 4),
        "disabled_us_per_call": round(disabled_us, 4),
        "note": (
            "enabled = begin + 4 phase marks + finish, slow-only sampling "
            "(ring off the hot path), histogram fold deferred to the "
            "scraper's thread; disabled = the frontends' telemetry-is-None "
            "check"
        ),
    }


def bench_e2e(requests: int) -> dict:
    """Bare vs telemetry-armed HTTP client against a live threaded server,
    with a bare rerun bounding the A/B noise floor."""
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    try:
        def measure(observe: bool):
            # sample=slow: the A/B benchmarks metrics-on/tracer-off-hot-path
            # (the production posture), not ring writes
            runner = PerfRunner(server.url, "http", "simple",
                                observe=observe, observe_sample="slow")
            try:
                runner.run(1, 50)  # warmup
                return runner.run(1, requests)
            finally:
                runner.close()

        out = {
            "bare_client": measure(False),
            "observed_client": measure(True),
            "bare_client_rerun": measure(False),
        }
        bare_avgs = [out["bare_client"]["latency_ms"]["avg"],
                     out["bare_client_rerun"]["latency_ms"]["avg"]]
        bare_avg = sum(bare_avgs) / 2
        observed_avg = out["observed_client"]["latency_ms"]["avg"]
        out["enabled_overhead_us_per_call"] = round(
            (observed_avg - bare_avg) * 1000.0, 2)
        out["ab_noise_floor_us"] = round(
            abs(bare_avgs[0] - bare_avgs[1]) * 1000.0, 2)
        return out
    finally:
        server.stop()


def trace_join() -> dict:
    """One traced request per frontend pair: client phases + the server's
    access record joined on the same trace id."""
    import numpy as np

    import client_tpu.grpc as grpcclient
    import client_tpu.http as httpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.server import (
        GrpcInferenceServer,
        HttpInferenceServer,
        ServerCore,
    )

    out = {}
    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    for proto, mod, server_cls in (
        ("http", httpclient, HttpInferenceServer),
        ("grpc", grpcclient, GrpcInferenceServer),
    ):
        core = ServerCore(default_model_zoo())
        server = server_cls(core).start()
        tel = Telemetry(sample="always")
        client = mod.InferenceServerClient(server.url).configure_telemetry(tel)
        try:
            in0 = mod.InferInput("INPUT0", [1, 16], "INT32")
            in0.set_data_from_numpy(a)
            in1 = mod.InferInput("INPUT1", [1, 16], "INT32")
            in1.set_data_from_numpy(b)
            client.infer("simple", [in0, in1],
                         request_id=f"bench-observe-{proto}")
            trace = tel.recent_traces()[-1]
            record = core.access_records()[-1]
            out[proto] = {
                "client_span": trace,
                "server_access_record": record,
                "joined": (record["trace_id"] == trace["trace_id"]
                           and record["client_span_id"] == trace["span_id"]),
            }
        finally:
            client.close()
            server.stop()
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_OBSERVE.json")
    parser.add_argument("--requests", type=int, default=600)
    parser.add_argument(
        "--micro-calls", type=int, default=20_000,
        help="calls per microbench repeat; keep under the telemetry "
             "inline-fold backlog (32768) so the deferred fold stays on "
             "the scraper's side of the measurement",
    )
    args = parser.parse_args()

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "telemetry hot-path microbench (the <2 µs/call acceptance "
            "number), end-to-end A/B vs a bare client with a rerun noise "
            "floor, and one traced request per frontend pair joined to "
            "its server-side access record on the same trace id"
        ),
        "hot_path": bench_hot_path(args.micro_calls),
        "e2e": bench_e2e(args.requests),
        "trace_join": trace_join(),
    }

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
