"""Generate BENCH_OBSERVE.json: the telemetry cost + join-proof artifact.

Three questions, answered against live in-process servers:

1. **Hot-path overhead, microbenchmarked** — the per-call cost of the
   telemetry span lifecycle in isolation (begin + 4 phase marks + finish,
   metrics on, tracer on the slow-only path so the ring never writes),
   versus the disabled path (the single attribute check every frontend
   performs when no telemetry is configured). This is the honest
   <2 µs/call acceptance number, decoupled from network noise.
2. **End-to-end A/B** — the same HTTP workload through a bare client,
   through a telemetry-armed client (sample=slow: metrics on, tracer off
   the hot path), and through a bare client again (the rerun bounds the
   container's run-to-run noise floor, so the delta can be read against
   it instead of being mistaken for signal).
3. **Trace join proof** — one traced request per frontend pair (HTTP
   sync, GRPC sync) showing the client span's phases and the server-side
   access record joined on the same trace id.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_observe.py [-o BENCH_OBSERVE.json]
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _simple_pair(mod):
    """INPUT0 arange + INPUT1 ones for the ``simple`` sum/diff model —
    the probe request every live section of this tool drives."""
    import numpy as np

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    in0 = mod.InferInput("INPUT0", [1, 16], "INT32")
    in0.set_data_from_numpy(a)
    in1 = mod.InferInput("INPUT1", [1, 16], "INT32")
    in1.set_data_from_numpy(b)
    return [in0, in1]


def bench_hot_path(n: int = 20_000, repeats: int = 12) -> dict:
    """µs/call of the enabled telemetry span lifecycle vs the disabled
    attribute check. min-of-repeats: the container's scheduler noise is
    bigger than the thing being measured, so the minimum is the honest
    estimate of the code's cost."""
    import timeit

    from client_tpu.observe import Telemetry

    # enabled, sampling off the slow path: the trace ring is written only
    # for requests slower than the threshold, finished spans queue on a
    # lock-free deque and fold into the histograms on the SCRAPER's thread
    tel = Telemetry(sample="slow", slow_threshold_s=3600.0)
    perf_ns = time.perf_counter_ns
    g = {"tel": tel, "perf_ns": perf_ns}

    def best(stmt: str) -> float:
        out = []
        for _ in range(repeats):
            out.append(timeit.Timer(stmt, globals=g).timeit(n) / n * 1e6)
            tel._pending.clear()  # keep the backlog fold out of the lane
        return min(out)

    # the per-request instrumentation: begin + 4 phase marks + finish.
    # Timestamps are pre-captured (the sync frontends already capture
    # RequestTimers for InferStat with telemetry OFF, so they are not a
    # marginal cost there); the fresh-timestamp variant prices the aio
    # frontends, which capture ns only when telemetry is on.
    enabled_us = best(
        "s = tel.begin('http', 'simple')\n"
        "s.phase('serialize', 1, 2)\n"
        "s.phase('ttfb', 1, 2)\n"
        "s.phase('recv', 1, 2)\n"
        "s.phase('deserialize', 1, 2)\n"
        "tel.finish(s)")
    enabled_fresh_ts_us = best(
        "s = tel.begin('http', 'simple')\n"
        "t = perf_ns()\n"
        "s.phase('serialize', t, perf_ns())\n"
        "s.phase('ttfb', t, perf_ns())\n"
        "s.phase('recv', t, perf_ns())\n"
        "s.phase('deserialize', t, perf_ns())\n"
        "tel.finish(s)")
    with_traceparent_us = best(
        "s = tel.begin('http', 'simple')\n"
        "h = s.traceparent()\n"
        "s.phase('serialize', 1, 2)\n"
        "s.phase('ttfb', 1, 2)\n"
        "s.phase('recv', 1, 2)\n"
        "s.phase('deserialize', 1, 2)\n"
        "tel.finish(s)")

    # scrape-side fold cost (runs on the scraper's thread, not the request
    # path): fill a backlog, time one flush
    tel._pending.clear()
    fold_n = min(n, 20_000)  # stay under the inline-fold backlog bound
    for _ in range(fold_n):
        s = tel.begin("http", "simple")
        s.phase("serialize", 1, 2)
        s.phase("ttfb", 1, 2)
        s.phase("recv", 1, 2)
        s.phase("deserialize", 1, 2)
        tel.finish(s)
    t0 = time.perf_counter()
    tel.flush()
    fold_us = (time.perf_counter() - t0) / fold_n * 1e6

    # the disabled path every frontend runs with no telemetry configured:
    # one attribute load + None check, then nothing
    class _Client:
        _telemetry = None

        def _obs_begin(self, frontend, model):
            t = self._telemetry
            if t is None:
                return None
            return t.begin(frontend, model)

    g["client"] = _Client()
    disabled_us = best("client._obs_begin('http', 'simple')")

    return {
        "calls_per_repeat": n,
        "repeats": repeats,
        "enabled_us_per_call": round(enabled_us, 4),
        "enabled_fresh_timestamps_us_per_call": round(
            enabled_fresh_ts_us, 4),
        "enabled_with_traceparent_us_per_call": round(
            with_traceparent_us, 4),
        "scrape_side_fold_us_per_record": round(fold_us, 4),
        "disabled_us_per_call": round(disabled_us, 4),
        "note": (
            "enabled = begin + 4 phase marks + finish, slow-only sampling "
            "(ring off the hot path), histogram fold deferred to the "
            "scraper's thread; disabled = the frontends' telemetry-is-None "
            "check"
        ),
    }


def bench_e2e(requests: int) -> dict:
    """Bare vs telemetry-armed HTTP client against a live threaded server,
    with a bare rerun bounding the A/B noise floor."""
    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import HttpInferenceServer, ServerCore

    server = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    try:
        def measure(observe: bool):
            # sample=slow: the A/B benchmarks metrics-on/tracer-off-hot-path
            # (the production posture), not ring writes
            runner = PerfRunner(server.url, "http", "simple",
                                observe=observe, observe_sample="slow")
            try:
                runner.run(1, 50)  # warmup
                return runner.run(1, requests)
            finally:
                runner.close()

        out = {
            "bare_client": measure(False),
            "observed_client": measure(True),
            "bare_client_rerun": measure(False),
        }
        bare_avgs = [out["bare_client"]["latency_ms"]["avg"],
                     out["bare_client_rerun"]["latency_ms"]["avg"]]
        bare_avg = sum(bare_avgs) / 2
        observed_avg = out["observed_client"]["latency_ms"]["avg"]
        out["enabled_overhead_us_per_call"] = round(
            (observed_avg - bare_avg) * 1000.0, 2)
        out["ab_noise_floor_us"] = round(
            abs(bare_avgs[0] - bare_avgs[1]) * 1000.0, 2)
        return out
    finally:
        server.stop()


def trace_join() -> dict:
    """One traced request per frontend pair: client phases + the server's
    access record joined on the same trace id."""
    import client_tpu.grpc as grpcclient
    import client_tpu.http as httpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.server import (
        GrpcInferenceServer,
        HttpInferenceServer,
        ServerCore,
    )

    out = {}
    for proto, mod, server_cls in (
        ("http", httpclient, HttpInferenceServer),
        ("grpc", grpcclient, GrpcInferenceServer),
    ):
        core = ServerCore(default_model_zoo())
        server = server_cls(core).start()
        tel = Telemetry(sample="always")
        client = mod.InferenceServerClient(server.url).configure_telemetry(tel)
        try:
            client.infer("simple", _simple_pair(mod),
                         request_id=f"bench-observe-{proto}")
            trace = tel.recent_traces()[-1]
            record = core.access_records()[-1]
            out[proto] = {
                "client_span": trace,
                "server_access_record": record,
                "joined": (record["trace_id"] == trace["trace_id"]
                           and record["client_span_id"] == trace["span_id"]),
            }
        finally:
            client.close()
            server.stop()
    return out


# -- streaming (BENCH_STREAM_OBSERVE.json) ------------------------------------
def bench_stream_hot_path(n: int = 20_000, repeats: int = 12) -> dict:
    """µs per chunk mark — the ≤2 µs/mark acceptance number — plus the
    disabled-path check (≤0.1 µs) and the stream span lifecycle cost."""
    import timeit

    from client_tpu.observe import Telemetry

    tel = Telemetry(sample="slow", slow_threshold_s=3600.0)
    span = tel.begin_stream("http", "tiny_lm_generate")
    mark = span.mark
    g = {"tel": tel, "span": span, "mark": mark, "none_mark": None}

    def best(stmt: str, reset=None) -> float:
        out = []
        for _ in range(repeats):
            out.append(timeit.Timer(stmt, globals=g).timeit(n) / n * 1e6)
            if reset is not None:
                reset()
        return min(out)

    def trim():
        # keep the mark list from growing across repeats (list append
        # amortization must not drift the measurement)
        del span.attempts[0].marks[:]

    mark_us = best("span.mark()", reset=trim)
    bound_mark_us = best("mark()", reset=trim)
    trim()
    # the disabled path every streaming loop runs with no telemetry: the
    # per-chunk `if mark is not None` check against a None local
    disabled_us = best("if none_mark is not None:\n    none_mark()")

    # full lifecycle: begin_stream + 8 marks + finish (per STREAM, not per
    # chunk), folded on the scraper's side
    def lifecycle_best() -> float:
        out = []
        stmt = (
            "s = tel.begin_stream('http', 'm')\n"
            + "s.mark()\n" * 8
            + "tel.finish_stream(s)")
        for _ in range(repeats):
            out.append(
                timeit.Timer(stmt, globals=g).timeit(n // 8) / (n // 8) * 1e6)
            tel._pending_streams.clear()
        return min(out)

    lifecycle_us = lifecycle_best()

    # scrape-side fold cost per finished stream (windowed sketch feeds)
    tel._pending_streams.clear()
    fold_n = 5_000
    for _ in range(fold_n):
        s = tel.begin_stream("http", "m")
        for _ in range(8):
            s.mark()
        tel.finish_stream(s)
    t0 = time.perf_counter()
    tel._fold_stream_pending()
    fold_us = (time.perf_counter() - t0) / fold_n * 1e6

    return {
        "calls_per_repeat": n,
        "repeats": repeats,
        "mark_us_per_chunk": round(mark_us, 4),
        "bound_mark_us_per_chunk": round(bound_mark_us, 4),
        "disabled_us_per_chunk": round(disabled_us, 4),
        "lifecycle_us_per_stream_8_chunks": round(lifecycle_us, 4),
        "scrape_side_fold_us_per_stream": round(fold_us, 4),
        "note": (
            "mark = one perf_counter_ns + one list append on the current "
            "attempt (the per-chunk hot path; acceptance ≤ 2 µs); "
            "disabled = the per-chunk `mark is not None` check the "
            "streaming loops run with no telemetry (acceptance ≤ 0.1 µs); "
            "TTFT/ITL/windowed-sketch math all happens at fold/scrape time"
        ),
    }


def stream_trace_join() -> dict:
    """One traced stream per protocol pair (HTTP SSE generate_stream +
    GRPC decoupled bidi), joined to the server's access record on the
    same trace id, with per-attempt TTFT on the span."""
    import queue

    import numpy as np

    import client_tpu.grpc as grpcclient
    import client_tpu.http as httpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.server import (
        GrpcInferenceServer,
        HttpInferenceServer,
        ServerCore,
    )

    out = {}

    # HTTP SSE
    core = ServerCore(default_model_zoo())
    tel = Telemetry(sample="always")
    with HttpInferenceServer(core) as server:
        with httpclient.InferenceServerClient(server.url) as client:
            client.configure_telemetry(tel)
            events = list(client.generate_stream(
                "tiny_lm_generate", {"TOKENS": [[1, 2, 3, 4]],
                                     "MAX_TOKENS": 8}))
            span = client.last_stream_span()
            record = core.access_records()[-1]
            out["http_sse"] = {
                "events": len(events),
                "client_stream_span": span.as_dict(),
                "server_access_record": record,
                "joined": (record["trace_id"] == span.trace_id
                           and record["client_span_id"] == span.span_id),
            }

    # GRPC decoupled
    core = ServerCore(default_model_zoo())
    tel = Telemetry(sample="always")
    with GrpcInferenceServer(core) as server:
        with grpcclient.InferenceServerClient(server.url) as client:
            client.configure_telemetry(tel)
            q: "queue.Queue" = queue.Queue()
            client.start_stream(lambda r, e: q.put((r, e)))
            tokens = grpcclient.InferInput("TOKENS", [1, 4], "INT32")
            tokens.set_data_from_numpy(
                np.array([[1, 2, 3, 4]], dtype=np.int32))
            max_tokens = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
            max_tokens.set_data_from_numpy(np.array([8], dtype=np.int32))
            client.async_stream_infer(
                "tiny_lm_generate", [tokens, max_tokens],
                enable_empty_final_response=True, request_id="stream-join")
            received = 0
            while True:
                result, error = q.get(timeout=60)
                assert error is None, error
                if result.is_final_response() and result.is_null_response():
                    break
                received += 1
            span = client.stream_span()
            client.stop_stream()
            records = [r for r in core.access_records()
                       if r["trace_id"] == span.trace_id]
            out["grpc_decoupled"] = {
                "tokens": received,
                "client_stream_span": span.as_dict(),
                "server_access_record": records[-1] if records else None,
                "joined": bool(records) and (
                    records[-1]["client_span_id"] == span.span_id),
            }
    return out


def stream_reconnect_demo() -> dict:
    """Flap chaos over an auto-reconnecting GRPC stream: the span grows a
    reconnect sub-attempt and TTFT is recorded PER attempt, so the
    reconnect backoff never inflates the stream's first-token number."""
    import queue
    import random

    import numpy as np

    import client_tpu.grpc as grpcclient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.resilience import ResiliencePolicy, RetryPolicy
    from client_tpu.server import GrpcInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy

    redial = [
        ("grpc.initial_reconnect_backoff_ms", 50),
        ("grpc.min_reconnect_backoff_ms", 50),
        ("grpc.max_reconnect_backoff_ms", 100),
    ]
    core = ServerCore(default_model_zoo())
    tel = Telemetry(sample="always")
    with GrpcInferenceServer(core) as server:
        with ChaosProxy("127.0.0.1", server.port) as proxy:
            policy = ResiliencePolicy(retry=RetryPolicy(
                max_attempts=4, initial_backoff_s=0.02, max_backoff_s=0.2,
                rng=random.Random(0x57BE)))
            tel.attach(policy)
            with grpcclient.InferenceServerClient(
                    proxy.url, channel_args=redial) as client:
                client.configure_resilience(policy)
                client.configure_telemetry(tel)
                q: "queue.Queue" = queue.Queue()
                client.start_stream(
                    lambda r, e: q.put((r, e)), auto_reconnect=True)
                a = np.arange(16, dtype=np.int32).reshape(1, 16)
                b = np.ones((1, 16), dtype=np.int32)
                in0 = grpcclient.InferInput("INPUT0", [1, 16], "INT32")
                in0.set_data_from_numpy(a)
                in1 = grpcclient.InferInput("INPUT1", [1, 16], "INT32")
                in1.set_data_from_numpy(b)

                client.async_stream_infer("simple", [in0, in1],
                                          request_id="pre-fault")
                result, error = q.get(timeout=30)
                assert error is None, error
                # kill the established bidi connection: the reconnecting
                # stream re-opens it and re-sends nothing (the request
                # completed), surfacing a StreamReconnected event
                proxy.reset_active()
                while True:
                    result, error = q.get(timeout=30)
                    assert error is None, error
                    if type(result).__name__ == "StreamReconnected":
                        break
                client.async_stream_infer("simple", [in0, in1],
                                          request_id="post-fault")
                result, error = q.get(timeout=30)
                assert error is None, error
                span = client.stream_span()
                client.stop_stream()
    tel.flush()
    return {
        "client_stream_span": span.as_dict(),
        "reconnects": len(span.attempts) - 1,
        "ttft_ms_per_attempt": span.ttft_ms_per_attempt(),
        "reconnect_counter": tel.stream_reconnects_total.get(),
        "note": (
            "one TTFT per attempt: attempt 0's first token and the "
            "post-reconnect attempt's first token are separate samples — "
            "reconnect backoff never inflates TTFT"
        ),
    }


# -- data plane (BENCH_DATAPLANE_OBSERVE.json) --------------------------------
def bench_dataplane_hot_path(n: int = 20_000, repeats: int = 12) -> dict:
    """µs per shm-op instrumentation hook (the ≤2 µs acceptance number)
    and the disabled-path gate the shm utils run with no recorder
    installed (≤0.1 µs)."""
    import timeit

    from client_tpu import observe

    recorder = observe.enable_dataplane()
    g = {"rec": recorder, "observe": observe}

    def best(stmt: str) -> float:
        out = []
        for _ in range(repeats):
            out.append(timeit.Timer(stmt, globals=g).timeit(n) / n * 1e6)
        return min(out)

    try:
        map_us = best("rec.on_map('system', True)")
        create_destroy_us = best(
            "rec.on_create('system', 4096)\n"
            "rec.on_destroy('system', 4096)") / 2.0
        rpc_us = best("rec.on_rpc('http', 'system', 'register', 0.0005)")
        # the gate every shm util op runs (module attribute + None check);
        # measured with the recorder REMOVED, exactly the disabled path
        observe.install_dataplane(None)
        disabled_us = best(
            "r = observe._DATAPLANE\n"
            "if r is not None:\n"
            "    r.on_map('system', True)")
    finally:
        observe.install_dataplane(None)
    return {
        "calls_per_repeat": n,
        "repeats": repeats,
        "map_op_us": round(map_us, 4),
        "create_destroy_op_us": round(create_destroy_us, 4),
        "register_rpc_record_us": round(rpc_us, 4),
        "disabled_us_per_op": round(disabled_us, 4),
        "note": (
            "enabled = one registry-lock acquire batching the op's "
            "counter/gauge updates (acceptance ≤ 2 µs); disabled = the "
            "module-attribute None check every shm util op runs with no "
            "recorder installed (acceptance ≤ 0.1 µs); register-RPC "
            "recording adds one histogram observe + outcome counter"
        ),
    }


def orca_e2e() -> dict:
    """ORCA gauges proven end-to-end on all four frontends against the
    in-repo servers: one opted-in infer each, the raw header, the parsed
    load, and the rendered client_tpu_endpoint_load gauge."""
    import asyncio

    import client_tpu.grpc as grpcclient
    import client_tpu.grpc.aio as aiogrpcclient
    import client_tpu.http as httpclient
    import client_tpu.http.aio as aiohttpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.observe import Telemetry
    from client_tpu.server import (
        AioHttpInferenceServer,
        GrpcInferenceServer,
        HttpInferenceServer,
        ServerCore,
    )

    def report(tel, url, header):
        load = tel.endpoint_loads().get(url)
        rendered = f'client_tpu_endpoint_load{{url="{url}"' in (
            tel.registry.prometheus_text())
        return {
            "header_sample": header,
            "parsed_metrics": load.metrics if load else None,
            "gauges_rendered": rendered,
            "proven": bool(load and rendered),
        }

    out = {}
    # sync pair
    for proto, mod, server_cls, fmt in (
            ("http", httpclient, HttpInferenceServer, "json"),
            ("grpc", grpcclient, GrpcInferenceServer, "text")):
        core = ServerCore(default_model_zoo())
        with server_cls(core) as server:
            tel = Telemetry(orca_format=fmt)
            with mod.InferenceServerClient(server.url) as client:
                client.configure_telemetry(tel)
                result = client.infer("simple", _simple_pair(mod))
                header = result.get_response_header("endpoint-load-metrics")
                out[proto] = report(tel, server.url, header)

    async def aio_pair():
        core = ServerCore(default_model_zoo())
        with AioHttpInferenceServer(core) as server:
            tel = Telemetry(orca_format="json")
            async with aiohttpclient.InferenceServerClient(
                    server.url) as client:
                client.configure_telemetry(tel)
                result = await client.infer("simple", _simple_pair(aiohttpclient))
                header = result.get_response_header("endpoint-load-metrics")
                out["http_aio"] = report(tel, server.url, header)
        core = ServerCore(default_model_zoo())
        with GrpcInferenceServer(core) as server:
            tel = Telemetry(orca_format="json")
            async with aiogrpcclient.InferenceServerClient(
                    server.url) as client:
                client.configure_telemetry(tel)
                result = await client.infer("simple", _simple_pair(aiogrpcclient))
                header = result.get_response_header("endpoint-load-metrics")
                out["grpc_aio"] = report(tel, server.url, header)

    asyncio.run(aio_pair())
    return out


def doctor_chaos_snapshot() -> dict:
    """A doctor snapshot captured from a live 3-replica run under the
    chaos proxy (one replica behind an 80 ms latency fault): the
    decomposition must attribute the extra milliseconds to the network
    leg, not the server, and the divergence flag must name the slowed
    replica."""
    import client_tpu.http as httpclient
    from client_tpu.doctor import collect_snapshot, render_summary
    from client_tpu.models import default_model_zoo
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    servers = [HttpInferenceServer(ServerCore(default_model_zoo())).start()
               for _ in range(3)]
    proxies = [ChaosProxy("127.0.0.1", s.port).start() for s in servers]
    try:
        for server in servers:  # jit warmup must not masquerade as chaos
            with httpclient.InferenceServerClient(server.url) as client:
                client.infer("simple", _simple_pair(httpclient))
        proxies[0].fault = Fault("latency", latency_s=0.08)
        snap = collect_snapshot(
            [p.url for p in proxies], requests_per_endpoint=8,
            skew_warn_ms=60000.0)
        slowed_url = proxies[0].url
        slowed_row = next(r for r in snap["decomposition"]
                          if r["url"] == slowed_url)
        other_rows = [r for r in snap["decomposition"]
                      if r["url"] != slowed_url]
        flags = {f["flag"]: f.get("url") for f in snap["anomalies"]}
        return {
            "summary": render_summary(snap),
            "snapshot": snap,
            "proof": {
                "slowed_replica": slowed_url,
                "slowed_network_leg_exceeds_server": (
                    slowed_row["network_client_overhead_ms"]
                    > slowed_row["server_total_ms"]),
                "slowed_server_compute_ms": slowed_row["server_compute_ms"],
                "other_server_compute_ms": [
                    r["server_compute_ms"] for r in other_rows],
                "divergence_flag_names_slowed_replica": (
                    flags.get("load_latency_divergence") == slowed_url),
            },
        }
    finally:
        for proxy in proxies:
            proxy.stop()
        for server in servers:
            server.stop()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default=None)
    parser.add_argument("--requests", type=int, default=600)
    parser.add_argument(
        "--micro-calls", type=int, default=20_000,
        help="calls per microbench repeat; keep under the telemetry "
             "inline-fold backlog (32768) so the deferred fold stays on "
             "the scraper's side of the measurement",
    )
    parser.add_argument(
        "--stream", action="store_true",
        help="benchmark the STREAMING telemetry instead (per-chunk mark "
             "cost, stream trace-join proof per protocol pair, reconnect "
             "sub-span demo); writes BENCH_STREAM_OBSERVE.json by default",
    )
    parser.add_argument(
        "--dataplane", action="store_true",
        help="benchmark the DATA-PLANE telemetry instead (shm-op "
             "instrumentation micro-overhead, ORCA e2e proof on all four "
             "frontends, doctor snapshot from a 3-replica chaos run); "
             "writes BENCH_DATAPLANE_OBSERVE.json by default",
    )
    args = parser.parse_args()

    if args.dataplane:
        out = {
            "generated_unix": int(time.time()),
            "platform": platform.platform(),
            "python": platform.python_version(),
            "note": (
                "data-plane telemetry cost + proof artifact: shm-op "
                "instrumentation microbench (enabled ≤2 µs, disabled "
                "≤0.1 µs acceptance), ORCA endpoint-load gauges proven "
                "e2e against the in-repo servers on all four frontends, "
                "and a doctor fleet snapshot from a live 3-replica chaos "
                "run (one replica behind an 80 ms latency fault) whose "
                "decomposition attributes the delay to the network leg"
            ),
            "dataplane_hot_path": bench_dataplane_hot_path(args.micro_calls),
            "orca_e2e": orca_e2e(),
            "doctor_chaos": doctor_chaos_snapshot(),
        }
        output = args.output or "BENCH_DATAPLANE_OBSERVE.json"
    elif args.stream:
        out = {
            "generated_unix": int(time.time()),
            "platform": platform.platform(),
            "python": platform.python_version(),
            "note": (
                "streaming telemetry cost + join artifact: per-chunk mark "
                "microbench (≤2 µs/mark acceptance; disabled ≤0.1 µs), "
                "one traced stream per protocol pair (HTTP SSE + GRPC "
                "decoupled) joined to its server access record on the "
                "same trace id, and a flap-chaos reconnect demo showing "
                "TTFT recorded per attempt"
            ),
            "stream_hot_path": bench_stream_hot_path(args.micro_calls),
            "stream_trace_join": stream_trace_join(),
            "reconnect_demo": stream_reconnect_demo(),
        }
        output = args.output or "BENCH_STREAM_OBSERVE.json"
    else:
        out = {
            "generated_unix": int(time.time()),
            "platform": platform.platform(),
            "python": platform.python_version(),
            "note": (
                "telemetry hot-path microbench (the <2 µs/call acceptance "
                "number), end-to-end A/B vs a bare client with a rerun "
                "noise floor, and one traced request per frontend pair "
                "joined to its server-side access record on the same "
                "trace id"
            ),
            "hot_path": bench_hot_path(args.micro_calls),
            "e2e": bench_e2e(args.requests),
            "trace_join": trace_join(),
        }
        output = args.output or "BENCH_OBSERVE.json"

    Path(output).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
