"""Produce the BASELINE.md measurement matrix in one run.

Spins the in-process server (whatever jax backend is live — TPU when the
tunnel is up, cpu fallback otherwise), then sweeps the perf harness across
protocol x shared-memory-mode x concurrency and prints a ready-to-paste
markdown table plus a JSON blob (written to BASELINE_SWEEP.json).

    python tools/baseline_sweep.py                  # quick matrix
    python tools/baseline_sweep.py --full           # c=1..32, more requests

This is the driver for SURVEY.md §6 / VERDICT r1 item 7 (concurrency sweeps
with p50/p99 per data-plane mode).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--full", action="store_true", help="c=1..32 sweep")
    parser.add_argument("--model", default="custom_identity_int32")
    parser.add_argument("--elems", type=int, default=1 << 18, help="tensor elems (default 1 MiB int32)")
    parser.add_argument("--requests", type=int, default=0, help="override measurement requests")
    parser.add_argument("--out", default="BASELINE_SWEEP.json")
    args = parser.parse_args()

    import jax

    from client_tpu.models import default_model_zoo
    from client_tpu.perf import PerfRunner
    from client_tpu.server import GrpcInferenceServer, HttpInferenceServer, ServerCore

    platform = jax.default_backend()
    concurrencies = [1, 2, 4, 8, 16, 32] if args.full else [1, 4, 16]
    requests = args.requests or (400 if args.full else 150)

    core = ServerCore(default_model_zoo())
    rows = []
    with HttpInferenceServer(core) as hs, GrpcInferenceServer(core) as gs:
        urls = {"http": hs.url, "grpc": gs.url, "native": hs.url, "native-grpc": gs.url}
        protocols = ["http", "grpc"]
        try:
            from client_tpu.native import available

            if available():
                protocols += ["native", "native-grpc"]
        except Exception:
            pass
        for protocol in protocols:
            for shm in ("none", "system", "tpu"):
                if protocol in ("native", "native-grpc") and shm == "system":
                    continue
                for c in concurrencies:
                    try:
                        runner = PerfRunner(
                            urls[protocol], protocol, args.model,
                            shared_memory=shm,
                            shape_overrides={"INPUT0": [1, args.elems]},
                        )
                        r = runner.run(concurrency=c, measurement_requests=requests)
                    except Exception as e:
                        rows.append({
                            "protocol": protocol, "shm": shm, "concurrency": c,
                            "error": str(e)[:200],
                        })
                        continue
                    rows.append({
                        "protocol": protocol, "shm": shm, "concurrency": c,
                        "infer_per_sec": r["infer_per_sec"],
                        "p50_ms": r["latency_ms"]["p50"],
                        "p99_ms": r["latency_ms"]["p99"],
                        "errors": r["errors"],
                    })
                    print(json.dumps(rows[-1]), flush=True)

    payload = {
        "platform": platform,
        "model": args.model,
        "tensor_bytes": args.elems * 4,
        "requests_per_point": requests,
        "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)

    # markdown table for BASELINE.md
    print(f"\n### Sweep ({platform}, {args.elems * 4 // (1 << 20)} MiB {args.model}, {requests} req/pt)\n")
    print("| protocol | shm | c | infer/s | p50 ms | p99 ms |")
    print("|---|---|---|---|---|---|")
    for row in rows:
        if "error" in row:
            print(f"| {row['protocol']} | {row['shm']} | {row['concurrency']} | error: {row['error'][:40]} | | |")
        else:
            print(
                f"| {row['protocol']} | {row['shm']} | {row['concurrency']} | "
                f"{row['infer_per_sec']} | {row['p50_ms']} | {row['p99_ms']} |"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
