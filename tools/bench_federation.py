"""Generate BENCH_FEDERATION.json: graceful degradation under cell-scale
failure, measured open-loop — plus the canary-burn rollback transcript.

The claims under test (ROADMAP item 5 / the federation ISSUE):

1. **Blackhole arm** — a 2-cell fleet (2 replicas per cell, every
   replica behind a ChaosProxy) replays one seeded open-loop unary
   trace while the WHOLE home cell blackholes mid-trace (one
   ``ChaosCell.blackhole()`` call):

   - ``single_cell`` baseline: a plain ``PoolClient`` over the home
     cell only. Expected: the run collapses — a large error fraction,
     failed SLOs, delivery ratio far below 1.
   - ``federated``: a ``FederatedClient`` over both cells, home-first.
     Expected: user-visible error rate ~0 (requests transparently spill
     to the surviving cell), the declared SLOs attained, delivery ratio
     ~1, and a nonzero spill count with the home cell's breaker open.

2. **Canary-burn arm** — the home cell healthy, a canary cell behind a
   latency fault, ``CanaryPolicy(weight=0.3, slo="p95<100ms")``.
   Expected: the burn watcher rolls the canary back to weight 0
   mid-replay (typed ``CanaryRolledBack``), ZERO user-visible errors
   attributable to the rollout or its rollback, and no canary routing
   after the rollback (the transcript records the event).

Methodology notes (honest-measurement rules from tools/bench_capacity.py):
open-loop arrivals (arXiv:2210.04323 — capacity under failure must be
offered, not self-throttled), both arms replay the SAME seeded trace,
servers are pre-warmed so jit never bills an SLO, and the artifact
keeps every arm's full replay row so the binding SLO is inspectable.

``--check`` re-validates the committed artifact's invariants (CI runs it
via tests/test_federation.py::test_bench_federation_artifact_claims);
``tools/capacity_gate.py --federation`` re-RUNS the federated blackhole
arm live on a shortened twin and fails when the invariants stop holding.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_federation.py [-o BENCH_FEDERATION.json]
    JAX_PLATFORMS=cpu python tools/bench_federation.py --check
"""

from __future__ import annotations

import argparse
import contextlib
import json
import platform
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# one seeded unary trace for every arm: numbers are apples-to-apples
TRACE_SPEC = ("poisson_burst:duration_s=5,rate=40,burst_factor=1,"
              "model=simple")
TRACE_SEED = 2033
# the blackhole lands at this fraction of the (speed-adjusted) replay
# window — far enough in that both arms have a healthy baseline, early
# enough that most of the trace runs under the failure
BLACKHOLE_AT_FRACTION = 0.4
# declared SLOs: p95 must absorb the spill-transition cohort (requests
# in flight toward the dying cell pay one bounded home attempt before
# spilling — see CELL_ATTEMPT_TIMEOUT_S), error budget is the headline
SLOS = ["p95<750ms", "error_rate<1%"]
# what the federated arm's transition cohort pays per doomed home try
CELL_ATTEMPT_TIMEOUT_S = 0.4
CELL_DEADLINE_S = 6.0
REPLAY_WORKERS = 32
# canary arm: latency fault + burn objective + split weight
CANARY_TRACE_SPEC = ("poisson_burst:duration_s=4,rate=30,burst_factor=1,"
                     "model=simple")
CANARY_LATENCY_S = 0.25
CANARY_SLO = "p95<100ms"
CANARY_WEIGHT = 0.3
CANARY_MIN_EVENTS = 10
# ceilings the committed artifact must beat (validated by --check)
FED_MAX_ERROR_RATE = 0.01
FED_MIN_DELIVERY = 0.95
BASELINE_MAX_DELIVERY = 0.75  # the collapse must be visible


@contextlib.contextmanager
def two_cells(replicas_per_cell: int = 2):
    """(cells dict, ChaosCell per cell) over live threaded HTTP servers,
    every replica behind its own ChaosProxy."""
    from client_tpu.models import default_model_zoo
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosCell, ChaosProxy

    n = 2 * replicas_per_cell
    cores = [ServerCore(default_model_zoo()) for _ in range(n)]
    servers = [HttpInferenceServer(c).start() for c in cores]
    proxies = [ChaosProxy("127.0.0.1", s.port).start() for s in servers]
    cell_a = ChaosCell(proxies[:replicas_per_cell])
    cell_b = ChaosCell(proxies[replicas_per_cell:])
    try:
        yield ({"a": cell_a.urls, "b": cell_b.urls},
               {"a": cell_a, "b": cell_b})
    finally:
        for p in proxies:
            p.stop()
        for s in servers:
            s.stop()


def _warm(url: str) -> None:
    """Pre-warm one server (jit compile) before the measured window."""
    import numpy as np

    import client_tpu.http as httpclient

    client = httpclient.InferenceServerClient(url)
    try:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        in0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
        in0.set_data_from_numpy(a)
        in1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
        in1.set_data_from_numpy(a)
        for _ in range(2):
            client.infer("simple", [in0, in1], client_timeout=10.0)
    finally:
        client.close()


def _blackhole_timer(cell, delay_s: float, transcript: List[Dict[str, Any]]):
    def fire():
        transcript.append({"event": "cell_blackhole", "cell": "a",
                           "at_s": round(delay_s, 3)})
        cell.blackhole()

    timer = threading.Timer(delay_s, fire)
    timer.daemon = True
    timer.start()
    return timer


def run_blackhole_arm(cells: Dict[str, List[str]], chaos,
                      federated: bool, duration_s: Optional[float] = None,
                      speed: float = 1.0) -> Dict[str, Any]:
    """One open-loop replay with the home cell blackholed mid-trace.

    ``federated=False`` is the single-cell baseline: the SAME client
    stack over the home cell only — identical attempt budget and
    per-attempt patience, the only difference is having no second cell
    to spill to. That keeps the comparison about AVAILABILITY (a tighter
    timeout or a different engine would smuggle in a second variable)."""
    from client_tpu import trace as trace_mod
    from client_tpu.perf import PerfRunner

    spec = TRACE_SPEC
    if duration_s is not None:
        spec = spec.replace("duration_s=5", f"duration_s={duration_s:g}")
    tr = trace_mod.generate(spec, seed=TRACE_SEED)
    for url in [u for urls in cells.values() for u in urls]:
        _warm(url)
    arm_cells = dict(cells) if federated else {"a": cells["a"]}
    runner = PerfRunner(
        cells["a"][0], "http", "simple",
        cells=arm_cells, home_cell="a",
        cells_deadline_s=CELL_DEADLINE_S,
        cells_attempt_timeout_s=CELL_ATTEMPT_TIMEOUT_S)
    trace_window = tr.duration_s / speed
    transcript: List[Dict[str, Any]] = []
    timer = _blackhole_timer(
        chaos["a"], BLACKHOLE_AT_FRACTION * trace_window, transcript)
    try:
        row = runner.run_trace(tr, speed=speed,
                               replay_workers=REPLAY_WORKERS,
                               slos=list(SLOS))
    finally:
        timer.cancel()
        runner.close()
        chaos["a"].heal(reset_active=True)
    issued = row["issued"] or 1
    out = {
        "arm": "federated" if federated else "single_cell",
        "slos": list(SLOS),
        "blackhole_at_s": round(BLACKHOLE_AT_FRACTION * trace_window, 3),
        "delivery_ratio": round(row["requests"] / issued, 4),
        "error_rate": row["error_rate"],
        "shed_rate": row["shed_rate"],
        "slo_ok": row["slo_ok"],
        "row": row,
    }
    if federated:
        fed = row.get("client_federation") or {}
        out["spills"] = fed.get("spills", 0)
        out["home_breaker"] = (fed.get("cells", {}).get("a") or {}).get(
            "breaker_state")
    out["transcript"] = transcript
    return out


def run_canary_arm(cells: Dict[str, List[str]], chaos,
                   duration_s: Optional[float] = None) -> Dict[str, Any]:
    """Home healthy, canary cell behind a latency fault: the replay must
    finish with zero errors, the canary rolled back mid-run, and no
    canary routing after the rollback."""
    from client_tpu import trace as trace_mod
    from client_tpu.perf import PerfRunner

    spec = CANARY_TRACE_SPEC
    if duration_s is not None:
        spec = spec.replace("duration_s=4", f"duration_s={duration_s:g}")
    tr = trace_mod.generate(spec, seed=TRACE_SEED + 1)
    for url in [u for urls in cells.values() for u in urls]:
        _warm(url)
    chaos["b"].latency(CANARY_LATENCY_S)  # the bad rollout
    transcript: List[Dict[str, Any]] = []
    runner = PerfRunner(
        cells["a"][0], "http", "simple",
        cells=cells, home_cell="a",
        canary_cell="b", canary_weight=CANARY_WEIGHT,
        canary_slo=CANARY_SLO, canary_min_events=CANARY_MIN_EVENTS,
        cells_deadline_s=CELL_DEADLINE_S,
        cells_attempt_timeout_s=2.0)
    try:
        t0 = time.monotonic()
        row = runner.run_trace(tr, speed=1.0,
                               replay_workers=REPLAY_WORKERS,
                               slos=["error_rate<0.5%"])
    finally:
        runner.close()
        chaos["b"].heal()
    canary = (row.get("client_federation") or {}).get("canary") or {}
    if canary.get("rolled_back"):
        transcript.append({
            "event": "canary_rolled_back",
            "cell": canary.get("cell"),
            "burn_rate": canary.get("burn_rate"),
            "events_at_decision": canary.get("ok", 0) + canary.get("bad", 0),
            "within_s": round(time.monotonic() - t0, 3),
        })
    return {
        "arm": "canary_burn",
        "canary_slo": CANARY_SLO,
        "canary_weight": CANARY_WEIGHT,
        "canary_latency_fault_s": CANARY_LATENCY_S,
        "error_rate": row["error_rate"],
        "rolled_back": bool(canary.get("rolled_back")),
        "weight_after": canary.get("weight"),
        "routed": canary.get("routed", 0),
        "fallbacks": canary.get("fallbacks", 0),
        "rollbacks": canary.get("rollbacks", 0),
        "transcript": transcript,
        "row": row,
    }


def generate(out_path: str) -> Dict[str, Any]:
    doc: Dict[str, Any] = {
        "kind": "client_tpu_bench_federation",
        "version": 1,
        "generated_unix": int(time.time()),
        "host": {"platform": platform.platform(),
                 "python": platform.python_version()},
        "trace": {"spec": TRACE_SPEC, "seed": TRACE_SEED},
        "slos": SLOS,
        "search": {
            "blackhole_at_fraction": BLACKHOLE_AT_FRACTION,
            "cell_attempt_timeout_s": CELL_ATTEMPT_TIMEOUT_S,
            "cell_deadline_s": CELL_DEADLINE_S,
            "replay_workers": REPLAY_WORKERS,
            "canary": {"spec": CANARY_TRACE_SPEC,
                       "seed": TRACE_SEED + 1,
                       "latency_fault_s": CANARY_LATENCY_S,
                       "slo": CANARY_SLO, "weight": CANARY_WEIGHT,
                       "min_events": CANARY_MIN_EVENTS},
        },
        "arms": {},
    }
    print("== single_cell baseline (home cell only, blackholed mid-trace)")
    with two_cells() as (cells, chaos):
        doc["arms"]["single_cell"] = run_blackhole_arm(
            cells, chaos, federated=False)
    arm = doc["arms"]["single_cell"]
    print(f"   delivery={arm['delivery_ratio']} error_rate="
          f"{arm['error_rate']} slo_ok={arm['slo_ok']}")
    print("== federated (2 cells, home blackholed mid-trace)")
    with two_cells() as (cells, chaos):
        doc["arms"]["federated"] = run_blackhole_arm(
            cells, chaos, federated=True)
    arm = doc["arms"]["federated"]
    print(f"   delivery={arm['delivery_ratio']} error_rate="
          f"{arm['error_rate']} slo_ok={arm['slo_ok']} "
          f"spills={arm['spills']} home_breaker={arm['home_breaker']}")
    print("== canary burn (latency-faulted canary cell, auto-rollback)")
    with two_cells() as (cells, chaos):
        doc["arms"]["canary_burn"] = run_canary_arm(cells, chaos)
    arm = doc["arms"]["canary_burn"]
    print(f"   rolled_back={arm['rolled_back']} error_rate="
          f"{arm['error_rate']} routed={arm['routed']} "
          f"weight_after={arm['weight_after']}")
    problems = check_artifact(doc)
    if problems:
        print("INVARIANT FAILURES (artifact NOT written):")
        for p in problems:
            print(f"  - {p}")
        raise SystemExit(1)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"written: {out_path}")
    return doc


def check_artifact(doc: Dict[str, Any]) -> List[str]:
    """Every claim the committed artifact makes, re-validated. Returns
    the list of violated invariants (empty = artifact holds)."""
    problems: List[str] = []
    arms = doc.get("arms", {})
    single = arms.get("single_cell")
    fed = arms.get("federated")
    canary = arms.get("canary_burn")
    if not (single and fed and canary):
        return ["artifact missing one of single_cell/federated/"
                "canary_burn arms"]
    # -- the federated arm holds under the blackhole
    if fed["error_rate"] > FED_MAX_ERROR_RATE:
        problems.append(
            f"federated error_rate {fed['error_rate']} > "
            f"{FED_MAX_ERROR_RATE}: spillover did not hold errors at ~0")
    if not fed["slo_ok"]:
        problems.append("federated arm missed a declared SLO")
    if fed["delivery_ratio"] < FED_MIN_DELIVERY:
        problems.append(
            f"federated delivery {fed['delivery_ratio']} < "
            f"{FED_MIN_DELIVERY}")
    if fed.get("spills", 0) <= 0:
        problems.append("federated arm recorded no spills — the "
                        "blackhole never exercised the spillover path")
    if fed.get("home_breaker") not in ("open", "half_open"):
        problems.append(
            f"home cell breaker {fed.get('home_breaker')!r} after the "
            "blackhole (expected open/half_open)")
    # -- the baseline visibly collapses (the comparison that makes the
    #    federated number a claim instead of a tautology)
    if single["slo_ok"]:
        problems.append("single_cell baseline attained its SLOs under "
                        "the blackhole — no collapse to degrade "
                        "gracefully from")
    collapsed = (single["delivery_ratio"] <= BASELINE_MAX_DELIVERY
                 or single["error_rate"] >= 0.1)
    if not collapsed:
        problems.append(
            f"single_cell baseline neither lost delivery "
            f"(ratio {single['delivery_ratio']}) nor errored "
            f"(rate {single['error_rate']}) — the blackhole arm "
            "proved nothing")
    if fed["delivery_ratio"] <= single["delivery_ratio"]:
        problems.append("federated delivery did not beat the baseline")
    # -- canary: rolled back, zero user-visible errors, routing stopped
    if not canary["rolled_back"]:
        problems.append("canary never rolled back under the burn")
    if canary["error_rate"] > 0.005:
        problems.append(
            f"canary arm error_rate {canary['error_rate']}: the rollout/"
            "rollback leaked user-visible errors")
    if canary.get("weight_after") != 0.0:
        problems.append(
            f"canary weight after rollback is "
            f"{canary.get('weight_after')!r}, not 0.0")
    if canary.get("rollbacks") != 1:
        problems.append(
            f"canary rollbacks {canary.get('rollbacks')} != 1 "
            "(must fire exactly once)")
    if canary.get("routed", 0) < CANARY_MIN_EVENTS:
        problems.append(
            "canary routed fewer requests than min_events — the burn "
            "verdict was never reachable")
    if not canary.get("transcript"):
        problems.append("canary arm carries no rollback transcript")
    return problems


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--out", default="BENCH_FEDERATION.json")
    parser.add_argument("--check", action="store_true",
                        help="validate the committed artifact's "
                             "invariants instead of regenerating")
    args = parser.parse_args()
    if args.check:
        with open(args.out) as f:
            doc = json.load(f)
        problems = check_artifact(doc)
        if problems:
            print("ARTIFACT CHECK FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(f"{args.out}: all invariants hold")
        return 0
    generate(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
