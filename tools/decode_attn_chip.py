"""Flash-decoding kernel on the real chip: Mosaic exactness + latency curve.

VERDICT-r3 #3: ``ops/decode_attention.py`` had only ever run in Pallas
interpret mode — this tool is its first (and repeatable) meeting with the
real Mosaic compiler. Two sections, one JSON:

- ``exactness``: compiled kernel vs the dense fp32 reference at several
  (shape, cache position) points, including the ragged-tail and pos=0
  extremes the CI tier pins off-chip (tests/test_decode_attention.py) and
  the decoder_lm serving shape.
- ``latency``: ms/step pallas vs einsum over cache length and fill level —
  the decode hot op is HBM-bandwidth-bound, so the interesting curve is
  traffic (the kernel's block skip reads only ``pos`` worth of cache; the
  dense path always reads MAX_LEN), plus the honest small-shape crossover:
  at the decoder_lm fixture size the whole cache fits one tile and dense
  einsum may win.

Timing methodology matches tools/chip_bench.py: ``steps`` iterations
chained inside ONE dispatch via ``lax.fori_loop`` with a carry-dependent
input perturbation (q * (1 + 0*acc)) so XLA cannot hoist the loop-invariant
attention out of the loop, divided by steps — tunnel RTT amortized away.

Run on the chip (or with --interpret off-chip for a pipeline check):
    python tools/decode_attn_chip.py [--json-out PATH] [--small]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.chip_bench import _timed_single_dispatch  # noqa: E402


def check_exactness(jnp, np, interpret):
    from client_tpu.ops.decode_attention import (
        decode_attention,
        decode_attention_reference,
    )

    cases = [
        # (batch, heads, max_len, dim, positions, dtype)
        (1, 4, 128, 32, [0, 5, 127], "float32"),   # decoder_lm shape
        (3, 2, 200, 64, [0, 99, 199], "float32"),  # ragged block tail
        (2, 8, 384, 128, [100, 383], "float32"),   # multi-block, MXU dim
        (4, 8, 1024, 128, [0, 511, 1023], "bfloat16"),  # serving-scale bf16
    ]
    if interpret:
        # off-chip pipeline check only — the interpreter walks the grid in
        # Python, so keep to the CI-tier shapes (tests cover the rest)
        cases = cases[:2]
    rows = []
    ok = True
    for batch, heads, max_len, dim, positions, dtype in cases:
        rng = np.random.default_rng(0)
        dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        q = jnp.asarray(rng.standard_normal((batch, heads, dim)), dt)
        k = jnp.asarray(
            rng.standard_normal((batch, heads, max_len, dim)), dt)
        v = jnp.asarray(
            rng.standard_normal((batch, heads, max_len, dim)), dt)
        # every listed position is exercised (batch-broadcast), so small
        # batches don't silently drop the pos extremes
        diff = 0.0
        for p in positions:
            pos = jnp.full((batch,), p, jnp.int32)
            out = decode_attention(q, k, v, pos, interpret=interpret)
            ref = decode_attention_reference(q, k, v, pos)
            diff = max(diff, float(jnp.max(jnp.abs(
                out.astype(jnp.float32) - ref.astype(jnp.float32)))))
        tol = 2e-2 if dtype == "bfloat16" else 1e-5
        rows.append({
            "shape": [batch, heads, max_len, dim], "dtype": dtype,
            "positions": positions, "max_abs_diff": diff,
            "tol": tol, "ok": diff < tol,
        })
        ok = ok and diff < tol
    return {"ok": ok, "cases": rows}


def bench_latency(jax, jnp, np, interpret, small):
    """ms/step pallas vs einsum over (max_len, fill) — plus the serving
    shape row feeding the BatchedDecoderModel default choice."""
    from client_tpu.ops.decode_attention import (
        decode_attention,
        decode_attention_reference,
    )

    if small:
        grid = [(2, 2, 128, 32, [127], 2)]
    else:
        grid = [
            # (batch, heads, max_len, dim, fills, steps)
            (8, 8, 2048, 128, [64, 512, 2047], 20),
            (8, 8, 8192, 128, [8191], 10),
            (16, 8, 4096, 128, [4095], 10),
            # decoder_lm_batched serving shape (slots=8): the honest
            # small-shape row — whichever impl wins here is the default
            (8, 4, 128, 32, [127], 40),
        ]

    def timed(impl_fn, q, k, v, pos, steps):
        @jax.jit
        def chained(q, k, v, pos):
            def body(_, acc):
                # carry-dependent perturbation: blocks XLA from hoisting
                # the loop-invariant attention out of the fori_loop (q is
                # tiny, so the extra elementwise is noise vs cache traffic);
                # cast back so the f32 carry doesn't promote the bf16 query
                # and silently bench a mixed-dtype dot
                qq = (q * (1.0 + 0.0 * acc)).astype(q.dtype)
                o = impl_fn(qq, k, v, pos)
                return acc + jnp.sum(o.astype(jnp.float32))

            return jax.lax.fori_loop(0, steps, body, jnp.float32(0))

        return _timed_single_dispatch(chained, q, k, v, pos, iters_inside=steps)

    rows = []
    for batch, heads, max_len, dim, fills, steps in grid:
        rng = np.random.default_rng(1)
        q = jnp.asarray(
            rng.standard_normal((batch, heads, dim)), jnp.bfloat16)
        k = jnp.asarray(
            rng.standard_normal((batch, heads, max_len, dim)), jnp.bfloat16)
        v = jnp.asarray(
            rng.standard_normal((batch, heads, max_len, dim)), jnp.bfloat16)
        for fill in fills:
            pos = jnp.full((batch,), fill, jnp.int32)
            row = {"batch": batch, "heads": heads, "max_len": max_len,
                   "dim": dim, "fill": fill}
            try:
                dt_p = timed(
                    lambda q, k, v, pos: decode_attention(
                        q, k, v, pos, interpret=interpret),
                    q, k, v, pos, steps)
                row["pallas_ms"] = round(dt_p * 1000, 4)
                # cache traffic actually needed: (fill+1) K+V rows, bf16
                need = batch * heads * (fill + 1) * dim * 2 * 2
                row["pallas_gbps_effective"] = round(need / dt_p / 1e9, 1)
            except Exception as e:
                row["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
            try:
                dt_e = timed(decode_attention_reference, q, k, v, pos, steps)
                row["einsum_ms"] = round(dt_e * 1000, 4)
            except Exception as e:
                row["einsum_error"] = f"{type(e).__name__}: {e}"[:300]
            if "pallas_ms" in row and "einsum_ms" in row:
                row["pallas_speedup"] = round(
                    row["einsum_ms"] / row["pallas_ms"], 3)
            rows.append(row)
    return rows


def run(interpret: bool, small: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = jax.devices()[0]
    result = {
        "platform": jax.default_backend(),
        "device_kind": device.device_kind,
        "mosaic_compiled": not interpret,
    }
    try:
        result["exactness"] = check_exactness(jnp, np, interpret)
    except Exception as e:
        result["exactness"] = {
            "ok": False, "error": f"{type(e).__name__}: {e}"[:500]}
    try:
        result["latency"] = bench_latency(jax, jnp, np, interpret, small)
    except Exception as e:
        result["latency_error"] = f"{type(e).__name__}: {e}"[:500]
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--interpret", action="store_true",
                        help="force interpret mode (off-chip pipeline check)")
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()

    import jax

    if args.interpret or os.environ.get("JAX_PLATFORMS") == "cpu":
        # pin BEFORE the first backend touch: under axon sitecustomize even
        # jax.default_backend() hangs on a dead tunnel (config-level update
        # wins over the env, which sitecustomize overwrote)
        jax.config.update("jax_platforms", "cpu")
    interpret = args.interpret or jax.default_backend() not in ("tpu", "axon")
    result = run(interpret, args.small)
    text = json.dumps(result, indent=1)
    print(text)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(text + "\n")
    return 0 if result.get("exactness", {}).get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
