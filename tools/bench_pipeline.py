"""Generate BENCH_PIPELINE.json: the client-orchestrated model-DAG proof.

Four arms over in-process replica servers (the same topology every other
bench in this repo uses — CPU container numbers, honest about it):

- **exactness**: the 3-stage chain DAG (``chain_tokenize`` ->
  ``chain_embed`` -> ``chain_rerank``, intermediates handed off as
  arena-resident shm leases) must be BIT-identical to the fused
  ``chain_fused`` single-model reference — the two paths share one
  ``ChainCore``'s weights and jitted step functions (models/chain.py).
- **dag_vs_sequential**: the DAG at a batch whose intermediate tensors
  are big enough to matter vs the naive client-side chaining baseline —
  three sequential ``infer()`` calls that round-trip every intermediate
  through host memory and back over the wire. The DAG must win at p50:
  its intermediates never leave the server host (shm handle handoff).
- **steady_state**: after warmup, N DAG runs must issue ZERO region
  creates and ZERO registration RPCs, return every lease (residual
  leased bytes 0), and peak arena residency must equal the slab plan's
  high-water mark on every run.
- **chaos**: the endpoint one stage is pinned to is RST mid-run
  (ChaosProxy); every armed run must fail with a typed ``StageFailed``
  naming that stage (never a partial result), unstarted dependents must
  never dispatch, zero arena leases may leak, and the same client must
  recover bit-exact after heal.

``--check`` re-validates an existing artifact's acceptance invariants
and exits nonzero on violation (tests/test_pipeline.py pins the same
claims); ``tools/capacity_gate.py --pipeline`` re-RUNS the chaos arm
live:

    JAX_PLATFORMS=cpu python tools/bench_pipeline.py [-o BENCH_PIPELINE.json]
    JAX_PLATFORMS=cpu python tools/bench_pipeline.py --check BENCH_PIPELINE.json
"""

from __future__ import annotations

import argparse
import json
import platform
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BATCH = 128   # EMBED intermediate = batch*length*32*4 B ~= 2 MiB: big
LENGTH = 128  # enough that the sequential host round-trip visibly pays


def _percentiles(samples_s):
    xs = sorted(samples_s)
    n = len(xs)
    if not n:
        return {}
    pick = lambda q: xs[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
    return {
        "avg": round(1e3 * sum(xs) / n, 3),
        "p50": round(1e3 * pick(0.50), 3),
        "p90": round(1e3 * pick(0.90), 3),
        "p99": round(1e3 * pick(0.99), 3),
    }


def _raw(batch, length, seed=0xDA6):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 2**16, size=(batch, length), dtype=np.int32)


def _sequential_chain(client, mod, raw):
    """The baseline the DAG is benchmarked against: naive client-side
    chaining, every intermediate round-tripped through host memory."""
    inp = mod.InferInput("RAW", list(raw.shape), "INT32")
    inp.set_data_from_numpy(raw)
    tokens = client.infer("chain_tokenize", [inp]).as_numpy("TOKENS")
    inp = mod.InferInput("TOKENS", list(tokens.shape), "INT32")
    inp.set_data_from_numpy(tokens)
    embed = client.infer("chain_embed", [inp]).as_numpy("EMBED")
    inp = mod.InferInput("EMBED", list(embed.shape), "FP32")
    inp.set_data_from_numpy(embed)
    return client.infer("chain_rerank", [inp]).as_numpy("SCORES")


def run_chaos_arm(runs: int = 8, batch: int = 1, length: int = 16,
                  seed: int = 0xDA6):
    """The killed-stage proof, self-contained so ``capacity_gate.py
    --pipeline`` can re-run it live: the chain's first stage is pinned
    to a replica behind a ChaosProxy; every even run arms a persistent
    RST of that endpoint. Armed runs must fail with a typed StageFailed
    naming the pinned stage, dependents must never dispatch, no lease
    may leak, and healed runs must stay bit-exact."""
    import client_tpu.http as httpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.pipeline import Pipeline, PipelineClient, Stage, StageFailed
    from client_tpu.server import HttpInferenceServer, ServerCore
    from client_tpu.testing import ChaosProxy, Fault

    raw = _raw(batch, length, seed)
    srv = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    victim = HttpInferenceServer(ServerCore(default_model_zoo())).start()
    proxy = ChaosProxy("127.0.0.1", victim.port).start()
    pipe = Pipeline(
        stages=[
            Stage("tokenize", "chain_tokenize", inputs={"RAW": "$.RAW"},
                  outputs={"TOKENS": ("INT32", [batch, length])},
                  endpoint=proxy.url),
            Stage("embed", "chain_embed",
                  inputs={"TOKENS": "tokenize.TOKENS"},
                  outputs={"EMBED": ("FP32", [batch, length, 32])},
                  endpoint=srv.url),
            Stage("rerank", "chain_rerank",
                  inputs={"EMBED": "embed.EMBED"},
                  outputs={"SCORES": ("FP32", [batch, length])},
                  endpoint=srv.url),
        ],
        inputs={"RAW": ("INT32", [batch, length])},
        outputs={"SCORES": "rerank.SCORES"})
    ref = httpclient.InferenceServerClient(srv.url)
    inp = httpclient.InferInput("RAW", list(raw.shape), "INT32")
    inp.set_data_from_numpy(raw)
    want = ref.infer("chain_fused", [inp]).as_numpy("SCORES")
    ref.close()
    client = PipelineClient([srv.url, proxy.url], pipe, protocol="http",
                            health_interval_s=None)
    row = {"runs": runs, "completed": 0, "typed_stage_failures": 0,
           "wrong_failures": 0, "dependents_dispatched": 0,
           "leaked_lease_bytes": 0, "bit_exact": True, "recovered": False}
    try:
        client.run({"RAW": raw})  # warm the healthy path (jit compiles)
        # delta baseline: the default arena is process-global, so a
        # host process may hold unrelated long-lived leases
        base_leased = client.arena().stats()["leased_bytes"]
        for i in range(runs):
            arm_kill = i % 2 == 0
            if arm_kill:
                proxy.fault = Fault("reset", after_bytes=0)
                proxy.reset_active()
            settles_before = client.stats()["stages"]["embed"]["count"]
            try:
                res = client.run({"RAW": raw}, client_timeout=10.0)
            except StageFailed as e:
                if e.stage == "tokenize":
                    row["typed_stage_failures"] += 1
                else:
                    row["wrong_failures"] += 1
                row["dependents_dispatched"] += (
                    client.stats()["stages"]["embed"]["count"]
                    - settles_before)
            except Exception:
                row["wrong_failures"] += 1
            else:
                row["completed"] += 1
                row["bit_exact"] = row["bit_exact"] and np.array_equal(
                    res.as_numpy("SCORES"), want)
            if arm_kill:
                proxy.heal()
            row["leaked_lease_bytes"] += (
                client.arena().stats()["leased_bytes"] - base_leased)
        res = client.run({"RAW": raw})  # healed: the same client recovers
        row["recovered"] = bool(np.array_equal(
            res.as_numpy("SCORES"), want))
    finally:
        client.close()
        proxy.stop()
        victim.stop()
        srv.stop()
    return row


def chaos_problems(row) -> list:
    """The chaos arm's acceptance invariants (shared by --check and the
    live capacity_gate --pipeline re-run)."""
    problems = []
    if row["runs"] <= 0:
        problems.append("chaos arm ran no runs")
    if row["typed_stage_failures"] <= 0:
        problems.append("no killed-stage run produced a typed "
                        "StageFailed naming the pinned stage")
    if row["wrong_failures"] != 0:
        problems.append(f"{row['wrong_failures']} failures were not the "
                        "typed StageFailed for the killed stage")
    if row["dependents_dispatched"] != 0:
        problems.append(f"{row['dependents_dispatched']} dependent "
                        "stages dispatched after their producer failed")
    if row["leaked_lease_bytes"] != 0:
        problems.append(f"{row['leaked_lease_bytes']} arena lease bytes "
                        "leaked across failed runs")
    if row["bit_exact"] is not True:
        problems.append("surviving runs are not bit-exact vs the fused "
                        "reference")
    if row["recovered"] is not True:
        problems.append("the client did not recover bit-exact after heal")
    return problems


def check_doc(data) -> list:
    failures = []
    exact = data["exactness"]
    if exact["runs"] <= 0:
        failures.append("exactness arm measured no runs")
    if exact["bit_exact"] is not True:
        failures.append("DAG runs are not bit-exact vs chain_fused")
    versus = data["dag_vs_sequential"]
    if versus["runs"] <= 0:
        failures.append("dag_vs_sequential arm measured no runs")
    if not versus.get("dag_ms") or not versus.get("sequential_ms"):
        failures.append("dag_vs_sequential arm missing percentiles")
    if versus["dag_p50_ms"] >= versus["sequential_p50_ms"]:
        failures.append(
            f"DAG p50 {versus['dag_p50_ms']} ms does not beat the "
            f"sequential host-round-trip baseline "
            f"{versus['sequential_p50_ms']} ms")
    steady = data["steady_state"]
    if steady["runs"] <= 0:
        failures.append("steady-state arm measured no runs")
    if steady["region_creates_per_run"] != 0:
        failures.append("steady-state DAG runs created shm regions")
    if steady["registration_rpcs_per_run"] != 0:
        failures.append("steady-state DAG runs issued registration RPCs")
    if steady["leaked_lease_bytes"] != 0:
        failures.append("steady-state DAG runs leaked lease bytes")
    if steady["high_water_matches_plan"] is not True:
        failures.append("peak arena residency diverged from the slab "
                        "plan's high-water mark")
    failures.extend(chaos_problems(data["chaos"]))
    return failures


def check(path: str) -> int:
    failures = check_doc(json.loads(Path(path).read_text()))
    for msg in failures:
        print(f"CHECK FAILED: {msg}", file=sys.stderr)
    if not failures:
        print(f"{path}: all model-DAG pipeline acceptance invariants "
              "hold")
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-o", "--output", default="BENCH_PIPELINE.json")
    parser.add_argument("--runs", type=int, default=30)
    parser.add_argument("--chaos-runs", type=int, default=8)
    parser.add_argument("--batch", type=int, default=BATCH)
    parser.add_argument("--length", type=int, default=LENGTH)
    parser.add_argument("--check", metavar="ARTIFACT",
                        help="validate an existing artifact instead of "
                             "benchmarking")
    args = parser.parse_args()
    if args.check:
        return check(args.check)

    import client_tpu.http as httpclient
    from client_tpu.models import default_model_zoo
    from client_tpu.pipeline import chain_pipeline, PipelineClient
    from client_tpu.server import HttpInferenceServer, ServerCore

    raw = _raw(args.batch, args.length)
    srv = HttpInferenceServer(ServerCore(default_model_zoo())).start()

    out = {
        "generated_unix": int(time.time()),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "note": (
            "client-orchestrated 3-stage chain DAG (client_tpu.pipeline) "
            "over an in-process replica server: intermediates handed off "
            "as arena-resident shm leases; the sequential baseline "
            "chains the same three models with every intermediate "
            "round-tripped through host memory; fused reference is "
            "chain_fused (same ChainCore weights => bit-exactness is "
            "checkable); CPU container numbers"
        ),
        "batch": args.batch,
        "length": args.length,
        "intermediate_bytes_per_run": int(
            args.batch * args.length * 4            # TOKENS INT32
            + args.batch * args.length * 32 * 4),   # EMBED FP32
    }

    client = PipelineClient([srv.url], chain_pipeline(args.batch,
                                                      args.length),
                            protocol="http", health_interval_s=None)
    seq = httpclient.InferenceServerClient(srv.url)
    try:
        # -- exactness + dag_vs_sequential -------------------------------
        inp = httpclient.InferInput("RAW", list(raw.shape), "INT32")
        inp.set_data_from_numpy(raw)
        want = seq.infer("chain_fused", [inp]).as_numpy("SCORES")
        client.run({"RAW": raw})                      # jit + arena warmup
        _sequential_chain(seq, httpclient, raw)       # same warmup
        exact, dag_s, seq_s = True, [], []
        for _ in range(args.runs):
            t0 = time.perf_counter()
            res = client.run({"RAW": raw})
            dag_s.append(time.perf_counter() - t0)
            exact = exact and np.array_equal(res.as_numpy("SCORES"), want)
            t0 = time.perf_counter()
            scores = _sequential_chain(seq, httpclient, raw)
            seq_s.append(time.perf_counter() - t0)
            exact = exact and np.array_equal(scores, want)
        dag_ms, seq_ms = _percentiles(dag_s), _percentiles(seq_s)
        out["exactness"] = {"runs": args.runs, "bit_exact": bool(exact)}
        out["dag_vs_sequential"] = {
            "runs": args.runs,
            "dag_ms": dag_ms,
            "sequential_ms": seq_ms,
            "dag_p50_ms": dag_ms["p50"],
            "sequential_p50_ms": seq_ms["p50"],
            "speedup_p50": round(seq_ms["p50"] / dag_ms["p50"], 3),
        }
        print("exactness:", json.dumps(out["exactness"]))
        print("dag_vs_sequential:", json.dumps(out["dag_vs_sequential"]))

        # -- steady state: 0 region creates / registration RPCs ----------
        arena = client.arena()
        before = arena.stats()
        plan_matches = True
        t0 = time.perf_counter()
        for _ in range(args.runs):
            res = client.run({"RAW": raw})
            plan_matches = plan_matches and (
                res.arena_high_water_bytes == res.plan_high_water_bytes)
        elapsed = time.perf_counter() - t0
        after = arena.stats()
        stage_ms = {name: row["avg_ms"] for name, row
                    in client.stats()["stages"].items()}
        out["steady_state"] = {
            "runs": args.runs,
            "region_creates_per_run": (
                after["regions_created"] - before["regions_created"])
            / args.runs,
            "registration_rpcs_per_run": (
                after["registrations_issued"]
                - before["registrations_issued"]) / args.runs,
            "leaked_lease_bytes": (after["leased_bytes"]
                                   - before["leased_bytes"]),
            "arena_hit_rate": after["hit_rate"],
            "high_water_matches_plan": bool(plan_matches),
            "plan_high_water_bytes": (
                client.plan().high_water_bytes),
            "stage_avg_ms": stage_ms,
            "runs_per_s": round(args.runs / elapsed, 1),
        }
        print("steady_state:", json.dumps(out["steady_state"]))
    finally:
        seq.close()
        client.close()
        srv.stop()

    # -- chaos: pinned stage endpoint RST mid-run (own stack) ------------
    out["chaos"] = run_chaos_arm(runs=args.chaos_runs)
    print("chaos:", json.dumps(out["chaos"]))

    Path(args.output).write_text(json.dumps(out, indent=2) + "\n")
    print(f"wrote {args.output}")
    return check(args.output)


if __name__ == "__main__":
    sys.exit(main())
