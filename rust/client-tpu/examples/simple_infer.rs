//! The reference's simple example (src/rust/triton-client/examples) in this
//! crate's idiom: health checks, metadata, one `simple` model inference.
//!
//! Run (once a cargo toolchain is available):
//!   cargo run --example simple_infer -- http://127.0.0.1:8001

use client_tpu::{Client, DataType, InferInput, InferRequestBuilder};

#[tokio::main]
async fn main() -> Result<(), client_tpu::Error> {
    let url = std::env::args()
        .nth(1)
        .unwrap_or_else(|| "http://127.0.0.1:8001".to_string());
    let client = Client::connect(&url).await?;

    assert!(client.is_server_live().await?);
    assert!(client.is_server_ready().await?);
    let metadata = client.server_metadata().await?;
    println!("server: {} {}", metadata.name, metadata.version);

    let model = client.model_metadata("simple", "").await?;
    println!(
        "model 'simple': {} inputs, {} outputs",
        model.inputs.len(),
        model.outputs.len()
    );

    let ones = [1i32; 16];
    let request = InferRequestBuilder::new("simple")
        .input(
            InferInput::new("INPUT0", vec![1, 16], DataType::Int32)
                .with_data_i32(&ones),
        )
        .input(
            InferInput::new("INPUT1", vec![1, 16], DataType::Int32)
                .with_data_i32(&ones),
        )
        .build();
    let response = client.infer(request).await?;
    let sum = response
        .output("OUTPUT0")
        .expect("OUTPUT0 missing")
        .as_i32()?;
    let diff = response
        .output("OUTPUT1")
        .expect("OUTPUT1 missing")
        .as_i32()?;
    println!("sum: {sum:?}");
    println!("diff: {diff:?}");
    assert!(sum.iter().all(|&v| v == 2) && diff.iter().all(|&v| v == 0));
    Ok(())
}
