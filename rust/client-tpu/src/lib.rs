//! # client-tpu
//!
//! Async Rust client for the client_tpu inference server (KServe v2 over
//! gRPC). Role parity with the reference Rust client
//! (`/root/reference/src/rust/triton-client`: `client.rs:178-704` surface,
//! `infer.rs` typed builders), re-designed for this framework: hand-framed
//! protobuf over the `h2` crate instead of tonic/prost codegen, and the
//! tpu shared-memory family in the CUDA one's seat.
//!
//! NOTE: source-complete but never compiled — this image has no cargo.
//! See README.md for the honesty note and design rationale.

pub mod client;
pub mod error;
pub mod messages;
pub mod pbwire;
pub mod types;

pub use client::{Client, ClientOptions};
pub use error::{Error, Result, StatusCode};
pub use messages::{
    InferResponse, ModelIndexEntry, ModelMetadata, ServerMetadata,
    TensorMetadata,
};
pub use types::{
    DataType, InferInput, InferRequest, InferRequestBuilder,
    InferRequestedOutput, OutputTensor, ParamValue,
};
