//! Typed errors (role parity: reference `error.rs` — 89 LoC of
//! thiserror-derived variants over tonic/prost causes; ours wrap h2/io and
//! carry gRPC status codes directly since there is no tonic layer).

use thiserror::Error;

/// gRPC status codes (the subset is the full canonical set — stable ABI).
#[derive(Debug, Clone, Copy, PartialEq, Eq)]
pub enum StatusCode {
    Ok = 0,
    Cancelled = 1,
    Unknown = 2,
    InvalidArgument = 3,
    DeadlineExceeded = 4,
    NotFound = 5,
    AlreadyExists = 6,
    PermissionDenied = 7,
    ResourceExhausted = 8,
    FailedPrecondition = 9,
    Aborted = 10,
    OutOfRange = 11,
    Unimplemented = 12,
    Internal = 13,
    Unavailable = 14,
    DataLoss = 15,
    Unauthenticated = 16,
}

impl StatusCode {
    pub fn from_i32(code: i32) -> Self {
        match code {
            0 => Self::Ok,
            1 => Self::Cancelled,
            3 => Self::InvalidArgument,
            4 => Self::DeadlineExceeded,
            5 => Self::NotFound,
            6 => Self::AlreadyExists,
            7 => Self::PermissionDenied,
            8 => Self::ResourceExhausted,
            9 => Self::FailedPrecondition,
            10 => Self::Aborted,
            11 => Self::OutOfRange,
            12 => Self::Unimplemented,
            13 => Self::Internal,
            14 => Self::Unavailable,
            15 => Self::DataLoss,
            16 => Self::Unauthenticated,
            _ => Self::Unknown,
        }
    }
}

#[derive(Debug, Error)]
pub enum Error {
    /// The server answered with a non-OK grpc-status.
    #[error("gRPC error {code:?}: {message}")]
    Grpc { code: StatusCode, message: String },

    /// HTTP/2 / socket level failure.
    #[error("transport error: {0}")]
    Transport(String),

    /// Malformed protobuf or gRPC framing in a response.
    #[error("malformed response: {0}")]
    Decode(String),

    /// Local misuse (bad shapes, missing output, oversized message).
    #[error("invalid argument: {0}")]
    InvalidArgument(String),

    /// The configured request timeout elapsed.
    #[error("deadline exceeded")]
    DeadlineExceeded,
}

impl From<h2::Error> for Error {
    fn from(e: h2::Error) -> Self {
        Error::Transport(e.to_string())
    }
}

impl From<std::io::Error> for Error {
    fn from(e: std::io::Error) -> Self {
        Error::Transport(e.to_string())
    }
}

pub type Result<T> = std::result::Result<T, Error>;
