//! KServe gRPC message encodings. Field numbers follow the public
//! `grpc_service.proto` (the same numbers `client_tpu/grpc/_messages.py`
//! carries and cross-validates against protoc, and
//! `native/src/grpc_client.cc` mirrors in C++).

use std::collections::BTreeMap;

use crate::error::{Error, Result};
use crate::pbwire::{Reader, Writer, WIRE_LEN, WIRE_VARINT};
use crate::types::{
    DataType, InferRequest, OutputTensor, ParamValue,
};

// ---------------------------------------------------------------------------
// parameter maps (InferParameter: bool=1, int64=2, string=3, double=4)
// ---------------------------------------------------------------------------

fn encode_param(value: &ParamValue) -> Vec<u8> {
    let mut w = Writer::new();
    // oneof members have explicit presence: emit even at the default
    // (false/0/""), or the entry decodes as "no parameter case set"
    match value {
        ParamValue::Bool(b) => w.bool_always(1, *b),
        ParamValue::Int(i) => w.int64_always(2, *i),
        ParamValue::Str(s) => w.string_always(3, s),
        ParamValue::Double(d) => w.fixed64(4, d.to_bits()),
    }
    w.finish().to_vec()
}

fn encode_param_map(w: &mut Writer, field: u32, params: &BTreeMap<String, ParamValue>) {
    for (key, value) in params {
        let mut entry = Writer::new();
        entry.string(1, key);
        entry.submessage(2, &encode_param(value));
        w.submessage(field, &entry.finish());
    }
}

// ---------------------------------------------------------------------------
// ModelInferRequest
// ---------------------------------------------------------------------------

/// ModelInferRequest: model_name=1, model_version=2, id=3, parameters=4,
/// inputs=5, outputs=6, raw_input_contents=7.
pub fn encode_infer_request(request: &InferRequest) -> Result<Vec<u8>> {
    let mut w = Writer::new();
    w.string(1, &request.model_name);
    w.string(2, &request.model_version);
    w.string(3, &request.request_id);

    let mut params = request.parameters.clone();
    if request.sequence_id != 0 {
        params.insert("sequence_id".into(), ParamValue::Int(request.sequence_id as i64));
        params.insert("sequence_start".into(), ParamValue::Bool(request.sequence_start));
        params.insert("sequence_end".into(), ParamValue::Bool(request.sequence_end));
    }
    if request.priority != 0 {
        params.insert("priority".into(), ParamValue::Int(request.priority as i64));
    }
    if request.timeout_us != 0 {
        params.insert("timeout".into(), ParamValue::Int(request.timeout_us as i64));
    }
    encode_param_map(&mut w, 4, &params);

    for input in &request.inputs {
        input.validate()?;
        // InferInputTensor: name=1, datatype=2, shape=3, parameters=4
        let mut t = Writer::new();
        t.string(1, &input.name);
        t.string(2, input.datatype.as_str());
        t.packed_int64(3, &input.shape);
        encode_param_map(&mut t, 4, &input.parameters);
        w.submessage(5, &t.finish());
    }
    for output in &request.outputs {
        // InferRequestedOutputTensor: name=1, parameters=2
        let mut t = Writer::new();
        t.string(1, &output.name);
        encode_param_map(&mut t, 2, &output.parameters);
        w.submessage(6, &t.finish());
    }
    // raw_input_contents, index-matched to non-shm inputs
    for input in &request.inputs {
        if !input.parameters.contains_key("shared_memory_region") {
            w.bytes_always(7, &input.raw);
        }
    }
    Ok(w.finish().to_vec())
}

// ---------------------------------------------------------------------------
// ModelInferResponse
// ---------------------------------------------------------------------------

/// Decoded response: model_name=1, model_version=2, id=3, outputs=5,
/// raw_output_contents=6.
#[derive(Debug, Default)]
pub struct InferResponse {
    pub model_name: String,
    pub model_version: String,
    pub id: String,
    pub outputs: Vec<OutputTensor>,
}

impl InferResponse {
    pub fn output(&self, name: &str) -> Option<&OutputTensor> {
        self.outputs.iter().find(|o| o.name == name)
    }
}

pub fn decode_infer_response(payload: &[u8]) -> Result<InferResponse> {
    let mut response = InferResponse::default();
    let mut raws: Vec<Vec<u8>> = Vec::new();
    let mut shm_flags: Vec<bool> = Vec::new();
    let mut r = Reader::new(payload);
    while let Some((field, wire_type)) = r.next()? {
        match field {
            1 => response.model_name = r.string()?,
            2 => response.model_version = r.string()?,
            3 => response.id = r.string()?,
            5 => {
                let raw = r.length_delimited()?;
                let mut t = Reader::new(raw);
                let mut name = String::new();
                let mut datatype = DataType::Bytes;
                let mut shape = Vec::new();
                let mut in_shm = false;
                while let Some((tf, twt)) = t.next()? {
                    match tf {
                        1 => name = t.string()?,
                        2 => {
                            let s = t.string()?;
                            datatype = DataType::parse(&s).ok_or_else(|| {
                                Error::Decode(format!("unknown datatype {s:?}"))
                            })?;
                        }
                        3 => t.repeated_int64(twt, &mut shape)?,
                        4 => {
                            // parameters map: a shared_memory_region key
                            // marks an shm-placed output (no raw entry)
                            let entry = t.length_delimited()?;
                            let mut e = Reader::new(entry);
                            while let Some((ef, ewt)) = e.next()? {
                                if ef == 1 {
                                    if e.string()? == "shared_memory_region" {
                                        in_shm = true;
                                    }
                                } else {
                                    e.skip(ewt)?;
                                }
                            }
                        }
                        _ => t.skip(twt)?,
                    }
                }
                response.outputs.push(OutputTensor {
                    name,
                    datatype,
                    shape,
                    raw: Vec::new(),
                });
                shm_flags.push(in_shm);
            }
            6 => raws.push(r.length_delimited()?.to_vec()),
            _ => r.skip(wire_type)?,
        }
    }
    // raw_output_contents is index-matched to NON-shm outputs only (the
    // same skip the Python client applies, grpc/_infer.py:226-236)
    let mut raw_iter = raws.into_iter();
    for (output, in_shm) in response.outputs.iter_mut().zip(shm_flags) {
        if !in_shm {
            if let Some(raw) = raw_iter.next() {
                output.raw = raw;
            }
        }
    }
    Ok(response)
}

// ---------------------------------------------------------------------------
// ModelStreamInferResponse (error_message=1, infer_response=2)
// ---------------------------------------------------------------------------

pub fn decode_stream_response(payload: &[u8]) -> Result<InferResponse> {
    let mut r = Reader::new(payload);
    let mut error_message = String::new();
    let mut inner: Option<InferResponse> = None;
    while let Some((field, wire_type)) = r.next()? {
        match field {
            1 => error_message = r.string()?,
            2 => inner = Some(decode_infer_response(r.length_delimited()?)?),
            _ => r.skip(wire_type)?,
        }
    }
    if !error_message.is_empty() {
        return Err(Error::Grpc {
            code: crate::error::StatusCode::Unknown,
            message: error_message,
        });
    }
    inner.ok_or_else(|| Error::Decode("stream response missing infer_response".into()))
}

// ---------------------------------------------------------------------------
// admin RPCs (requests encoded here; responses decoded into simple structs)
// ---------------------------------------------------------------------------

/// name=1 + version=2 request shell shared by several RPCs.
pub fn encode_name_version(name: &str, version: &str) -> Vec<u8> {
    let mut w = Writer::new();
    w.string(1, name);
    w.string(2, version);
    w.finish().to_vec()
}

/// Single-bool responses (ServerLive ready=1, ServerReady, ModelReady).
pub fn decode_bool_field1(payload: &[u8]) -> Result<bool> {
    let mut r = Reader::new(payload);
    let mut out = false;
    while let Some((field, wire_type)) = r.next()? {
        if field == 1 && wire_type == WIRE_VARINT {
            out = r.varint()? != 0;
        } else {
            r.skip(wire_type)?;
        }
    }
    Ok(out)
}

#[derive(Debug, Default)]
pub struct ServerMetadata {
    pub name: String,
    pub version: String,
    pub extensions: Vec<String>,
}

pub fn decode_server_metadata(payload: &[u8]) -> Result<ServerMetadata> {
    let mut r = Reader::new(payload);
    let mut out = ServerMetadata::default();
    while let Some((field, wire_type)) = r.next()? {
        match field {
            1 => out.name = r.string()?,
            2 => out.version = r.string()?,
            3 => out.extensions.push(r.string()?),
            _ => r.skip(wire_type)?,
        }
    }
    Ok(out)
}

#[derive(Debug, Default)]
pub struct TensorMetadata {
    pub name: String,
    pub datatype: String,
    pub shape: Vec<i64>,
}

#[derive(Debug, Default)]
pub struct ModelMetadata {
    pub name: String,
    pub versions: Vec<String>,
    pub platform: String,
    pub inputs: Vec<TensorMetadata>,
    pub outputs: Vec<TensorMetadata>,
}

fn decode_tensor_metadata(raw: &[u8]) -> Result<TensorMetadata> {
    let mut t = Reader::new(raw);
    let mut out = TensorMetadata::default();
    while let Some((field, wire_type)) = t.next()? {
        match field {
            1 => out.name = t.string()?,
            2 => out.datatype = t.string()?,
            3 => t.repeated_int64(wire_type, &mut out.shape)?,
            _ => t.skip(wire_type)?,
        }
    }
    Ok(out)
}

pub fn decode_model_metadata(payload: &[u8]) -> Result<ModelMetadata> {
    let mut r = Reader::new(payload);
    let mut out = ModelMetadata::default();
    while let Some((field, wire_type)) = r.next()? {
        match field {
            1 => out.name = r.string()?,
            2 => out.versions.push(r.string()?),
            3 => out.platform = r.string()?,
            4 => out.inputs.push(decode_tensor_metadata(r.length_delimited()?)?),
            5 => out.outputs.push(decode_tensor_metadata(r.length_delimited()?)?),
            _ => r.skip(wire_type)?,
        }
    }
    Ok(out)
}

#[derive(Debug, Default)]
pub struct ModelIndexEntry {
    pub name: String,
    pub version: String,
    pub state: String,
    pub reason: String,
}

/// RepositoryIndexResponse: models=1 { name=1, version=2, state=3, reason=4 }
pub fn decode_repository_index(payload: &[u8]) -> Result<Vec<ModelIndexEntry>> {
    let mut r = Reader::new(payload);
    let mut out = Vec::new();
    while let Some((field, wire_type)) = r.next()? {
        if field == 1 && wire_type == WIRE_LEN {
            let raw = r.length_delimited()?;
            let mut m = Reader::new(raw);
            let mut entry = ModelIndexEntry::default();
            while let Some((mf, mwt)) = m.next()? {
                match mf {
                    1 => entry.name = m.string()?,
                    2 => entry.version = m.string()?,
                    3 => entry.state = m.string()?,
                    4 => entry.reason = m.string()?,
                    _ => m.skip(mwt)?,
                }
            }
            out.push(entry);
        } else {
            r.skip(wire_type)?;
        }
    }
    Ok(out)
}

/// SystemSharedMemoryRegisterRequest: name=1, key=2, offset=3, byte_size=4.
pub fn encode_system_shm_register(
    name: &str, key: &str, offset: u64, byte_size: u64,
) -> Vec<u8> {
    let mut w = Writer::new();
    w.string(1, name);
    w.string(2, key);
    w.uint64(3, offset);
    w.uint64(4, byte_size);
    w.finish().to_vec()
}

/// TpuSharedMemoryRegisterRequest (this framework's device family; the
/// reference's CudaSharedMemoryRegisterRequest seat): name=1,
/// raw_handle=2 (b64 descriptor), device_id=3, byte_size=4.
pub fn encode_tpu_shm_register(
    name: &str, raw_handle_b64: &str, device_id: i64, byte_size: u64,
) -> Vec<u8> {
    let mut w = Writer::new();
    w.string(1, name);
    w.bytes(2, raw_handle_b64.as_bytes());
    w.int64(3, device_id);
    w.uint64(4, byte_size);
    w.finish().to_vec()
}

/// Single-name request shell (unregister, status filters, load/unload).
pub fn encode_name_only(name: &str) -> Vec<u8> {
    let mut w = Writer::new();
    w.string(1, name);
    w.finish().to_vec()
}
