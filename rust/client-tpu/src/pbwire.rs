//! Schema-driven protobuf wire codec + gRPC message framing.
//!
//! The Rust sibling of `client_tpu/grpc/_wire.py` (protoc-cross-validated,
//! hypothesis-fuzzed) and `native/include/client_tpu/pbwire.h`: varints,
//! the four wire types the KServe protocol uses, and the 5-byte gRPC
//! message frame (flag byte + big-endian u32 length).

use bytes::{Buf, BufMut, Bytes, BytesMut};

use crate::error::{Error, Result};

pub const WIRE_VARINT: u32 = 0;
pub const WIRE_I64: u32 = 1;
pub const WIRE_LEN: u32 = 2;
pub const WIRE_I32: u32 = 5;

// ---------------------------------------------------------------------------
// writer
// ---------------------------------------------------------------------------

#[derive(Default)]
pub struct Writer {
    buf: BytesMut,
}

impl Writer {
    pub fn new() -> Self {
        Self::default()
    }

    fn key(&mut self, field: u32, wire_type: u32) {
        self.varint(u64::from(field << 3 | wire_type));
    }

    pub fn varint(&mut self, mut v: u64) {
        loop {
            let byte = (v & 0x7f) as u8;
            v >>= 7;
            if v == 0 {
                self.buf.put_u8(byte);
                return;
            }
            self.buf.put_u8(byte | 0x80);
        }
    }

    pub fn uint64(&mut self, field: u32, v: u64) {
        if v != 0 {
            self.key(field, WIRE_VARINT);
            self.varint(v);
        }
    }

    pub fn int64(&mut self, field: u32, v: i64) {
        if v != 0 {
            self.key(field, WIRE_VARINT);
            self.varint(v as u64); // two's-complement, 10-byte form for negatives
        }
    }

    pub fn fixed64(&mut self, field: u32, v: u64) {
        self.key(field, WIRE_I64);
        self.buf.put_u64_le(v);
    }

    pub fn bool(&mut self, field: u32, v: bool) {
        if v {
            self.key(field, WIRE_VARINT);
            self.varint(1);
        }
    }

    /// Explicit-presence variants for oneof members (InferParameter):
    /// proto3 oneof fields serialize even at their default value, unlike
    /// ordinary singular fields — skipping a `false`/`0`/`""` here frames
    /// an EMPTY InferParameter, which a strict peer reads as "no case set"
    /// (caught by the golden wire vectors in tests/vectors/).
    pub fn bool_always(&mut self, field: u32, v: bool) {
        self.key(field, WIRE_VARINT);
        self.varint(u64::from(v));
    }

    pub fn int64_always(&mut self, field: u32, v: i64) {
        self.key(field, WIRE_VARINT);
        self.varint(v as u64);
    }

    pub fn string_always(&mut self, field: u32, v: &str) {
        self.bytes_always(field, v.as_bytes());
    }

    pub fn string(&mut self, field: u32, v: &str) {
        if !v.is_empty() {
            self.bytes(field, v.as_bytes());
        }
    }

    pub fn bytes(&mut self, field: u32, v: &[u8]) {
        self.key(field, WIRE_LEN);
        self.varint(v.len() as u64);
        self.buf.put_slice(v);
    }

    /// Length-delimited submessage from an already-encoded body. Unlike
    /// string/bytes this always emits, even empty (presence semantics).
    pub fn submessage(&mut self, field: u32, body: &[u8]) {
        self.bytes_always(field, body);
    }

    pub fn bytes_always(&mut self, field: u32, v: &[u8]) {
        self.key(field, WIRE_LEN);
        self.varint(v.len() as u64);
        self.buf.put_slice(v);
    }

    /// Packed repeated int64 (shape fields).
    pub fn packed_int64(&mut self, field: u32, values: &[i64]) {
        if values.is_empty() {
            return;
        }
        let mut inner = Writer::new();
        for v in values {
            inner.varint(*v as u64);
        }
        self.bytes_always(field, &inner.finish());
    }

    pub fn finish(self) -> Bytes {
        self.buf.freeze()
    }
}

// ---------------------------------------------------------------------------
// reader
// ---------------------------------------------------------------------------

pub struct Reader<'a> {
    data: &'a [u8],
    pos: usize,
}

impl<'a> Reader<'a> {
    pub fn new(data: &'a [u8]) -> Self {
        Self { data, pos: 0 }
    }

    pub fn done(&self) -> bool {
        self.pos >= self.data.len()
    }

    pub fn varint(&mut self) -> Result<u64> {
        let mut out: u64 = 0;
        let mut shift = 0u32;
        loop {
            if self.pos >= self.data.len() {
                return Err(Error::Decode("truncated varint".into()));
            }
            let byte = self.data[self.pos];
            self.pos += 1;
            if shift >= 64 {
                return Err(Error::Decode("varint overflow".into()));
            }
            out |= u64::from(byte & 0x7f) << shift;
            if byte & 0x80 == 0 {
                return Ok(out);
            }
            shift += 7;
        }
    }

    /// Next (field, wire_type); None at end of buffer.
    pub fn next(&mut self) -> Result<Option<(u32, u32)>> {
        if self.done() {
            return Ok(None);
        }
        let key = self.varint()?;
        Ok(Some(((key >> 3) as u32, (key & 0x7) as u32)))
    }

    pub fn length_delimited(&mut self) -> Result<&'a [u8]> {
        let len = self.varint()? as usize;
        // overflow-safe: `pos + len` with an untrusted len near usize::MAX
        // would wrap (release) or panic (debug); compare against remaining
        if len > self.data.len() - self.pos {
            return Err(Error::Decode("truncated length-delimited field".into()));
        }
        let out = &self.data[self.pos..self.pos + len];
        self.pos += len;
        Ok(out)
    }

    pub fn string(&mut self) -> Result<String> {
        let raw = self.length_delimited()?;
        String::from_utf8(raw.to_vec())
            .map_err(|_| Error::Decode("invalid utf-8 in string field".into()))
    }

    /// Packed or single repeated int64 (shape fields appear both ways).
    pub fn repeated_int64(&mut self, wire_type: u32, out: &mut Vec<i64>) -> Result<()> {
        if wire_type == WIRE_LEN {
            let raw = self.length_delimited()?;
            let mut inner = Reader::new(raw);
            while !inner.done() {
                out.push(inner.varint()? as i64);
            }
        } else {
            out.push(self.varint()? as i64);
        }
        Ok(())
    }

    pub fn skip(&mut self, wire_type: u32) -> Result<()> {
        match wire_type {
            WIRE_VARINT => {
                self.varint()?;
            }
            WIRE_I64 => {
                if self.data.len() - self.pos < 8 {
                    return Err(Error::Decode("truncated fixed64 field".into()));
                }
                self.pos += 8;
            }
            WIRE_LEN => {
                self.length_delimited()?;
            }
            WIRE_I32 => {
                if self.data.len() - self.pos < 4 {
                    return Err(Error::Decode("truncated fixed32 field".into()));
                }
                self.pos += 4;
            }
            other => {
                return Err(Error::Decode(format!("unknown wire type {other}")));
            }
        }
        Ok(())
    }
}

// ---------------------------------------------------------------------------
// gRPC message framing
// ---------------------------------------------------------------------------

/// 5-byte prefix: compressed flag (always 0 — this client does not
/// negotiate message compression) + big-endian u32 payload length.
pub fn frame_message(payload: &[u8]) -> Bytes {
    let mut out = BytesMut::with_capacity(5 + payload.len());
    out.put_u8(0);
    out.put_u32(payload.len() as u32);
    out.put_slice(payload);
    out.freeze()
}

/// Split one framed message off the front of `buf`; None until a complete
/// frame has accumulated. Errors on the compressed flag (unsupported here).
pub fn unframe_message(buf: &mut BytesMut) -> Result<Option<Bytes>> {
    if buf.len() < 5 {
        return Ok(None);
    }
    let compressed = buf[0] != 0;
    let len = u32::from_be_bytes([buf[1], buf[2], buf[3], buf[4]]) as usize;
    if buf.len() < 5 + len {
        return Ok(None);
    }
    if compressed {
        return Err(Error::Decode(
            "compressed gRPC message (compression not negotiated)".into(),
        ));
    }
    buf.advance(5);
    Ok(Some(buf.split_to(len).freeze()))
}
