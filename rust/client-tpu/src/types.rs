//! Typed tensor builders — role parity with the reference `infer.rs`
//! (`DataType` :136, `InferInput` builders :210-433, `InferRequestedOutput`
//! :478-520, `InferRequestBuilder` :548+), re-shaped for this framework:
//! one generic little-endian data path instead of 12 hand-unrolled copies,
//! and the tpu shared-memory family in place of CUDA.

use std::collections::BTreeMap;

use crate::error::{Error, Result};

/// KServe v2 datatypes.
#[derive(Debug, Clone, Copy, PartialEq, Eq)]
pub enum DataType {
    Bool,
    Uint8,
    Uint16,
    Uint32,
    Uint64,
    Int8,
    Int16,
    Int32,
    Int64,
    Fp16,
    Bf16,
    Fp32,
    Fp64,
    Bytes,
}

impl DataType {
    pub fn as_str(self) -> &'static str {
        match self {
            Self::Bool => "BOOL",
            Self::Uint8 => "UINT8",
            Self::Uint16 => "UINT16",
            Self::Uint32 => "UINT32",
            Self::Uint64 => "UINT64",
            Self::Int8 => "INT8",
            Self::Int16 => "INT16",
            Self::Int32 => "INT32",
            Self::Int64 => "INT64",
            Self::Fp16 => "FP16",
            Self::Bf16 => "BF16",
            Self::Fp32 => "FP32",
            Self::Fp64 => "FP64",
            Self::Bytes => "BYTES",
        }
    }

    pub fn parse(s: &str) -> Option<Self> {
        Some(match s {
            "BOOL" => Self::Bool,
            "UINT8" => Self::Uint8,
            "UINT16" => Self::Uint16,
            "UINT32" => Self::Uint32,
            "UINT64" => Self::Uint64,
            "INT8" => Self::Int8,
            "INT16" => Self::Int16,
            "INT32" => Self::Int32,
            "INT64" => Self::Int64,
            "FP16" => Self::Fp16,
            "BF16" => Self::Bf16,
            "FP32" => Self::Fp32,
            "FP64" => Self::Fp64,
            "BYTES" => Self::Bytes,
            _ => return None,
        })
    }

    /// Element width in bytes; None for BYTES (variable).
    pub fn itemsize(self) -> Option<usize> {
        Some(match self {
            Self::Bool | Self::Uint8 | Self::Int8 => 1,
            Self::Uint16 | Self::Int16 | Self::Fp16 | Self::Bf16 => 2,
            Self::Uint32 | Self::Int32 | Self::Fp32 => 4,
            Self::Uint64 | Self::Int64 | Self::Fp64 => 8,
            Self::Bytes => return None,
        })
    }
}

/// Anything with a fixed little-endian wire form. One generic data path
/// replaces the reference's twelve `with_data_*` bodies; the per-type
/// methods below remain as the public, discoverable surface.
pub trait LeBytes: Copy {
    fn put_le(self, out: &mut Vec<u8>);
}

macro_rules! le_bytes {
    ($($t:ty),*) => {$(
        impl LeBytes for $t {
            fn put_le(self, out: &mut Vec<u8>) {
                out.extend_from_slice(&self.to_le_bytes());
            }
        }
    )*};
}
le_bytes!(u8, i8, u16, i16, u32, i32, u64, i64, f32, f64);

impl LeBytes for bool {
    fn put_le(self, out: &mut Vec<u8>) {
        out.push(self as u8);
    }
}

/// A parameter value (request/input/output parameters maps).
#[derive(Debug, Clone)]
pub enum ParamValue {
    Bool(bool),
    Int(i64),
    Str(String),
    Double(f64),
}

/// One input tensor: name + shape + datatype + either inline raw bytes or
/// a shared-memory placement.
#[derive(Debug, Clone)]
pub struct InferInput {
    pub(crate) name: String,
    pub(crate) shape: Vec<i64>,
    pub(crate) datatype: DataType,
    pub(crate) raw: Vec<u8>,
    pub(crate) parameters: BTreeMap<String, ParamValue>,
}

impl InferInput {
    pub fn new(name: impl Into<String>, shape: Vec<i64>, datatype: DataType) -> Self {
        Self {
            name: name.into(),
            shape,
            datatype,
            raw: Vec::new(),
            parameters: BTreeMap::new(),
        }
    }

    /// Generic typed data (the engine under every `with_data_*`).
    pub fn with_data<T: LeBytes>(mut self, data: &[T]) -> Self {
        self.raw.clear();
        self.raw.reserve(data.len() * std::mem::size_of::<T>());
        for v in data {
            v.put_le(&mut self.raw);
        }
        self
    }

    pub fn with_data_bool(self, data: &[bool]) -> Self { self.with_data(data) }
    pub fn with_data_u8(self, data: &[u8]) -> Self { self.with_data(data) }
    pub fn with_data_i8(self, data: &[i8]) -> Self { self.with_data(data) }
    pub fn with_data_u16(self, data: &[u16]) -> Self { self.with_data(data) }
    pub fn with_data_i16(self, data: &[i16]) -> Self { self.with_data(data) }
    pub fn with_data_u32(self, data: &[u32]) -> Self { self.with_data(data) }
    pub fn with_data_i32(self, data: &[i32]) -> Self { self.with_data(data) }
    pub fn with_data_u64(self, data: &[u64]) -> Self { self.with_data(data) }
    pub fn with_data_i64(self, data: &[i64]) -> Self { self.with_data(data) }
    pub fn with_data_f32(self, data: &[f32]) -> Self { self.with_data(data) }
    pub fn with_data_f64(self, data: &[f64]) -> Self { self.with_data(data) }

    /// Pre-serialized little-endian bytes (FP16/BF16 producers).
    pub fn with_data_raw(mut self, data: Vec<u8>) -> Self {
        self.raw = data;
        self
    }

    /// BYTES elements: 4-byte little-endian length prefix per element (the
    /// Triton BYTES wire form, reference `infer.rs:373`).
    pub fn with_data_bytes(mut self, data: &[&[u8]]) -> Self {
        self.raw.clear();
        for elem in data {
            self.raw
                .extend_from_slice(&(elem.len() as u32).to_le_bytes());
            self.raw.extend_from_slice(elem);
        }
        self
    }

    /// Place this input in a registered shared-memory region instead of
    /// shipping bytes (system or tpu family; the region name selects it).
    pub fn with_shared_memory(
        mut self, region: impl Into<String>, byte_size: u64, offset: u64,
    ) -> Self {
        self.raw.clear();
        self.parameters.insert(
            "shared_memory_region".into(),
            ParamValue::Str(region.into()),
        );
        self.parameters.insert(
            "shared_memory_byte_size".into(),
            ParamValue::Int(byte_size as i64),
        );
        if offset != 0 {
            self.parameters.insert(
                "shared_memory_offset".into(),
                ParamValue::Int(offset as i64),
            );
        }
        self
    }

    pub fn with_string_parameter(
        mut self, key: impl Into<String>, value: impl Into<String>,
    ) -> Self {
        self.parameters.insert(key.into(), ParamValue::Str(value.into()));
        self
    }

    pub fn with_int_parameter(mut self, key: impl Into<String>, value: i64) -> Self {
        self.parameters.insert(key.into(), ParamValue::Int(value));
        self
    }

    pub fn with_bool_parameter(mut self, key: impl Into<String>, value: bool) -> Self {
        self.parameters.insert(key.into(), ParamValue::Bool(value));
        self
    }

    pub fn name(&self) -> &str { &self.name }
    pub fn shape(&self) -> &[i64] { &self.shape }
    pub fn datatype(&self) -> DataType { self.datatype }

    /// Validate raw size against shape*itemsize (BYTES skipped: variable).
    pub fn validate(&self) -> Result<()> {
        if self.parameters.contains_key("shared_memory_region") {
            return Ok(());
        }
        if let Some(itemsize) = self.datatype.itemsize() {
            let elems: i64 = self.shape.iter().product();
            let expected = elems.max(0) as usize * itemsize;
            if self.raw.len() != expected {
                return Err(Error::InvalidArgument(format!(
                    "input '{}': {} bytes provided, shape {:?} x {} needs {}",
                    self.name, self.raw.len(), self.shape, itemsize, expected,
                )));
            }
        }
        Ok(())
    }
}

/// A requested output: by name, optionally classification-k or placed in
/// shared memory.
#[derive(Debug, Clone, Default)]
pub struct InferRequestedOutput {
    pub(crate) name: String,
    pub(crate) parameters: BTreeMap<String, ParamValue>,
}

impl InferRequestedOutput {
    pub fn new(name: impl Into<String>) -> Self {
        Self { name: name.into(), parameters: BTreeMap::new() }
    }

    pub fn with_classification(mut self, k: i64) -> Self {
        self.parameters.insert("classification".into(), ParamValue::Int(k));
        self
    }

    pub fn with_shared_memory(
        mut self, region: impl Into<String>, byte_size: u64, offset: u64,
    ) -> Self {
        self.parameters.insert(
            "shared_memory_region".into(),
            ParamValue::Str(region.into()),
        );
        self.parameters.insert(
            "shared_memory_byte_size".into(),
            ParamValue::Int(byte_size as i64),
        );
        if offset != 0 {
            self.parameters.insert(
                "shared_memory_offset".into(),
                ParamValue::Int(offset as i64),
            );
        }
        self
    }

    pub fn with_string_parameter(
        mut self, key: impl Into<String>, value: impl Into<String>,
    ) -> Self {
        self.parameters.insert(key.into(), ParamValue::Str(value.into()));
        self
    }

    pub fn name(&self) -> &str { &self.name }
}

/// A fully-specified inference request (reference `InferRequestBuilder`).
#[derive(Debug, Clone, Default)]
pub struct InferRequest {
    pub(crate) model_name: String,
    pub(crate) model_version: String,
    pub(crate) request_id: String,
    pub(crate) inputs: Vec<InferInput>,
    pub(crate) outputs: Vec<InferRequestedOutput>,
    pub(crate) parameters: BTreeMap<String, ParamValue>,
    pub(crate) sequence_id: u64,
    pub(crate) sequence_start: bool,
    pub(crate) sequence_end: bool,
    pub(crate) priority: u64,
    pub(crate) timeout_us: u64,
}

pub struct InferRequestBuilder {
    request: InferRequest,
}

impl InferRequestBuilder {
    pub fn new(model_name: impl Into<String>) -> Self {
        Self {
            request: InferRequest {
                model_name: model_name.into(),
                ..Default::default()
            },
        }
    }

    pub fn model_version(mut self, version: impl Into<String>) -> Self {
        self.request.model_version = version.into();
        self
    }

    pub fn request_id(mut self, id: impl Into<String>) -> Self {
        self.request.request_id = id.into();
        self
    }

    pub fn input(mut self, input: InferInput) -> Self {
        self.request.inputs.push(input);
        self
    }

    pub fn output(mut self, output: InferRequestedOutput) -> Self {
        self.request.outputs.push(output);
        self
    }

    pub fn sequence(mut self, id: u64, start: bool, end: bool) -> Self {
        self.request.sequence_id = id;
        self.request.sequence_start = start;
        self.request.sequence_end = end;
        self
    }

    pub fn priority(mut self, priority: u64) -> Self {
        self.request.priority = priority;
        self
    }

    pub fn timeout_us(mut self, timeout_us: u64) -> Self {
        self.request.timeout_us = timeout_us;
        self
    }

    pub fn parameter(mut self, key: impl Into<String>, value: ParamValue) -> Self {
        self.request.parameters.insert(key.into(), value);
        self
    }

    pub fn build(self) -> InferRequest {
        self.request
    }
}

/// One decoded output tensor view.
#[derive(Debug, Clone)]
pub struct OutputTensor {
    pub name: String,
    pub datatype: DataType,
    pub shape: Vec<i64>,
    pub raw: Vec<u8>,
}

macro_rules! as_typed {
    ($fn_name:ident, $t:ty, $dt:pat) => {
        pub fn $fn_name(&self) -> Result<Vec<$t>> {
            match self.datatype {
                $dt => {}
                other => {
                    return Err(Error::InvalidArgument(format!(
                        "output '{}' is {:?}, not requested type",
                        self.name, other
                    )))
                }
            }
            const W: usize = std::mem::size_of::<$t>();
            if self.raw.len() % W != 0 {
                return Err(Error::Decode(format!(
                    "output '{}' byte length {} not a multiple of {}",
                    self.name, self.raw.len(), W
                )));
            }
            Ok(self
                .raw
                .chunks_exact(W)
                .map(|c| <$t>::from_le_bytes(c.try_into().unwrap()))
                .collect())
        }
    };
}

impl OutputTensor {
    as_typed!(as_i32, i32, DataType::Int32);
    as_typed!(as_i64, i64, DataType::Int64);
    as_typed!(as_u32, u32, DataType::Uint32);
    as_typed!(as_u64, u64, DataType::Uint64);
    as_typed!(as_f32, f32, DataType::Fp32);
    as_typed!(as_f64, f64, DataType::Fp64);

    pub fn as_raw(&self) -> &[u8] {
        &self.raw
    }

    /// BYTES elements (4-byte little-endian length prefixes).
    pub fn as_bytes(&self) -> Result<Vec<Vec<u8>>> {
        let mut out = Vec::new();
        let mut pos = 0usize;
        while pos + 4 <= self.raw.len() {
            let len = u32::from_le_bytes(self.raw[pos..pos + 4].try_into().unwrap()) as usize;
            pos += 4;
            if pos + len > self.raw.len() {
                return Err(Error::Decode(format!(
                    "output '{}': truncated BYTES element", self.name
                )));
            }
            out.push(self.raw[pos..pos + len].to_vec());
            pos += len;
        }
        Ok(out)
    }
}
