//! Async client over the `h2` crate: one multiplexed HTTP/2 connection,
//! every RPC a stream on it — the same model the C++ client's
//! completion-queue worker uses (native/src/grpc_client.cc AsyncTransfer)
//! and the role of the reference `TritonClient` (client.rs:178-704).

use std::collections::BTreeMap;
use std::sync::Arc;
use std::time::Duration;

use bytes::{Bytes, BytesMut};
use http::{Request, Uri};
use tokio::net::TcpStream;
use tokio::sync::{mpsc, Mutex};

use crate::error::{Error, Result, StatusCode};
use crate::messages::{
    decode_bool_field1, decode_infer_response, decode_model_metadata,
    decode_repository_index, decode_server_metadata, decode_stream_response,
    encode_infer_request, encode_name_only, encode_name_version,
    encode_system_shm_register, encode_tpu_shm_register, InferResponse,
    ModelIndexEntry, ModelMetadata, ServerMetadata,
};
use crate::pbwire::{frame_message, unframe_message};
use crate::types::InferRequest;

const SERVICE: &str = "/inference.GRPCInferenceService/";

/// Connection knobs (reference `ClientOptions`, client.rs:91-152).
#[derive(Debug, Clone)]
pub struct ClientOptions {
    pub connect_timeout: Duration,
    pub request_timeout: Option<Duration>,
    pub max_message_size: usize,
    pub keep_alive_interval: Option<Duration>,
    pub keep_alive_timeout: Duration,
}

impl Default for ClientOptions {
    fn default() -> Self {
        Self {
            connect_timeout: Duration::from_secs(10),
            request_timeout: None,
            max_message_size: (1 << 31) - 1,
            keep_alive_interval: None,
            keep_alive_timeout: Duration::from_secs(20),
        }
    }
}

impl ClientOptions {
    pub fn connect_timeout(mut self, timeout: Duration) -> Self {
        self.connect_timeout = timeout;
        self
    }
    pub fn request_timeout(mut self, timeout: Duration) -> Self {
        self.request_timeout = Some(timeout);
        self
    }
    pub fn max_message_size(mut self, size: usize) -> Self {
        self.max_message_size = size;
        self
    }
    pub fn keep_alive_interval(mut self, interval: Duration) -> Self {
        self.keep_alive_interval = Some(interval);
        self
    }
    pub fn keep_alive_timeout(mut self, timeout: Duration) -> Self {
        self.keep_alive_timeout = timeout;
        self
    }
}

/// Async KServe v2 gRPC client.
///
/// Cloning is cheap: clones share the underlying multiplexed connection
/// (h2's `SendRequest` is a handle), so concurrent `infer` calls from many
/// tasks ride one socket — in-flight concurrency is the transport's
/// stream multiplexing, not a connection pool.
#[derive(Clone)]
pub struct Client {
    send_request: Arc<Mutex<h2::client::SendRequest<Bytes>>>,
    authority: String,
    options: ClientOptions,
}

impl Client {
    pub async fn connect(url: &str) -> Result<Self> {
        Self::connect_with_options(url, ClientOptions::default()).await
    }

    pub async fn connect_with_options(url: &str, options: ClientOptions) -> Result<Self> {
        let authority = url
            .trim_start_matches("http://")
            .trim_start_matches("grpc://")
            .trim_end_matches('/')
            .to_string();
        if authority.is_empty() {
            return Err(Error::InvalidArgument("empty server url".into()));
        }
        let tcp = tokio::time::timeout(
            options.connect_timeout,
            TcpStream::connect(&authority),
        )
        .await
        .map_err(|_| Error::Transport(format!("connect to {authority} timed out")))??;
        tcp.set_nodelay(true)?;
        let (send_request, mut connection) = h2::client::Builder::new()
            .initial_window_size(1 << 24)
            .initial_connection_window_size(1 << 24)
            .max_frame_size(1 << 20)
            .handshake(tcp)
            .await?;
        // keep-alive: h2 PING on the configured interval; a ping that gets
        // no pong within keep_alive_timeout abandons the probe task (the
        // connection itself will surface the failure on the next RPC).
        if let Some(interval) = options.keep_alive_interval {
            if let Some(mut ping_pong) = connection.ping_pong() {
                let timeout = options.keep_alive_timeout;
                tokio::spawn(async move {
                    loop {
                        tokio::time::sleep(interval).await;
                        let probe = ping_pong.ping(h2::Ping::opaque());
                        match tokio::time::timeout(timeout, probe).await {
                            Ok(Ok(_pong)) => continue,
                            _ => return,  // dead peer or closed connection
                        }
                    }
                });
            }
        }
        // The connection task owns the socket; it ends when the client and
        // all in-flight streams drop.
        tokio::spawn(async move {
            let _ = connection.await;
        });
        Ok(Self {
            send_request: Arc::new(Mutex::new(send_request)),
            authority,
            options,
        })
    }

    // -- unary plumbing ----------------------------------------------------

    async fn unary(&self, method: &str, payload: Vec<u8>) -> Result<Bytes> {
        let call = self.unary_inner(method, payload);
        match self.options.request_timeout {
            Some(timeout) => tokio::time::timeout(timeout, call)
                .await
                .map_err(|_| Error::DeadlineExceeded)?,
            None => call.await,
        }
    }

    async fn unary_inner(&self, method: &str, payload: Vec<u8>) -> Result<Bytes> {
        let uri: Uri = format!("http://{}{}{}", self.authority, SERVICE, method)
            .parse()
            .map_err(|e| Error::Transport(format!("bad uri: {e}")))?;
        let request = Request::builder()
            .method("POST")
            .uri(uri)
            .header("content-type", "application/grpc")
            .header("te", "trailers")
            .body(())
            .map_err(|e| Error::Transport(e.to_string()))?;

        let (response_fut, mut send_stream) = {
            let mut sender = self.send_request.lock().await;
            // ready() waits for stream credit; the lock is held only for
            // stream creation, not the exchange — calls still overlap.
            futures_ready(&mut sender).await?;
            sender.send_request(request, false)?
        };
        send_stream.send_data(frame_message(&payload), true)?;

        let response = response_fut.await?;
        let grpc_status_header = decode_status(response.headers());
        let mut body = response.into_body();
        let mut buf = BytesMut::new();
        let mut flow = body.flow_control().clone();
        while let Some(chunk) = body.data().await {
            let chunk = chunk?;
            if buf.len() + chunk.len() > self.options.max_message_size {
                return Err(Error::Decode("response exceeds max_message_size".into()));
            }
            let _ = flow.release_capacity(chunk.len());
            buf.extend_from_slice(&chunk);
        }
        let trailers = body.trailers().await?;
        let status = trailers
            .as_ref()
            .map(|t| decode_status(t))
            .unwrap_or(grpc_status_header);
        if let Some((code, message)) = status {
            if code != StatusCode::Ok {
                return Err(Error::Grpc { code, message });
            }
        }
        match unframe_message(&mut buf)? {
            Some(message) => Ok(message),
            None if buf.is_empty() => Ok(Bytes::new()),
            None => Err(Error::Decode("truncated gRPC response frame".into())),
        }
    }

    // -- health / metadata (reference client.rs:243-406) --------------------

    pub async fn is_server_live(&self) -> Result<bool> {
        decode_bool_field1(&self.unary("ServerLive", Vec::new()).await?)
    }

    pub async fn is_server_ready(&self) -> Result<bool> {
        decode_bool_field1(&self.unary("ServerReady", Vec::new()).await?)
    }

    pub async fn is_model_ready(&self, model_name: &str, model_version: &str) -> Result<bool> {
        let payload = encode_name_version(model_name, model_version);
        decode_bool_field1(&self.unary("ModelReady", payload).await?)
    }

    pub async fn server_metadata(&self) -> Result<ServerMetadata> {
        decode_server_metadata(&self.unary("ServerMetadata", Vec::new()).await?)
    }

    pub async fn model_metadata(
        &self, model_name: &str, model_version: &str,
    ) -> Result<ModelMetadata> {
        let payload = encode_name_version(model_name, model_version);
        decode_model_metadata(&self.unary("ModelMetadata", payload).await?)
    }

    /// Raw ModelConfig response bytes (the config proto is large and
    /// backend-specific; callers that need fields decode with `pbwire`).
    pub async fn model_config(
        &self, model_name: &str, model_version: &str,
    ) -> Result<Bytes> {
        let payload = encode_name_version(model_name, model_version);
        self.unary("ModelConfig", payload).await
    }

    // -- inference (reference client.rs:407-458) ----------------------------

    pub async fn infer(&self, request: InferRequest) -> Result<InferResponse> {
        let payload = encode_infer_request(&request)?;
        decode_infer_response(&self.unary("ModelInfer", payload).await?)
    }

    /// Bi-di streaming: returns (request sender, response receiver). Each
    /// sent `InferRequest` yields one response (or a stream error) on the
    /// receiver, in server order. Dropping the sender half-closes the
    /// stream; the receiver then drains and ends.
    pub async fn infer_stream(
        &self,
    ) -> Result<(
        mpsc::Sender<InferRequest>,
        mpsc::Receiver<Result<InferResponse>>,
    )> {
        let uri: Uri = format!("http://{}{}ModelStreamInfer", self.authority, SERVICE)
            .parse()
            .map_err(|e| Error::Transport(format!("bad uri: {e}")))?;
        let request = Request::builder()
            .method("POST")
            .uri(uri)
            .header("content-type", "application/grpc")
            .header("te", "trailers")
            .body(())
            .map_err(|e| Error::Transport(e.to_string()))?;
        let (response_fut, mut send_stream) = {
            let mut sender = self.send_request.lock().await;
            futures_ready(&mut sender).await?;
            sender.send_request(request, false)?
        };

        let (req_tx, mut req_rx) = mpsc::channel::<InferRequest>(16);
        let (resp_tx, resp_rx) = mpsc::channel::<Result<InferResponse>>(16);

        // writer task: frame + send each request; half-close on sender drop.
        // Encode/validate failures are DELIVERED on the response channel
        // before the stream closes — a vanished request with a silently
        // ended receiver is indistinguishable from a server-side close.
        let resp_tx_writer = resp_tx.clone();
        tokio::spawn(async move {
            while let Some(request) = req_rx.recv().await {
                let payload = match encode_infer_request(&request) {
                    Ok(p) => p,
                    Err(e) => {
                        let _ = resp_tx_writer.send(Err(e)).await;
                        break;
                    }
                };
                if let Err(e) = send_stream.send_data(frame_message(&payload), false) {
                    let _ = resp_tx_writer.send(Err(e.into())).await;
                    break;
                }
            }
            let _ = send_stream.send_data(Bytes::new(), true);
        });

        // reader task: unframe + decode each response message
        let max_message_size = self.options.max_message_size;
        tokio::spawn(async move {
            let response = match response_fut.await {
                Ok(r) => r,
                Err(e) => {
                    let _ = resp_tx.send(Err(e.into())).await;
                    return;
                }
            };
            let mut body = response.into_body();
            let mut flow = body.flow_control().clone();
            let mut buf = BytesMut::new();
            while let Some(chunk) = body.data().await {
                let chunk = match chunk {
                    Ok(c) => c,
                    Err(e) => {
                        let _ = resp_tx.send(Err(e.into())).await;
                        return;
                    }
                };
                let _ = flow.release_capacity(chunk.len());
                if buf.len() + chunk.len() > max_message_size {
                    // the unary path enforces this cap; the stream must too
                    let _ = resp_tx
                        .send(Err(Error::Decode(
                            "stream response exceeds max_message_size".into(),
                        )))
                        .await;
                    return;
                }
                buf.extend_from_slice(&chunk);
                loop {
                    match unframe_message(&mut buf) {
                        Ok(Some(message)) => {
                            let _ = resp_tx
                                .send(decode_stream_response(&message))
                                .await;
                        }
                        Ok(None) => break,
                        Err(e) => {
                            let _ = resp_tx.send(Err(e)).await;
                            return;
                        }
                    }
                }
            }
            if let Ok(Some(trailers)) = body.trailers().await {
                if let Some((code, message)) = decode_status(&trailers) {
                    if code != StatusCode::Ok {
                        let _ = resp_tx.send(Err(Error::Grpc { code, message })).await;
                    }
                }
            }
        });

        Ok((req_tx, resp_rx))
    }

    // -- repository / statistics (reference client.rs:460-529) --------------

    pub async fn model_statistics(
        &self, model_name: &str, model_version: &str,
    ) -> Result<Bytes> {
        let payload = encode_name_version(model_name, model_version);
        self.unary("ModelStatistics", payload).await
    }

    pub async fn repository_index(&self) -> Result<Vec<ModelIndexEntry>> {
        decode_repository_index(&self.unary("RepositoryIndex", Vec::new()).await?)
    }

    pub async fn load_model(&self, model_name: &str) -> Result<()> {
        // RepositoryModelLoadRequest: repository_name=1 (unused), model_name=2
        let mut w = crate::pbwire::Writer::new();
        w.string(2, model_name);
        self.unary("RepositoryModelLoad", w.finish().to_vec()).await?;
        Ok(())
    }

    pub async fn unload_model(&self, model_name: &str) -> Result<()> {
        let mut w = crate::pbwire::Writer::new();
        w.string(2, model_name);
        self.unary("RepositoryModelUnload", w.finish().to_vec()).await?;
        Ok(())
    }

    // -- shared memory (tpu family in the reference's cuda seat) ------------

    pub async fn system_shared_memory_status(&self, name: &str) -> Result<Bytes> {
        self.unary("SystemSharedMemoryStatus", encode_name_only(name)).await
    }

    pub async fn system_shared_memory_register(
        &self, name: &str, key: &str, offset: u64, byte_size: u64,
    ) -> Result<()> {
        let payload = encode_system_shm_register(name, key, offset, byte_size);
        self.unary("SystemSharedMemoryRegister", payload).await?;
        Ok(())
    }

    pub async fn system_shared_memory_unregister(&self, name: &str) -> Result<()> {
        self.unary("SystemSharedMemoryUnregister", encode_name_only(name)).await?;
        Ok(())
    }

    pub async fn tpu_shared_memory_status(&self, name: &str) -> Result<Bytes> {
        self.unary("TpuSharedMemoryStatus", encode_name_only(name)).await
    }

    /// Register a tpu_shared_memory region by its base64 raw handle (the
    /// cudaIpcMemHandle seat; `client_tpu/utils/tpu_shared_memory`
    /// get_raw_handle produces these).
    pub async fn tpu_shared_memory_register(
        &self, name: &str, raw_handle_b64: &str, device_id: i64, byte_size: u64,
    ) -> Result<()> {
        let payload =
            encode_tpu_shm_register(name, raw_handle_b64, device_id, byte_size);
        self.unary("TpuSharedMemoryRegister", payload).await?;
        Ok(())
    }

    pub async fn tpu_shared_memory_unregister(&self, name: &str) -> Result<()> {
        self.unary("TpuSharedMemoryUnregister", encode_name_only(name)).await?;
        Ok(())
    }

    // cuda-named aliases (drop-in reference surface; the server aliases
    // CudaSharedMemory* onto the tpu family)
    pub async fn cuda_shared_memory_status(&self, name: &str) -> Result<Bytes> {
        self.unary("CudaSharedMemoryStatus", encode_name_only(name)).await
    }

    pub async fn cuda_shared_memory_unregister(&self, name: &str) -> Result<()> {
        self.unary("CudaSharedMemoryUnregister", encode_name_only(name)).await?;
        Ok(())
    }

    // -- trace / log settings (reference client.rs:668-704) -----------------

    pub async fn trace_setting(&self, model_name: &str) -> Result<Bytes> {
        // TraceSettingRequest: settings=1 (empty = read), model_name=2
        let mut w = crate::pbwire::Writer::new();
        w.string(2, model_name);
        self.unary("TraceSetting", w.finish().to_vec()).await
    }

    pub async fn log_settings(&self) -> Result<Bytes> {
        self.unary("LogSettings", Vec::new()).await
    }
}

/// grpc-status/grpc-message out of a header/trailer map.
fn decode_status(headers: &http::HeaderMap) -> Option<(StatusCode, String)> {
    let code = headers
        .get("grpc-status")?
        .to_str()
        .ok()?
        .parse::<i32>()
        .ok()?;
    let message = headers
        .get("grpc-message")
        .and_then(|v| v.to_str().ok())
        .map(percent_decode)
        .unwrap_or_default();
    Some((StatusCode::from_i32(code), message))
}

fn percent_decode(s: &str) -> String {
    let bytes = s.as_bytes();
    let mut out = Vec::with_capacity(bytes.len());
    let mut i = 0;
    while i < bytes.len() {
        if bytes[i] == b'%' && i + 2 < bytes.len() {
            if let Ok(v) = u8::from_str_radix(
                std::str::from_utf8(&bytes[i + 1..i + 3]).unwrap_or(""), 16,
            ) {
                out.push(v);
                i += 3;
                continue;
            }
        }
        out.push(bytes[i]);
        i += 1;
    }
    String::from_utf8_lossy(&out).into_owned()
}

/// SendRequest::ready() is a poll-style API; adapt to async/await.
async fn futures_ready(
    sender: &mut h2::client::SendRequest<Bytes>,
) -> Result<()> {
    std::future::poll_fn(|cx| sender.poll_ready(cx))
        .await
        .map_err(Error::from)
}

/// Unused but kept for API completeness with the reference's parameter
/// plumbing: BTreeMap is the canonical parameter container here.
pub type Parameters = BTreeMap<String, crate::types::ParamValue>;
