"""Compatibility re-export of :mod:`client_tpu.grpc.auth`."""

from client_tpu.grpc.auth import BasicAuth, InferenceServerClientPlugin  # noqa: F401
