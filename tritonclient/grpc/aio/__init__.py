"""Compatibility re-export of :mod:`client_tpu.grpc.aio`."""

from client_tpu.grpc.aio import *  # noqa: F401,F403
from client_tpu.grpc.aio import InferenceServerClient  # noqa: F401
