"""Compatibility re-export of :mod:`client_tpu.grpc.aio.auth`."""

from client_tpu.grpc.aio.auth import BasicAuth, InferenceServerClientPlugin  # noqa: F401
