"""Compatibility re-export of :mod:`client_tpu.grpc`."""

from client_tpu.grpc import *  # noqa: F401,F403
from client_tpu.grpc import (  # noqa: F401
    CallContext,
    InferInput,
    InferRequestedOutput,
    InferResult,
    InferenceServerClient,
    InferenceServerException,
    KeepAliveOptions,
)
