"""tritonclient compatibility namespace.

Drop-in import paths for code written against the reference
``tritonclient`` wheel: the submodules re-export this framework's
implementations (``client_tpu``), so

    import tritonclient.http as httpclient
    import tritonclient.grpc as grpcclient
    from tritonclient.utils import np_to_triton_dtype, InferenceServerException
    import tritonclient.utils.shared_memory as shm
    import tritonclient.utils.tpu_shared_memory as tpushm

work unchanged. ``tritonclient.utils.cuda_shared_memory`` raises with a
pointer at the TPU data plane (there is no CUDA on this stack).
"""
