"""Compatibility re-export of :mod:`client_tpu.http.aio.auth`."""

from client_tpu.http.aio.auth import BasicAuth, InferenceServerClientPlugin  # noqa: F401
