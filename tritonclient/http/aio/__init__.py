"""Compatibility re-export of :mod:`client_tpu.http.aio`."""

from client_tpu.http.aio import *  # noqa: F401,F403
from client_tpu.http.aio import InferenceServerClient  # noqa: F401
