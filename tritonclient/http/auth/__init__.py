"""Compatibility re-export of :mod:`client_tpu.http.auth`."""

from client_tpu.http.auth import BasicAuth, InferenceServerClientPlugin  # noqa: F401
