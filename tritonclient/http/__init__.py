"""Compatibility re-export of :mod:`client_tpu.http`."""

from client_tpu.http import *  # noqa: F401,F403
from client_tpu.http import (  # noqa: F401
    InferAsyncRequest,
    InferInput,
    InferRequestedOutput,
    InferResult,
    InferenceServerClient,
    InferenceServerException,
)
