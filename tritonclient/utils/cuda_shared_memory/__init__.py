"""There is no CUDA on this stack — use tpu_shared_memory.

Importing this module is the one reference surface that cannot be satisfied
on a TPU VM; it fails loudly with migration guidance instead of silently
degrading.
"""

raise ImportError(
    "tritonclient.utils.cuda_shared_memory is unavailable on the TPU stack: "
    "there is no CUDA here. Use tritonclient.utils.tpu_shared_memory — the "
    "API mirrors cuda_shared_memory function-for-function "
    "(create_shared_memory_region/get_raw_handle/set_shared_memory_region"
    "[_from_dlpack]/get_contents_as_numpy/destroy_shared_memory_region), with "
    "jax.Array bindings replacing device pointers."
)
