"""System shared-memory module tests (serverless; reference tier-1 mirror:
src/python/library/tests/test_shared_memory.py:34-170)."""

import numpy as np
import pytest

import client_tpu.utils.shared_memory as shm
from client_tpu.utils.shared_memory import SharedMemoryException


@pytest.fixture
def region():
    h = shm.create_shared_memory_region("test_region", "/cltpu_test_0", 256)
    yield h
    shm.destroy_shared_memory_region(h)


def test_lifecycle(region):
    assert region.name == "test_region"
    assert region.byte_size == 256
    assert "test_region" in shm.mapped_shared_memory_regions()


def test_set_and_get_roundtrip(region):
    arr = np.arange(16, dtype=np.int32)
    shm.set_shared_memory_region(region, [arr])
    out = shm.get_contents_as_numpy(region, np.int32, [16])
    np.testing.assert_array_equal(out, arr)


def test_two_tensors_with_offsets(region):
    a = np.arange(8, dtype=np.float32)
    b = np.arange(8, 16, dtype=np.float32)
    shm.set_shared_memory_region(region, [a])
    shm.set_shared_memory_region(region, [b], offset=32)
    np.testing.assert_array_equal(shm.get_contents_as_numpy(region, np.float32, [8]), a)
    np.testing.assert_array_equal(
        shm.get_contents_as_numpy(region, np.float32, [8], offset=32), b
    )


def test_oversize_write_raises(region):
    with pytest.raises(SharedMemoryException):
        shm.set_shared_memory_region(region, [np.zeros(1024, dtype=np.int64)])


def test_create_only_duplicate_raises(region):
    with pytest.raises(SharedMemoryException):
        shm.create_shared_memory_region("dup", "/cltpu_test_0", 256, create_only=True)


def test_attach_shares_memory(region):
    second = shm.create_shared_memory_region("attached", "/cltpu_test_0", 256)
    try:
        shm.set_shared_memory_region(region, [np.array([42], dtype=np.int32)])
        out = shm.get_contents_as_numpy(second, np.int32, [1])
        assert out[0] == 42
    finally:
        shm.destroy_shared_memory_region(second)


def test_bytes_roundtrip(region):
    arr = np.array([b"ab", b"", b"hello world"], dtype=np.object_)
    shm.set_shared_memory_region(region, [arr])
    out = shm.get_contents_as_numpy(region, "BYTES", [3])
    assert out.tolist() == arr.tolist()


def test_zero_copy_view(region):
    shm.set_shared_memory_region(region, [np.zeros(4, dtype=np.int32)])
    view = shm.get_contents_as_numpy(region, np.int32, [4])
    region.buf()[0:4] = (7).to_bytes(4, "little")
    assert view[0] == 7  # the view aliases the region


def test_invalid_byte_size():
    with pytest.raises(SharedMemoryException):
        shm.create_shared_memory_region("bad", "/cltpu_bad", 0)
