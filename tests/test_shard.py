"""Sharded scatter-gather serving: layout/gather units + end-to-end proof.

Proves the ISSUE acceptance criteria: (a) exact scatter/gather round-trips
on sync AND aio frontends — a logical request split across replica-pinned
endpoints returns BIT-identical results to the single-server reference,
including the ``decoder_lm_tp_prefill`` fleet against a local
single-process reference model; (b) axis-coverage/overlap validation and
gather exactness asserts raise typed errors; (c) a killed replica fails
the WHOLE logical request with a typed ``ShardFailed`` naming the shard
and endpoint — no partial results, no silent retry (each shard's endpoint
is called exactly once); (d) scatter/gather ride the shm arena zero-copy
fast path with 0 region creates and 0 registration RPCs per steady-state
request, and gather views are lease-pinned; (e) admission charges ONE
token per logical request; (f) hedging/coalescing/sequences are rejected
typed; (g) the logical span decomposes into shard_scatter / per-shard
attempt / shard_gather phases; (h) the ``sharded`` trace kind replays
end-to-end and stays forward-compatible (v2 records, v1 skip rule).
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu import trace as trace_mod
from client_tpu._base import InferenceServerClientBase
from client_tpu.admission import AdmissionController
from client_tpu.models import default_model_zoo
from client_tpu.models.decoder_prefill import PrefillDecoderModel
from client_tpu.observe import REQUEST_PHASES, Telemetry
from client_tpu.pool import HedgePolicy, PoolClient
from client_tpu.server import HttpInferenceServer, ServerCore
from client_tpu.shard import (
    AioShardedClient,
    ShardAxis,
    ShardConfigError,
    ShardFailed,
    ShardGatherError,
    ShardLayout,
    ShardLayoutError,
    ShardedClient,
    ShardedInferResult,
)
from client_tpu.testing import ChaosProxy, Fault
from client_tpu.utils import np_to_triton_dtype


# -- helpers ------------------------------------------------------------------
def _matmul_input(x, mod=httpclient):
    return mod.InferInput("X", list(x.shape), "FP32").set_data_from_numpy(x)


class FakeResult:
    """A minimal InferResult stand-in for gather units/stub endpoints."""

    def __init__(self, outputs):
        self._outputs = {k: np.asarray(v) for k, v in outputs.items()}

    def get_output(self, name):
        arr = self._outputs.get(name)
        if arr is None:
            return None
        return {"name": name, "datatype": np_to_triton_dtype(arr.dtype),
                "shape": list(arr.shape)}

    def get_response(self):
        return {"model_name": "fake",
                "outputs": [self.get_output(n) for n in self._outputs]}

    def as_numpy(self, name):
        arr = self._outputs.get(name)
        return None if arr is None else arr


class ShardStub(InferenceServerClientBase):
    """A scriptable shard endpoint: echoes the received X slice as Y (so
    gather exactness is checkable) unless ``behavior`` overrides."""

    def __init__(self, url, behavior=None):
        super().__init__()
        self.url = url
        self.behavior = behavior
        self.calls = []

    def infer(self, model_name, inputs=None, **kwargs):
        self.calls.append({"model": model_name, "kwargs": dict(kwargs),
                           "inputs": list(inputs or ())})
        op = self.behavior or self._echo

        def run():
            return op(inputs, **kwargs)

        if self._resilience is not None:
            return self._resilience.execute(run, idempotent=True)
        return run()

    def _echo(self, inputs, **kwargs):
        from client_tpu.shard import _input_array

        # echo the X slice back as Y (gather exactness is checkable);
        # other inputs ride along for call inspection but are not outputs
        out = {"Y" if inp.name() == "X" else inp.name():
               _input_array(inp)
               for inp in inputs if inp.name() == "X"}
        return FakeResult(out)

    def is_server_ready(self, probe=False, **kw):
        return True

    def close(self):
        pass


def _stub_sharded(behaviors, layout=None, **pool_kwargs):
    urls = list(behaviors)
    stubs = {}

    def factory(url):
        stubs[url] = ShardStub(url, behaviors[url])
        return stubs[url]

    pool_kwargs.setdefault("health_interval_s", None)
    pool = PoolClient(urls, client_factory=factory, **pool_kwargs)
    layout = layout or ShardLayout(urls, inputs={"X": 0}, outputs={"Y": 0})
    return ShardedClient(pool, layout), stubs


@pytest.fixture()
def shard_replicas():
    cores = [ServerCore(default_model_zoo()) for _ in range(2)]
    servers = [HttpInferenceServer(c).start() for c in cores]
    proxies = [ChaosProxy("127.0.0.1", s.port).start() for s in servers]
    yield servers, proxies
    for p in proxies:
        p.stop()
    for s in servers:
        s.stop()


# -- layout validation (typed) ------------------------------------------------
def test_layout_validation_typed_errors():
    with pytest.raises(ShardLayoutError):
        ShardLayout([], inputs={"X": 0}, outputs={"Y": 0})
    with pytest.raises(ShardLayoutError, match="distinct"):
        ShardLayout(["a", "a"], inputs={"X": 0}, outputs={"Y": 0})
    with pytest.raises(ShardLayoutError, match="replicated"):
        ShardLayout(["a", "b"], inputs={"X": None}, outputs={"Y": 0})
    with pytest.raises(ShardLayoutError, match="axis"):
        ShardLayout(["a", "b"], inputs={"X": "bogus"}, outputs={"Y": 0})
    with pytest.raises(ShardLayoutError):
        ShardAxis(-1)
    layout = ShardLayout.parse("X=0,W=r->Y=0,S=r", ["a", "b"])
    assert layout.inputs["X"].axis == 0
    assert layout.inputs["W"] is None
    assert layout.outputs["S"] is None
    assert layout.describe()["inputs"] == {"X": 0, "W": "replicated"}
    with pytest.raises(ShardLayoutError, match="inputs->outputs"):
        ShardLayout.parse("X=0", ["a", "b"])
    with pytest.raises(ShardLayoutError, match="not an int"):
        ShardLayout.parse("X=zero->Y=0", ["a", "b"])


def test_shard_axis_coverage_and_overlap_validation():
    ok = ShardAxis(0, ranges=[(0, 3), (3, 8)])
    assert ok.resolve("X", 8, 2) == [(0, 3), (3, 8)]
    with pytest.raises(ShardLayoutError, match="overlaps"):
        ShardAxis(0, ranges=[(0, 5), (4, 8)]).resolve("X", 8, 2)
    with pytest.raises(ShardLayoutError, match="uncovered"):
        ShardAxis(0, ranges=[(0, 3), (5, 8)]).resolve("X", 8, 2)
    with pytest.raises(ShardLayoutError, match="length"):
        ShardAxis(0, ranges=[(0, 3), (3, 6)]).resolve("X", 8, 2)
    with pytest.raises(ShardLayoutError, match="ranges"):
        ShardAxis(0, ranges=[(0, 8)]).resolve("X", 8, 2)
    with pytest.raises(ShardLayoutError, match="empty"):
        ShardAxis(0, ranges=[(0, 0), (0, 8)]).resolve("X", 8, 2)
    # auto split: near-equal contiguous blocks covering the whole axis
    assert ShardAxis(0).resolve("X", 8, 3) == [(0, 3), (3, 6), (6, 8)]
    with pytest.raises(ShardLayoutError, match="at least one"):
        ShardAxis(0).resolve("X", 1, 2)


# -- gather exactness asserts (typed) -----------------------------------------
def _gather(layout, shard_outputs):
    return ShardedInferResult(
        layout, [FakeResult(o) for o in shard_outputs])


def test_gather_exactness_asserts():
    layout = ShardLayout(["a", "b"], inputs={"X": 0},
                         outputs={"Y": 0, "S": None})
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    s = np.array([7], dtype=np.int32)
    res = _gather(layout, [{"Y": a, "S": s}, {"Y": a + 6, "S": s}])
    np.testing.assert_array_equal(
        res.as_numpy("Y"), np.concatenate([a, a + 6]))
    np.testing.assert_array_equal(res.as_numpy("S"), s)
    assert res.get_output("Y")["shape"] == [4, 3]
    assert res.get_response()["shards"] == 2
    # missing output on one shard
    with pytest.raises(ShardGatherError, match="missing from shard 1"):
        _gather(layout, [{"Y": a, "S": s}, {"S": s}])
    # dtype disagreement
    with pytest.raises(ShardGatherError, match="dtype"):
        _gather(layout, [{"Y": a, "S": s},
                         {"Y": a.astype(np.float64), "S": s}])
    # non-gather dim disagreement
    with pytest.raises(ShardGatherError, match="non-gather"):
        _gather(layout, [{"Y": a, "S": s},
                         {"Y": np.zeros((2, 4), np.float32), "S": s}])
    # undeclared output in the response
    with pytest.raises(ShardGatherError, match="does not declare"):
        _gather(layout, [{"Y": a, "S": s, "EXTRA": s},
                         {"Y": a, "S": s, "EXTRA": s}])
    # ... including when only a NON-zero shard carries it (one
    # misconfigured replica must not hide behind shard 0)
    with pytest.raises(ShardGatherError, match="does not declare"):
        _gather(layout, [{"Y": a, "S": s},
                         {"Y": a, "S": s, "EXTRA": s}])
    # replicated output content disagreement (bit-level)
    bad = _gather(layout, [{"Y": a, "S": s},
                           {"Y": a, "S": np.array([8], np.int32)}])
    with pytest.raises(ShardGatherError, match="bit-for-bit"):
        bad.as_numpy("S")


# -- composition rejections (typed) -------------------------------------------
def test_sharded_composition_rejections():
    layout = ShardLayout(["u1", "u2"], inputs={"X": 0}, outputs={"Y": 0})
    hedged = PoolClient(["u1", "u2"],
                        client_factory=lambda u: ShardStub(u),
                        health_interval_s=None, hedge=HedgePolicy())
    with pytest.raises(ShardConfigError, match="hedg"):
        ShardedClient(hedged, layout)
    hedged.close()

    client, _ = _stub_sharded({"u1": None, "u2": None}, layout)
    with pytest.raises(ShardConfigError, match="coalesc"):
        client.coalescing()
    with pytest.raises(ShardConfigError, match="sequence"):
        client.infer("m", [_matmul_input(np.zeros((4, 2), np.float32))],
                     sequence_id=9)
    with pytest.raises(ShardConfigError, match="stream"):
        client.generate_stream("m", {})
    coalescing = client.inner.coalescing()
    with pytest.raises(ShardConfigError, match="coalescing"):
        ShardedClient(coalescing, layout)
    client.close()

    with pytest.raises(ShardConfigError, match="pins endpoints"):
        pool = PoolClient(["u1"], client_factory=lambda u: ShardStub(u),
                          health_interval_s=None)
        try:
            ShardedClient(pool, layout)
        finally:
            pool.close()


def test_request_layout_mismatch_typed():
    layout = ShardLayout(["u1", "u2"], inputs={"X": 0, "W": 1},
                         outputs={"Y": 0})
    client, _ = _stub_sharded({"u1": None, "u2": None}, layout)
    x = np.zeros((4, 2), np.float32)
    # undeclared request input
    with pytest.raises(ShardLayoutError, match="not declared"):
        client.infer("m", [
            _matmul_input(x),
            httpclient.InferInput("Z", [4, 2],
                                  "FP32").set_data_from_numpy(x),
            httpclient.InferInput("W", [4, 2],
                                  "FP32").set_data_from_numpy(x)])
    # layout input missing from the request
    with pytest.raises(ShardLayoutError, match="missing from the request"):
        client.infer("m", [_matmul_input(x)])
    # axis out of range for the real tensor
    with pytest.raises(ShardLayoutError, match="out of range"):
        bad = ShardLayout(["u1", "u2"], inputs={"X": 3}, outputs={"Y": 0})
        ShardedClient(client.inner, bad).infer("m", [_matmul_input(x)])
    client.close()


# -- failure semantics: typed ShardFailed, no silent retry --------------------
def test_shard_failed_is_whole_request_no_silent_retry():
    boom = ConnectionResetError("replica died")

    def fail(inputs, **kw):
        raise boom

    client, stubs = _stub_sharded({"u1": None, "u2": fail})
    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    with pytest.raises(ShardFailed) as excinfo:
        client.infer("m", [_matmul_input(x)])
    err = excinfo.value
    assert err.shard == 1 and err.url == "u2"
    assert err.cause is boom
    assert "u2" in str(err) and "shard 1" in str(err)
    # NO silent partial retry: the dead shard was attempted exactly once,
    # and the healthy shard was NOT re-driven
    assert len(stubs["u2"].calls) == 1
    assert len(stubs["u1"].calls) == 1
    client.close()


def test_replicated_input_reaches_every_shard_once():
    from client_tpu.shard import _input_array

    layout = ShardLayout(["u1", "u2"], inputs={"X": 0, "W": None},
                         outputs={"Y": 0})
    client, stubs = _stub_sharded({"u1": None, "u2": None}, layout)
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    w = np.arange(4, dtype=np.float32)
    res = client.infer("m", [
        _matmul_input(x),
        httpclient.InferInput("W", [4], "FP32").set_data_from_numpy(w)])
    np.testing.assert_array_equal(res.as_numpy("Y"), x)
    for i, url in enumerate(("u1", "u2")):
        (call,) = stubs[url].calls
        got = {inp.name(): _input_array(inp) for inp in call["inputs"]}
        np.testing.assert_array_equal(got["W"], w)  # full copy per shard
        np.testing.assert_array_equal(got["X"], x[3 * i: 3 * (i + 1)])
    client.close()


def test_admission_charges_one_token_per_logical_request():
    tel = Telemetry(sample="always")
    ctrl = AdmissionController()
    client, _ = _stub_sharded({"u1": None, "u2": None},
                              telemetry=tel, admission=ctrl)
    x = np.zeros((4, 2), np.float32)
    for _ in range(3):
        client.infer("m", [_matmul_input(x)])
    # one admission token per LOGICAL request, not per shard
    assert ctrl.admitted_total == 3
    tel.flush()
    fanned = sum(
        s.value for s in tel.shard_subrequests_total._series.values())
    assert fanned == 6  # 2 shards x 3 logical requests
    client.close()


def test_logical_span_decomposes_scatter_attempt_gather():
    assert "shard_scatter" in REQUEST_PHASES
    assert "shard_gather" in REQUEST_PHASES
    tel = Telemetry(sample="always")
    client, _ = _stub_sharded({"u1": None, "u2": None}, telemetry=tel)
    x = np.zeros((4, 2), np.float32)
    client.infer("m", [_matmul_input(x)])
    tel.flush()
    spans = [t for t in tel.tracer.recent()
             if t.get("op") == "shard_infer"]
    assert spans, "no logical shard span retained"
    phases = [p["name"] for p in spans[-1]["phases"]]
    assert phases.count("attempt") == 2  # one sub-span per shard
    assert "shard_scatter" in phases and "shard_gather" in phases
    breakdown = tel.phase_breakdown()
    assert "shard_scatter" in breakdown and "shard_gather" in breakdown
    assert spans[-1]["frontend"].startswith("shard+")
    reqs = sum(s.value for s in tel.shard_requests_total._series.values())
    assert reqs == 1
    client.close()


# -- end-to-end: exact scatter/gather round-trips -----------------------------
def test_scatter_gather_bit_exact_sync_http(shard_replicas):
    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    layout = ShardLayout(urls, inputs={"X": 0}, outputs={"Y": 0})
    rng = np.random.default_rng(0xC11E)
    x = rng.standard_normal((7, 64)).astype(np.float32)  # uneven: 4 + 3
    with ShardedClient(urls, layout,
                       health_interval_s=None) as client, \
            httpclient.InferenceServerClient(urls[0]) as ref:
        res = client.infer("batched_matmul", [_matmul_input(x)])
        want = ref.infer("batched_matmul",
                         [_matmul_input(x)]).as_numpy("Y")
        got = res.as_numpy("Y")
        assert got.shape == (7, 16)
        np.testing.assert_array_equal(got, want)  # BIT-exact
        res.release()


def test_scatter_gather_bit_exact_aio_http(shard_replicas):
    import client_tpu.http.aio as aioclient

    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    layout = ShardLayout(urls, inputs={"X": 0}, outputs={"Y": 0})
    rng = np.random.default_rng(0xA10)
    x = rng.standard_normal((8, 64)).astype(np.float32)

    async def run():
        client = AioShardedClient(urls, layout, health_interval_s=None)
        try:
            res = await client.infer(
                "batched_matmul",
                [aioclient.InferInput("X", [8, 64],
                                      "FP32").set_data_from_numpy(x)])
            out = res.as_numpy("Y").copy()
            res.release()  # the gather lease came from the default arena
            return out
        finally:
            await client.close()

    got = asyncio.run(run())
    with httpclient.InferenceServerClient(urls[0]) as ref:
        want = ref.infer("batched_matmul",
                         [_matmul_input(x)]).as_numpy("Y")
    np.testing.assert_array_equal(got, want)


@pytest.mark.shard_smoke
def test_sharded_decoder_tp_bit_exact_vs_reference(shard_replicas):
    """The headline exactness criterion: a batch of prompts scattered
    across N ``decoder_lm_tp_prefill`` replicas and gathered must equal
    the single-process reference model's full-batch logits, bit for
    bit (the TP step is bit-equal to the single-device decoder, and
    batch rows are independent — the gather must preserve both)."""
    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    layout = ShardLayout(urls, inputs={"TOKENS": 0},
                         outputs={"LOGITS": 0, "NEXT_TOKEN": 0})
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 256, size=(4, 8), dtype=np.int32)
    reference = PrefillDecoderModel(tp=False).execute(
        {"TOKENS": tokens}, {})
    with ShardedClient(urls, layout, health_interval_s=None) as client:
        res = client.infer("decoder_lm_tp_prefill", [
            httpclient.InferInput("TOKENS", [4, 8],
                                  "INT32").set_data_from_numpy(tokens)])
        np.testing.assert_array_equal(
            res.as_numpy("LOGITS"), reference["LOGITS"])
        np.testing.assert_array_equal(
            res.as_numpy("NEXT_TOKEN"), reference["NEXT_TOKEN"])
        res.release()  # the gather leases came from the default arena


@pytest.mark.shard_smoke
@pytest.mark.chaos_smoke
def test_killed_shard_fails_fast_no_partial_gather(shard_replicas):
    """Chaos: one pinned replica RSTs mid-run. Every affected logical
    request must raise the typed ShardFailed naming the dead endpoint;
    every success must stay bit-exact (zero partial gathers); after the
    replica heals, logical requests succeed again."""
    servers, proxies = shard_replicas
    urls = [p.url for p in proxies]
    layout = ShardLayout(urls, inputs={"X": 0}, outputs={"Y": 0})
    tel = Telemetry(sample="always")
    pool = PoolClient(urls, protocol="http", health_interval_s=None,
                      telemetry=tel)
    rng = np.random.default_rng(5)
    x = rng.standard_normal((8, 64)).astype(np.float32)
    with httpclient.InferenceServerClient(
            f"127.0.0.1:{servers[0].port}") as ref:
        want = ref.infer("batched_matmul",
                         [_matmul_input(x)]).as_numpy("Y")
    client = ShardedClient(pool, layout)
    try:
        outcomes = {"ok": 0, "shard_failed": 0}
        for i in range(30):
            if i == 10:
                proxies[1].fault = Fault("reset", after_bytes=0)
                proxies[1].reset_active()
            if i == 20:
                proxies[1].heal()
                time.sleep(0.2)
            try:
                res = client.infer("batched_matmul", [_matmul_input(x)],
                                   client_timeout=10.0)
            except ShardFailed as e:
                outcomes["shard_failed"] += 1
                assert e.url == urls[1], e  # names the dead endpoint
                assert e.shard == 1
            else:
                # ZERO partial gathers: every success is the full,
                # bit-exact logical answer
                np.testing.assert_array_equal(res.as_numpy("Y"), want)
                outcomes["ok"] += 1
            time.sleep(0.01)
        assert outcomes["shard_failed"] > 0, outcomes
        assert outcomes["ok"] >= 10, outcomes
        tel.flush()
        failed = sum(
            s.value for s in tel.shard_failed_total._series.values())
        assert failed == outcomes["shard_failed"]
    finally:
        client.close()


# -- arena fast path: zero-copy + steady-state amortization -------------------
def test_arena_scatter_gather_zero_copy_steady_state(shard_replicas):
    from client_tpu.arena import ShmArena

    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    layout = ShardLayout(urls, inputs={"X": 0}, outputs={"Y": 0})
    arena = ShmArena(name_prefix="shard_t")
    pool = PoolClient(urls, protocol="http", health_interval_s=None,
                      shm_arena=arena)
    client = ShardedClient(pool, layout)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 64)).astype(np.float32)
    try:
        warm = client.infer("batched_matmul", [_matmul_input(x)])
        # zero-copy gather: repeated reads serve the SAME lease-pinned
        # view over the arena slab
        a = warm.as_numpy("Y")
        b = warm.as_numpy("Y")
        assert a is b
        assert warm._gather_leases, "gather did not lease from the arena"
        lease = warm._gather_leases[0]
        assert np.shares_memory(
            a, np.frombuffer(lease.memoryview(), dtype=np.uint8))
        warm.release()
        # steady state: N more logical requests create ZERO new regions
        # and issue ZERO registration RPCs (slabs + registrations cached)
        before = arena.stats()
        for _ in range(10):
            res = client.infer("batched_matmul", [_matmul_input(x)])
            res.as_numpy("Y")
            res.release()
        after = arena.stats()
        assert after["regions_created"] == before["regions_created"]
        assert (after["registrations_issued"]
                == before["registrations_issued"])
        assert after["leased_bytes"] == 0  # no lease leaks
    finally:
        client.close()


# -- trace format + replay ----------------------------------------------------
def test_sharded_trace_records_version_and_roundtrip():
    records = trace_mod.sharded(seed=2, duration_s=2.0, rate=5.0, shards=2,
                                model="batched_matmul",
                                shapes={"X": [8, 64]}, dtypes={"X": "FP32"})
    assert records and all(r.kind == "sharded" for r in records)
    text = trace_mod.dumps_trace(records)
    # header stays at the BASE version so v1 readers keep the trace's
    # v1-compatible records; sharded records stamp their own v=2
    head = text.splitlines()[0]
    assert '"version":1' in head
    assert '"v":2' in text.splitlines()[1]
    loaded = trace_mod.loads_trace(text)
    assert loaded.skipped == 0
    assert loaded.kind_counts()["sharded"] == len(records)
    assert loaded.records[0].shards == 2
    # the v1 skip rule: records newer than THIS parser skip, not fail
    newer = text.replace('"v":2', f'"v":{trace_mod.TRACE_VERSION + 1}')
    skipped = trace_mod.loads_trace(newer)
    assert skipped.skipped == len(records)
    assert skipped.kind_counts()["sharded"] == 0
    # mixed generator: shard_fraction=0 stays byte-identical (the rng
    # draw count is unchanged), nonzero emits sharded records
    base = trace_mod.dumps_trace(trace_mod.mixed(seed=7, duration_s=2.0))
    again = trace_mod.dumps_trace(
        trace_mod.mixed(seed=7, duration_s=2.0, shard_fraction=0.0))
    assert base == again
    sharded_mix = trace_mod.mixed(seed=7, duration_s=2.0,
                                  shard_fraction=0.4)
    assert any(r.kind == "sharded" for r in sharded_mix)


@pytest.mark.shard_smoke
def test_sharded_trace_replay_e2e(shard_replicas):
    from client_tpu.perf import PerfRunner

    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    records = [
        trace_mod.TraceRecord(at_s=i * 0.03, kind="sharded",
                              model="batched_matmul",
                              shapes={"X": [8, 64]}, dtypes={"X": "FP32"},
                              shards=2)
        for i in range(20)
    ]
    runner = PerfRunner(urls[0], "http", "batched_matmul", endpoints=urls,
                        shape_overrides={"X": [8, 64]},
                        shard_layout="X=0->Y=0")
    try:
        row = runner.run_trace(trace_mod.Trace(header={}, records=records),
                               replay_workers=8,
                               slos=["error_rate<1%"])
    finally:
        runner.close()
    assert row["kinds"]["sharded"]["ok"] == 20
    assert row["errors"] == 0 and row["shed"] == 0
    assert row["slo_ok"], row["slo"]


def test_replay_sharded_records_require_layout(shard_replicas):
    from client_tpu.perf import PerfRunner

    servers, _ = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    rec = trace_mod.TraceRecord(at_s=0.0, kind="sharded",
                                model="batched_matmul",
                                shapes={"X": [8, 64]},
                                dtypes={"X": "FP32"}, shards=2)
    runner = PerfRunner(urls[0], "http", "batched_matmul", endpoints=urls)
    try:
        with pytest.raises(ValueError, match="shard-layout"):
            runner.run_trace(trace_mod.Trace(header={}, records=[rec]))
    finally:
        runner.close()


# -- doctor: shard topology + degraded anomaly --------------------------------
def test_doctor_shard_section_and_degraded_anomaly(shard_replicas):
    from client_tpu.doctor import collect_snapshot

    servers, proxies = shard_replicas
    urls = [f"127.0.0.1:{s.port}" for s in servers]
    snap = collect_snapshot(urls, requests_per_endpoint=2,
                            model="batched_matmul",
                            shard_layout="X=0->Y=0")
    assert snap["shard"]["layout"]["shards"] == 2
    assert [r["shard"] for r in snap["shard"]["shards"]] == [0, 1]
    assert all(r["ready"] for r in snap["shard"]["shards"])
    assert not any(f["flag"] == "shard_degraded"
                   for f in snap["anomalies"])
    servers[1].stop()
    snap = collect_snapshot(urls, requests_per_endpoint=2,
                            model="batched_matmul",
                            shard_layout="X=0->Y=0",
                            probe_timeout_s=3.0)
    degraded = [f for f in snap["anomalies"]
                if f["flag"] == "shard_degraded"]
    assert degraded and degraded[0]["url"] == urls[1]
    assert "zero failover headroom" in degraded[0]["detail"]


# -- committed artifact invariants -------------------------------------------
def test_bench_shard_artifact_claims():
    """BENCH_SHARD.json is the committed proof for the acceptance
    criteria: scatter-gather over N decoder_tp replicas is bit-exact vs
    the single-process reference, steady-state sharded infers issue 0
    region-create and 0 registration RPCs per request, and the chaos arm
    shows a killed shard producing typed ShardFailed on 100% of affected
    logical requests with zero partial gathers."""
    import json
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "BENCH_SHARD.json"
    data = json.loads(path.read_text())
    assert data["exactness"]["bit_exact"] is True
    assert data["exactness"]["requests"] > 0
    steady = data["steady_state"]
    assert steady["requests"] > 0
    assert steady["region_creates_per_request"] == 0
    assert steady["registration_rpcs_per_request"] == 0
    chaos = data["chaos"]
    assert chaos["affected_requests"] > 0
    assert chaos["shard_failed_typed"] == chaos["affected_requests"]
    assert chaos["partial_gathers"] == 0
    assert chaos["failed_shard_named"] is True
