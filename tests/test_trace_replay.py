"""Trace-driven workload replay & capacity harness tests (ISSUE 8).

Covers the versioned JSONL trace format (round-trip, malformed-record
rejection with line numbers, forward-compat version skip), the seeded
generators' determinism contract (same seed + same spec => byte-identical
trace), the new ``request_ms`` SLO metric and SLO spec parsing, the
schedule-slip reporting on open-loop rows, the capacity bisection / gate
comparison logic, and the mixed-kind replay smoke against the in-repo
threaded server (``replay_smoke`` marker, run by tools/chaos_smoke.sh).
"""

import io
import json
import time

import numpy as np
import pytest

from client_tpu import trace
from client_tpu.models import default_model_zoo
from client_tpu.observe import SLO, Telemetry, parse_slo_spec
from client_tpu.perf import PerfRunner
from client_tpu.server import HttpInferenceServer, ServerCore

from tools.bench_capacity import bisect_capacity, sustainable
from tools.capacity_gate import compare as gate_compare
from tools.capacity_gate import probe_at_floor, shortened_trace

MIXED_SPEC = ("mixed:duration_s=3,rate=30,stream_fraction=0.2,"
              "seq_fraction=0.15,output_mean=4,max_output=6")


# -- format: round-trip --------------------------------------------------------
def test_trace_round_trip_equal():
    tr = trace.generate(MIXED_SPEC, seed=5)
    assert tr.records, "generator produced an empty trace"
    buf = io.StringIO()
    trace.dump_trace(tr.records, buf, header=tr.header)
    loaded = trace.loads_trace(buf.getvalue())
    assert loaded.records == tr.records
    assert loaded.skipped == 0
    assert loaded.header["spec"] == MIXED_SPEC
    assert loaded.header["seed"] == 5
    assert loaded.header["records"] == len(tr.records)


def test_trace_round_trip_via_file(tmp_path):
    tr = trace.generate("poisson_burst:duration_s=2,rate=40", seed=1)
    path = tmp_path / "t.jsonl"
    trace.dump_trace(tr.records, str(path), header=tr.header)
    loaded = trace.load_trace(str(path))
    assert loaded.records == tr.records
    assert loaded.duration_s == 2


def test_trace_records_sorted_and_kinds_counted():
    tr = trace.generate(MIXED_SPEC, seed=9)
    offsets = [r.at_s for r in tr.records]
    assert offsets == sorted(offsets)
    counts = tr.kind_counts()
    assert counts["unary"] > 0 and counts["generate_stream"] > 0 \
        and counts["sequence"] > 0
    assert sum(counts.values()) == len(tr.records)
    # sequence groups are complete and ordered
    by_group = {}
    for r in tr.records:
        if r.kind == "sequence":
            by_group.setdefault(r.seq_group, []).append(r)
    for group, steps in by_group.items():
        assert [s.seq_index for s in steps] == list(range(steps[0].seq_len))


# -- format: determinism (satellite) ------------------------------------------
def test_trace_generation_deterministic_byte_identical():
    a = trace.generate(MIXED_SPEC, seed=42)
    b = trace.generate(MIXED_SPEC, seed=42)
    text_a = trace.dumps_trace(a.records, a.header)
    text_b = trace.dumps_trace(b.records, b.header)
    assert text_a.encode() == text_b.encode()
    c = trace.generate(MIXED_SPEC, seed=43)
    assert trace.dumps_trace(c.records, c.header) != text_a


# -- format: malformed rejection with line numbers ----------------------------
def _valid_lines():
    tr = trace.generate("poisson_burst:duration_s=1,rate=20", seed=0)
    return trace.dumps_trace(tr.records, tr.header).splitlines()


def test_trace_malformed_json_line_number():
    lines = _valid_lines()
    lines[2] = "{not json"
    with pytest.raises(trace.TraceParseError) as exc:
        trace.loads_trace("\n".join(lines))
    assert exc.value.line == 3
    assert "line 3" in str(exc.value)


@pytest.mark.parametrize("mutation, message", [
    (lambda o: o.pop("at_s"), "at_s"),
    (lambda o: o.update(at_s=-1.0), "at_s"),
    (lambda o: o.update(kind="nope"), "kind"),
    (lambda o: o.pop("model"), "model"),
])
def test_trace_bad_record_fields_rejected(mutation, message):
    lines = _valid_lines()
    obj = json.loads(lines[1])
    mutation(obj)
    lines[1] = json.dumps(obj)
    with pytest.raises(trace.TraceParseError) as exc:
        trace.loads_trace("\n".join(lines))
    assert exc.value.line == 2
    assert message in str(exc.value)


def test_trace_unary_requires_shapes():
    bad = json.dumps({
        "type": "request", "at_s": 0.1, "kind": "unary", "model": "simple"})
    with pytest.raises(trace.TraceParseError, match="line 1.*shapes"):
        trace.loads_trace(bad)


def test_trace_stream_and_sequence_field_validation():
    bad_stream = json.dumps({
        "type": "request", "at_s": 0.1, "kind": "generate_stream",
        "model": "m"})
    with pytest.raises(trace.TraceParseError, match="line 1.*prompt_tokens"):
        trace.loads_trace(bad_stream)
    bad_seq = json.dumps({
        "type": "request", "at_s": 0.1, "kind": "sequence", "model": "m",
        "seq_group": 1, "seq_index": 5, "seq_len": 3,
        "shapes": {"INPUT": [1, 1]}, "dtypes": {"INPUT": "INT32"}})
    with pytest.raises(trace.TraceParseError, match="seq_index"):
        trace.loads_trace(bad_seq)


# -- format: forward-compat version skip --------------------------------------
def test_trace_newer_version_records_skipped_not_fatal():
    lines = _valid_lines()
    total = len(lines) - 1  # minus header
    # a single record from a newer format: unknown semantics, skip it
    newer = {"type": "request", "v": trace.TRACE_VERSION + 1,
             "kind": "teleport", "model": "m", "at_s": 0.5,
             "wormhole": True}
    lines.insert(2, json.dumps(newer))
    # an unknown record TYPE rides the same rule
    lines.append(json.dumps({"type": "annotation", "note": "hi"}))
    loaded = trace.loads_trace("\n".join(lines))
    assert loaded.skipped == 2
    assert len(loaded.records) == total


def test_trace_whole_file_from_newer_format_skips_all():
    text = "\n".join([
        json.dumps({"type": "header", "version": trace.TRACE_VERSION + 7}),
        json.dumps({"type": "request", "kind": "quantum", "at_s": 0.0}),
        json.dumps({"type": "request", "kind": "unary", "model": "m",
                    "at_s": 0.1}),
    ])
    loaded = trace.loads_trace(text)
    # every record inherits the newer header version -> all skipped
    assert loaded.records == [] and loaded.skipped == 2


# -- generators ---------------------------------------------------------------
def test_poisson_burst_modulation_and_bounds():
    recs = trace.poisson_burst(seed=3, duration_s=10.0, rate=100.0,
                               burst_factor=5.0, period_s=2.0, duty=0.2)
    assert all(0.0 <= r.at_s < 10.0 for r in recs)
    # on-phase (first 20% of each period) must be several times denser
    # than the off-phase: count arrivals per phase bucket
    on = sum(1 for r in recs if (r.at_s % 2.0) / 2.0 < 0.2)
    off = len(recs) - on
    assert on > off, f"burst did not dominate: on={on} off={off}"
    # long-run mean stays near the declared rate
    assert 0.6 * 100.0 * 10.0 < len(recs) < 1.4 * 100.0 * 10.0


@pytest.mark.parametrize("tail", ["lognormal", "pareto"])
def test_heavy_tail_lengths_clipped_and_spread(tail):
    recs = trace.heavy_tail(seed=4, duration_s=20.0, rate=20.0, tail=tail,
                            max_prompt=96, max_output=32)
    prompts = [r.prompt_tokens for r in recs]
    assert all(1 <= p <= 96 for p in prompts)
    assert all(1 <= r.output_tokens <= 32 for r in recs)
    assert len(set(prompts)) > 5, "no spread in prompt lengths"


def test_generator_spec_parsing_and_errors():
    name, params = trace.parse_gen_spec(
        "mixed:duration_s=5,rate=40,tail=pareto,unary_model=simple")
    assert name == "mixed" and params["duration_s"] == 5
    assert params["tail"] == "pareto" and params["unary_model"] == "simple"
    with pytest.raises(ValueError, match="unknown trace generator"):
        trace.parse_gen_spec("nope:duration_s=5")
    with pytest.raises(ValueError, match="key=value"):
        trace.parse_gen_spec("mixed:duration_s")
    with pytest.raises(ValueError, match="bad params"):
        trace.generate("mixed:bogus_param=1")


# -- SLO spec parsing + request_ms metric -------------------------------------
def test_parse_slo_spec_matrix():
    spec = parse_slo_spec("ttft_p95<200ms")
    assert (spec.kind, spec.metric, spec.threshold_ms, spec.objective) == \
        ("latency", "ttft_ms", 200.0, 0.95)
    spec = parse_slo_spec("p99<50ms")
    assert (spec.metric, spec.objective) == ("request_ms", 0.99)
    spec = parse_slo_spec("latency_p999<1s")
    assert (spec.metric, spec.threshold_ms, spec.objective) == \
        ("request_ms", 1000.0, 0.999)
    spec = parse_slo_spec("error_rate<0.1%")
    assert (spec.kind, spec.limit) == ("error_rate", 0.001)
    assert parse_slo_spec("error_rate<0.005").limit == 0.005
    for bad in ("nope", "p<50ms", "latency<5ms", "error_rate<20ms",
                "error_rate<150%", "ttft_p95<5%", "foo_p95<5ms", "p00<1ms",
                # p100 would misparse to objective 0.10 — must be rejected,
                # not silently certify a 10%-good "SLO"
                "p100<50ms", "p05<50ms"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


def test_request_ms_slo_fed_from_unary_spans():
    tel = Telemetry()
    slo = tel.track_slo("lat_p90", "request_ms", threshold_ms=50.0,
                        objective=0.9)
    for _ in range(8):
        span = tel.begin("http", "m")
        tel.finish(span)  # ~instant: good
    slow = tel.begin("http", "m")
    slow.start_ns -= int(80e6)  # 80 ms ago
    tel.finish(slow)
    err = tel.begin("http", "m")
    tel.finish(err, error=RuntimeError("boom"))  # errors always count bad
    rows = tel.slo_report()
    assert rows[0]["good"] == 8 and rows[0]["bad"] == 2
    assert rows[0]["events"] == 10
    assert rows[0]["attained"] is False  # 20% bad > 10% budget
    # stream-metric SLOs are untouched by unary spans
    ttft = tel.track_slo("ttft", "ttft_ms", threshold_ms=100.0)
    span = tel.begin("http", "m")
    tel.finish(span)
    assert tel.slo_report()[1]["events"] == 0
    assert ttft.report()["events"] == 0


def test_slo_report_unbound_uses_window():
    slo = SLO("x", "request_ms", threshold_ms=10.0, objective=0.5)
    slo.observe(5.0)
    slo.observe(50.0)
    slo.observe_failure()
    row = slo.report()
    assert row["good"] == 1 and row["bad"] == 2 and row["attained"] is False


# -- open-loop schedule slip (satellite) --------------------------------------
def test_open_loop_rows_report_offered_vs_achieved_and_max_lag():
    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(server.url, "http", "simple")
        try:
            row = runner.run_rate(50.0, 60, distribution="poisson",
                                  pool_size=8)
        finally:
            runner.close()
    assert row["offered_rate"] == 50.0
    assert row["achieved_arrival_rate"] > 0.0
    lag = row["schedule_lag_ms"]
    assert lag["max"] >= lag["p99"] >= lag["p50"] >= 0.0
    assert row["issued"] == 60


def test_open_loop_poisson_schedule_seed_deterministic():
    r1 = PerfRunner.__new__(PerfRunner)
    r1.rng = np.random.default_rng(7)
    r2 = PerfRunner.__new__(PerfRunner)
    r2.rng = np.random.default_rng(7)
    gaps1 = r1.rng.exponential(1.0 / 25.0, size=64)
    gaps2 = r2.rng.exponential(1.0 / 25.0, size=64)
    assert np.array_equal(gaps1, gaps2)


# -- capacity bisection / gate logic ------------------------------------------
def test_bisect_capacity_finds_boundary():
    probes = []

    def evaluate(speed):
        probes.append(speed)
        return speed <= 3.0, {"speed": speed, "slo_ok": speed <= 3.0}

    best, rows = bisect_capacity(evaluate, 1.0, 8.0, iters=8)
    assert abs(best - 3.0) < 0.1
    assert len(rows) == len(probes)
    assert all(r["slo_ok"] == (r["speed"] <= 3.0) for r in rows)


def test_bisect_capacity_edges():
    best, rows = bisect_capacity(
        lambda s: (False, {"speed": s}), 1.0, 8.0, iters=4)
    assert best == 0.0 and len(rows) == 1  # lo already fails: stop early
    best, rows = bisect_capacity(
        lambda s: (True, {"speed": s}), 1.0, 8.0, iters=4)
    assert best == 8.0 and len(rows) == 2  # hi passes: nothing to bisect


def test_sustainable_requires_delivery_not_just_latency():
    """Past saturation the replay self-throttles: request latency stays
    flattering while the schedule slips. A probe that could not ISSUE the
    offered arrival schedule on time must NOT count as sustainable,
    whatever its latency SLOs say — and the metric is the arrival rate,
    not the completion rate (whose elapsed includes the drain tail)."""
    ok = {"slo_ok": True, "offered_rate": 100.0,
          "achieved_arrival_rate": 99.0}
    assert sustainable(ok) is True
    under = {"slo_ok": True, "offered_rate": 700.0,
             "achieved_arrival_rate": 300.0}
    assert sustainable(under) is False
    missed = {"slo_ok": False, "offered_rate": 100.0,
              "achieved_arrival_rate": 100.0}
    assert sustainable(missed) is False


def test_capacity_gate_compare_tolerance():
    ok = gate_compare(100.0, 90.0, tolerance=0.15)
    assert ok["regressed"] is False
    bad = gate_compare(100.0, 84.0, tolerance=0.15)
    assert bad["regressed"] is True and bad["floor_qps"] == 85.0
    # improvements never fail; a zero committed baseline can't regress
    assert gate_compare(100.0, 140.0)["regressed"] is False
    assert gate_compare(0.0, 0.0)["regressed"] is False


def test_capacity_gate_zero_committed_capacity_never_regresses():
    doc = {"arms": {"baseline": {"max_speed": 0.0,
                                 "max_sustainable_qps": 0.0}}}
    res = probe_at_floor(doc, "baseline", tolerance=0.15, duration_s=1.0,
                         replay_workers=4, attempts=2)
    assert res["regressed"] is False and res["attempts"] == []


def test_capacity_gate_shortened_trace_same_shape():
    doc = {"trace": {"spec": MIXED_SPEC, "seed": 5}}
    short = shortened_trace(doc, 1.5)
    assert short.header["seed"] == 5
    assert short.header["spec"] == MIXED_SPEC
    assert short.duration_s == 1.5
    # same workload shape at a shorter duration: every kind the spec
    # mixes still present (sharded stays 0 — the spec requests none),
    # arrivals inside the window (sequence tails may spill past it), and
    # re-generation is deterministic
    assert all(short.kind_counts()[k] > 0
               for k in ("unary", "generate_stream", "sequence"))
    assert all(r.at_s < 1.5 for r in short.records if r.kind != "sequence")
    again = shortened_trace(doc, 1.5)
    assert again.records == short.records


def test_pool_wait_healthy_probes_fresh_pool():
    """Endpoints start optimistically healthy; wait_healthy must not
    vouch for a fresh pool without issuing a single probe."""
    from client_tpu._base import InferenceServerClientBase
    from client_tpu.pool import PoolClient

    class DownStub(InferenceServerClientBase):
        def __init__(self, url):
            super().__init__()
            self.url = url

        def is_server_ready(self, probe=False, client_timeout=None, **kw):
            return False

        def close(self):
            pass

    pool = PoolClient(["u1", "u2"], client_factory=DownStub,
                      health_interval_s=None)
    try:
        assert pool.wait_healthy(timeout_s=0.3) is False
        assert pool.wait_healthy(min_healthy=0, timeout_s=0.2) is True
    finally:
        pool.close()


# -- replay engine ------------------------------------------------------------
def test_run_trace_rejects_bad_inputs():
    runner = PerfRunner.__new__(PerfRunner)  # no server needed
    runner.protocol = "grpc"
    runner.shared_memory = "none"
    with pytest.raises(ValueError, match="empty trace"):
        PerfRunner.run_trace(runner, [])
    stream_rec = trace.TraceRecord(
        at_s=0.0, kind="generate_stream", model="m",
        prompt_tokens=4, output_tokens=2)
    with pytest.raises(ValueError, match="HTTP SSE"):
        PerfRunner.run_trace(runner, [stream_rec])
    runner.protocol = "native"
    with pytest.raises(ValueError, match="python frontend"):
        PerfRunner.run_trace(runner, [stream_rec])
    runner.protocol = "http"
    runner.shared_memory = "tpu"
    with pytest.raises(ValueError, match="shared-memory none"):
        PerfRunner.run_trace(runner, [stream_rec])
    runner.shared_memory = "none"
    with pytest.raises(ValueError, match="speed"):
        PerfRunner.run_trace(runner, [stream_rec], speed=0.0)


def test_stream_dead_before_first_chunk_counts_bad_on_ttft_slo():
    """A stream that errors before any chunk has no TTFT sample — it must
    count BAD on a ttft SLO (same rule as errored unary requests), never
    vanish from the verdict."""
    tel = Telemetry()
    tel.track_slo("ttft", "ttft_ms", threshold_ms=100.0)
    span = tel.begin_stream("http", "m")
    tel.finish_stream(span, error=RuntimeError("connect reset pre-token"))
    row = tel.slo_report()[0]
    assert row["bad"] == 1 and row["good"] == 0
    assert row["attained"] is False


def test_errored_stream_counts_bad_on_duration_slo():
    """A truncated (errored) stream's short duration must never count as
    a GOOD duration event — the session did not complete inside the
    objective, it did not complete at all."""
    tel = Telemetry()
    tel.track_slo("dur", "stream_duration_ms", threshold_ms=5000.0)
    span = tel.begin_stream("http", "m")
    span.mark()  # one chunk arrived, then the stream died
    tel.finish_stream(span, error=RuntimeError("reset mid-stream"))
    row = tel.slo_report()[0]
    assert row["bad"] == 1 and row["good"] == 0


def test_slo_report_zero_events_not_attained():
    """A declared objective that never received an event must not be
    certified as met (a ttft SLO on a unary-only replay, say)."""
    tel = Telemetry()
    tel.track_slo("ttft", "ttft_ms", threshold_ms=100.0)
    span = tel.begin("http", "m")
    tel.finish(span)  # unary span: feeds no ttft events
    row = tel.slo_report()[0]
    assert row["events"] == 0 and row["attained"] is False


@pytest.mark.replay_smoke
def test_mixed_trace_replay_smoke_threaded_server():
    """The acceptance-shaped smoke: a seeded mixed-kind trace replayed
    open-loop against the in-repo threaded server. Every record must
    complete without error, sequence steps must hit the server in order
    (the accumulator proves it), per-kind percentiles and SLO verdicts
    must be present, and offered-vs-achieved rates reported."""
    tr = trace.generate(
        "mixed:duration_s=2,rate=25,stream_fraction=0.15,"
        "seq_fraction=0.15,output_mean=3,max_output=5", seed=13)
    counts = tr.kind_counts()
    # the spec mixes unary + stream + sequence (sharded stays 0: the
    # spec requests none)
    assert all(counts[k] > 0
               for k in ("unary", "generate_stream", "sequence")), counts
    seq_results = {}

    def on_result(rec, outcome):
        if rec.kind == "sequence" and not isinstance(outcome, Exception):
            seq_results[(rec.seq_group, rec.seq_index)] = int(
                outcome.as_numpy("OUTPUT")[0, 0])

    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(server.url, "http", "simple")
        try:
            row = runner.run_trace(
                tr, speed=1.5, replay_workers=12,
                slos=["ttft_p95<5000ms", "p99<5000ms", "error_rate<1%"],
                on_result=on_result)
        finally:
            runner.close()

    assert row["issued"] == len(tr.records)
    assert row["errors"] == 0 and row["shed"] == 0, row["error_sample"]
    assert set(row["kinds"]) == {"unary", "generate_stream", "sequence"}
    for kind_row in row["kinds"].values():
        assert kind_row["latency_ms"]["p99"] >= kind_row["latency_ms"]["p50"]
    assert row["offered_rate"] > 0 and row["achieved_rate"] > 0
    assert row["achieved_arrival_rate"] > 0
    assert row["schedule_lag_ms"]["max"] >= 0
    # stream kinds carried TTFT/ITL sourced from StreamSpans
    assert row["client_stream_ms"]["ttft_ms"]["count"] == \
        counts["generate_stream"]
    assert row["slo_ok"] is True, row["slo"]
    assert {r["slo"] for r in row["slo"]} == \
        {"ttft_p95<5000ms", "p99<5000ms", "error_rate<1%"}
    # request_ms population: exactly ONE event per unary/sequence record
    # (never inner-dispatch or hedge-attempt spans)
    p99_row = next(r for r in row["slo"] if r["slo"] == "p99<5000ms")
    assert p99_row["events"] == counts["unary"] + counts["sequence"]
    # sequence ordering: the accumulator's running total at step k is
    # (k+1) * v where v is the (cached, constant) step value — any
    # out-of-order or resent step would break the arithmetic progression
    groups = {g for g, _ in seq_results}
    assert len(groups) == row["sequence_groups"]
    for group in groups:
        steps = sorted(i for g, i in seq_results if g == group)
        assert steps == list(range(len(steps)))
        v = seq_results[(group, 0)]
        for i in steps:
            assert seq_results[(group, i)] == (i + 1) * v, \
                (group, i, v, seq_results)


def test_replay_instantaneous_burst_uses_header_duration():
    """All arrivals at offset 0 (a pure burst): offered_rate must fall
    back to the header's declared span instead of dividing by ~0 and
    producing an unsatisfiable 1e9 req/s."""
    layout = ({"INPUT0": [1, 16], "INPUT1": [1, 16]},
              {"INPUT0": "INT32", "INPUT1": "INT32"})
    recs = [trace.TraceRecord(at_s=0.0, kind="unary", model="simple",
                              shapes=layout[0], dtypes=layout[1])
            for _ in range(4)]
    tr = trace.Trace(header={"duration_s": 2.0}, records=recs)
    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(server.url, "http", "simple")
        try:
            row = runner.run_trace(tr, speed=1.0, replay_workers=4)
        finally:
            runner.close()
    assert row["requests"] == 4
    assert row["offered_rate"] == 2.0  # 4 records over the declared 2 s


def test_replay_abandons_sequence_group_after_failed_step():
    """A failed sequence step poisons its group: later steps must not be
    sent into server state that never saw the failure — they count as
    errors ('group abandoned'), never as served."""
    layout = ({"INPUT": [1, 1]}, {"INPUT": "INT32"})
    recs = [trace.TraceRecord(
        at_s=0.01 * i, kind="sequence", model="no_such_model",
        shapes=layout[0], dtypes=layout[1],
        seq_group=1, seq_index=i, seq_len=3) for i in range(3)]
    dispatched = []

    def on_result(rec, outcome):
        dispatched.append((rec.seq_index, outcome))

    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(server.url, "http", "simple")
        try:
            row = runner.run_trace(recs, speed=2.0, replay_workers=3,
                                   on_result=on_result)
        finally:
            runner.close()
    assert row["errors"] == 3 and row["requests"] == 0
    later = {i: outcome for i, outcome in dispatched if i > 0}
    assert len(later) == 2
    for outcome in later.values():
        assert "abandoned" in str(outcome), outcome


def test_spanless_stream_failures_count_bad_on_stream_slos():
    """Streams that fail before a StreamSpan exists (pool endpoint
    selection with every replica down) must still count BAD on span-fed
    ttft/duration SLOs — not vanish from the verdict."""
    recs = [trace.TraceRecord(at_s=0.02 * i, kind="generate_stream",
                              model="tiny_lm_generate",
                              prompt_tokens=4, output_tokens=2)
            for i in range(3)]
    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        # control plane on the live server; the POOL has one dead replica
        runner = PerfRunner(server.url, "http", "simple",
                            endpoints=["127.0.0.1:1"])
        try:
            row = runner.run_trace(recs, speed=4.0, replay_workers=3,
                                   slos=["ttft_p95<5s", "duration_p90<5s"])
        finally:
            runner.close()
    assert row["errors"] + row["shed"] == 3
    for slo_row in row["slo"]:
        assert slo_row["bad"] == 3 and slo_row["good"] == 0, slo_row
        assert slo_row["attained"] is False
    assert row["slo_ok"] is False


def test_nonfinite_generator_params_rejected():
    """inf/nan duration or rate would make the arrival loop walk forever
    — reject at the boundary instead of hanging the CLI."""
    for override in ({"duration_s": float("inf")}, {"rate": float("nan")}):
        params = {"duration_s": 1.0, "rate": 10.0, **override}
        with pytest.raises(ValueError, match="finite"):
            trace.poisson_burst(seed=0, **params)


def test_burst_over_budget_rejected():
    """burst_factor*duty > 1 cannot preserve the declared mean rate (the
    off-phase clamps at 0): reject instead of silently over-offering."""
    with pytest.raises(ValueError, match="burst_factor"):
        trace.poisson_burst(seed=0, duration_s=2.0, rate=50.0,
                            burst_factor=5.0, duty=0.25)
    # product == 1 is the degenerate-but-exact boundary: all mass in the
    # burst, long-run mean still equal to the declared rate
    assert trace.poisson_burst(seed=0, duration_s=2.0, rate=50.0,
                               burst_factor=4.0, duty=0.25)


def test_replay_reports_errors_without_aborting():
    """Records targeting a missing model count as errors; the replay
    completes and the error-rate SLO verdict reflects them."""
    recs = [trace.TraceRecord(at_s=0.01 * i, kind="unary", model="no_such",
                              shapes={"INPUT0": [1, 16]},
                              dtypes={"INPUT0": "INT32"})
            for i in range(10)]
    core = ServerCore(default_model_zoo())
    with HttpInferenceServer(core) as server:
        runner = PerfRunner(server.url, "http", "simple")
        try:
            row = runner.run_trace(recs, speed=4.0, replay_workers=4,
                                   slos=["error_rate<1%"])
        finally:
            runner.close()
    assert row["issued"] == 10 and row["errors"] == 10
    assert row["error_rate"] == 1.0
    assert row["slo_ok"] is False
    err_row = next(r for r in row["slo"] if r["metric"] == "error_rate")
    assert err_row["attained"] is False and err_row["value"] == 1.0
