"""Wire-codec property/fuzz tests: random messages round-trip exactly and
random bytes never crash the decoder with anything but ValueError."""

import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from client_tpu.grpc import _messages as M
from client_tpu.grpc._wire import decode_message, encode_message

_names = st.text(
    alphabet=st.characters(min_codepoint=32, max_codepoint=126), max_size=12
)


@st.composite
def infer_requests(draw):
    """Random-but-valid ModelInferRequest dicts."""
    request = {"model_name": draw(_names), "id": draw(_names)}
    inputs = []
    for _ in range(draw(st.integers(0, 3))):
        tensor = {
            "name": draw(_names),
            "datatype": draw(st.sampled_from(["INT32", "FP32", "BYTES", "BF16"])),
            "shape": draw(st.lists(st.integers(-1, 1 << 40), max_size=4)),
        }
        params = {}
        for key in draw(st.lists(_names.filter(bool), max_size=2, unique=True)):
            params[key] = draw(
                st.sampled_from(
                    [
                        {"bool_param": draw(st.booleans())},
                        {"int64_param": draw(st.integers(-(1 << 62), 1 << 62))},
                        {"string_param": draw(_names)},
                        {"double_param": draw(st.floats(allow_nan=False, width=64))},
                    ]
                )
            )
        if params:
            tensor["parameters"] = params
        inputs.append(tensor)
    if inputs:
        request["inputs"] = inputs
    raws = draw(st.lists(st.binary(max_size=64), max_size=3))
    if raws:
        request["raw_input_contents"] = raws
    return request


@given(infer_requests())
@settings(max_examples=150, deadline=None)
def test_infer_request_roundtrip_property(request):
    decoded = decode_message(
        M.MODEL_INFER_REQUEST, encode_message(M.MODEL_INFER_REQUEST, request)
    )
    # proto3 semantics: default-valued non-oneof fields vanish on the wire
    for key, value in request.items():
        if key in ("model_name", "id"):
            if value:
                assert decoded[key] == value
            else:
                assert key not in decoded
        elif key == "raw_input_contents":
            assert decoded[key] == value
        elif key == "inputs":
            assert len(decoded[key]) == len(value)
            for got, want in zip(decoded[key], value):
                assert got.get("name", "") == want.get("name", "")
                assert got.get("datatype", "") == want.get("datatype", "")
                assert got.get("shape", []) == [int(d) for d in want.get("shape", [])]
                if want.get("parameters"):
                    assert "parameters" in got, "parameters dropped by codec"
                    for pk, pv in want["parameters"].items():
                        assert got["parameters"][pk] == pv


@given(st.binary(max_size=200))
@settings(max_examples=300, deadline=None)
def test_decoder_never_crashes_on_garbage(data):
    """Arbitrary bytes: decode either succeeds or raises ValueError — never
    IndexError/struct.error/KeyError/segfault."""
    for spec in (M.MODEL_INFER_REQUEST, M.MODEL_INFER_RESPONSE, M.MODEL_CONFIG):
        try:
            decode_message(spec, data)
        except ValueError:
            pass


@given(st.binary(max_size=100), st.integers(0, 100))
@settings(max_examples=200, deadline=None)
def test_bytes_deserializer_never_crashes(data, count):
    from client_tpu.utils import InferenceServerException, deserialize_bytes_tensor

    try:
        out = deserialize_bytes_tensor(data, count=count)
        assert out.dtype == np.object_
    except InferenceServerException:
        pass
