"""Wire-codec property/fuzz tests: random messages round-trip exactly and
random bytes never crash the decoder with anything but ValueError.

Deterministic seeded fuzzing (no ``hypothesis`` dependency — the
previous version failed COLLECTION on machines without it, so tier-1
never ran these at all): every case is a pure function of a fixed seed,
so a failure reproduces exactly by its printed case index. The
generators mirror the original strategies — random-but-valid
ModelInferRequest dicts for the round-trip property, raw byte soup for
the never-crash properties.
"""

import random
import string

import numpy as np
import pytest

from client_tpu.grpc import _messages as M
from client_tpu.grpc._wire import decode_message, encode_message

_SEED = 0xF022
# codepoints 32..126 — the original strategy's alphabet, space included
_NAME_ALPHABET = (string.digits + string.ascii_letters
                  + string.punctuation + " ")


def _name(rng: random.Random, max_size: int = 12) -> str:
    return "".join(
        rng.choice(_NAME_ALPHABET) for _ in range(rng.randint(0, max_size)))


def _param_value(rng: random.Random) -> dict:
    kind = rng.randrange(4)
    if kind == 0:
        return {"bool_param": rng.random() < 0.5}
    if kind == 1:
        return {"int64_param": rng.randint(-(1 << 62), 1 << 62)}
    if kind == 2:
        return {"string_param": _name(rng)}
    # finite doubles only (NaN would fail == in the round-trip assert)
    return {"double_param": rng.uniform(-1e300, 1e300)}


def _infer_request(rng: random.Random) -> dict:
    """One random-but-valid ModelInferRequest dict (mirrors the original
    hypothesis strategy, including negative/huge shape dims)."""
    request = {"model_name": _name(rng), "id": _name(rng)}
    inputs = []
    for _ in range(rng.randint(0, 3)):
        tensor = {
            "name": _name(rng),
            "datatype": rng.choice(["INT32", "FP32", "BYTES", "BF16"]),
            "shape": [rng.randint(-1, 1 << 40)
                      for _ in range(rng.randint(0, 4))],
        }
        params = {}
        for _ in range(rng.randint(0, 2)):
            key = _name(rng)
            if key:
                params[key] = _param_value(rng)
        if params:
            tensor["parameters"] = params
        inputs.append(tensor)
    if inputs:
        request["inputs"] = inputs
    raws = [rng.randbytes(rng.randint(0, 64))
            for _ in range(rng.randint(0, 3))]
    if raws:
        request["raw_input_contents"] = raws
    return request


@pytest.mark.parametrize("case", range(150))
def test_infer_request_roundtrip_property(case):
    rng = random.Random((_SEED << 16) | case)
    request = _infer_request(rng)
    decoded = decode_message(
        M.MODEL_INFER_REQUEST, encode_message(M.MODEL_INFER_REQUEST, request)
    )
    # proto3 semantics: default-valued non-oneof fields vanish on the wire
    for key, value in request.items():
        if key in ("model_name", "id"):
            if value:
                assert decoded[key] == value, f"case {case}"
            else:
                assert key not in decoded, f"case {case}"
        elif key == "raw_input_contents":
            assert decoded[key] == value, f"case {case}"
        elif key == "inputs":
            assert len(decoded[key]) == len(value), f"case {case}"
            for got, want in zip(decoded[key], value):
                assert got.get("name", "") == want.get("name", "")
                assert got.get("datatype", "") == want.get("datatype", "")
                assert got.get("shape", []) == [
                    int(d) for d in want.get("shape", [])]
                if want.get("parameters"):
                    assert "parameters" in got, \
                        f"case {case}: parameters dropped by codec"
                    for pk, pv in want["parameters"].items():
                        assert got["parameters"][pk] == pv, f"case {case}"


def _garbage(rng: random.Random, max_size: int) -> bytes:
    """Byte soup biased toward protobuf-shaped prefixes: purely random
    bytes usually die on the first tag, so half the cases splice valid
    field tags in front of random payloads to reach deeper decoder
    paths (the same depth hypothesis found by shrinking)."""
    raw = rng.randbytes(rng.randint(0, max_size))
    if rng.random() < 0.5:
        field = rng.randint(1, 15)
        wire_type = rng.choice([0, 1, 2, 5])
        raw = bytes([(field << 3) | wire_type]) + raw
    return raw


@pytest.mark.parametrize("case", range(300))
def test_decoder_never_crashes_on_garbage(case):
    """Arbitrary bytes: decode either succeeds or raises ValueError — never
    IndexError/struct.error/KeyError/segfault."""
    rng = random.Random((_SEED << 17) | case)
    data = _garbage(rng, 200)
    for spec in (M.MODEL_INFER_REQUEST, M.MODEL_INFER_RESPONSE,
                 M.MODEL_CONFIG):
        try:
            decode_message(spec, data)
        except ValueError:
            pass


@pytest.mark.parametrize("case", range(200))
def test_bytes_deserializer_never_crashes(case):
    from client_tpu.utils import InferenceServerException, deserialize_bytes_tensor

    rng = random.Random((_SEED << 18) | case)
    data = rng.randbytes(rng.randint(0, 100))
    count = rng.randint(0, 100)
    try:
        out = deserialize_bytes_tensor(data, count=count)
        assert out.dtype == np.object_
    except InferenceServerException:
        pass


# -- response-side fuzzing -----------------------------------------------------
# The request-side properties above pin the ENCODER/DECODER pair; these
# pin the client's RESPONSE parse path against a byzantine or corrupted
# server: whatever bytes arrive, the parser either produces a result
# whose views are structurally sound or raises the TYPED client
# exception (IntegrityError is a subclass) — never struct.error,
# UnicodeDecodeError, KeyError, or a garbage-length numpy view.

def _valid_response_body(rng: random.Random):
    """One valid HTTP infer response: JSON header + binary tail."""
    import json

    n = rng.randint(1, 8)
    data = bytes(rng.randbytes(4 * n))
    header = {
        "model_name": "m", "id": "rq",
        "outputs": [{
            "name": "OUT", "datatype": "INT32", "shape": [1, n],
            "parameters": {"binary_data_size": 4 * n},
        }],
    }
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return hdr + data, len(hdr)


@pytest.mark.parametrize("case", range(200))
def test_http_response_parser_never_crashes_on_garbage(case):
    """Pure byte soup (with and without a header-length claim): the
    response parser raises typed or returns a parsed result."""
    from client_tpu.http._infer_result import InferResult
    from client_tpu.utils import InferenceServerException

    rng = random.Random((_SEED << 19) | case)
    body = rng.randbytes(rng.randint(0, 160))
    choice = rng.randrange(3)
    header_length = (None if choice == 0
                     else rng.randint(0, len(body) + 20) if choice == 1
                     else len(body))
    try:
        InferResult.from_response_body(body, header_length)
    except InferenceServerException:
        pass  # the one legal failure mode (IntegrityError included)


@pytest.mark.parametrize("case", range(200))
def test_http_response_parser_mutated_valid_body(case):
    """Mutations of a VALID response (truncation, over-length claims,
    header bit-flips, appended junk): parse + as_numpy either succeed
    with a structurally-sound array or raise typed — a wrong-size view
    is never handed back."""
    from client_tpu.http._infer_result import InferResult
    from client_tpu.utils import InferenceServerException

    rng = random.Random((_SEED << 20) | case)
    body, json_size = _valid_response_body(rng)
    mutation = rng.randrange(4)
    if mutation == 0:    # truncate anywhere
        body = body[: rng.randint(0, len(body))]
    elif mutation == 1:  # claim more header than exists
        json_size = json_size + rng.randint(1, 64)
    elif mutation == 2:  # flip bytes inside the JSON header
        buf = bytearray(body)
        for _ in range(rng.randint(1, 4)):
            buf[rng.randrange(json_size)] ^= rng.randrange(1, 256)
        body = bytes(buf)
    else:                # append junk past the declared tail
        body = body + rng.randbytes(rng.randint(1, 32))
    try:
        result = InferResult.from_response_body(body, min(json_size,
                                                          len(body)))
        arr = result.as_numpy("OUT")
        if arr is not None:
            # a delivered view must be exactly the claimed span
            assert arr.dtype == np.int32
            assert arr.nbytes == 4 * arr.size
    except InferenceServerException:
        pass


@pytest.mark.parametrize("case", range(150))
def test_bytes_framing_walk_never_crashes(case):
    """walk_bytes_framing on arbitrary buffers: returns the element
    count it walked or raises a typed IntegrityError — the BYTES
    length-prefix chain is walked to exhaustion, never trusted."""
    from client_tpu.integrity import IntegrityError, walk_bytes_framing

    rng = random.Random((_SEED << 21) | case)
    if rng.random() < 0.5:
        buf = rng.randbytes(rng.randint(0, 80))
    else:
        # framing-shaped: a few length-prefixed elements, then corruption
        parts = []
        for _ in range(rng.randint(1, 4)):
            blob = rng.randbytes(rng.randint(0, 12))
            parts.append(len(blob).to_bytes(4, "little") + blob)
        buf = b"".join(parts) + rng.randbytes(rng.randint(0, 8))
    count = rng.randint(0, 8)
    try:
        walked = walk_bytes_framing(buf, count, "u", "f")
        assert isinstance(walked, int)
    except IntegrityError:
        pass


@pytest.mark.parametrize("case", range(150))
def test_sse_event_parser_never_crashes(case):
    """Generate-stream SSE payload soup: parse_sse_event returns a dict
    or raises the typed client exception — non-UTF-8 and non-object
    payloads must not leak UnicodeDecodeError/AttributeError."""
    import json

    from client_tpu.http._utils import parse_sse_event
    from client_tpu.utils import InferenceServerException

    rng = random.Random((_SEED << 22) | case)
    choice = rng.randrange(3)
    if choice == 0:
        payload = rng.randbytes(rng.randint(0, 60))
    elif choice == 1:
        payload = json.dumps(rng.choice(
            [[1, 2], "str", 7, None, {"INDEX": [rng.randint(-5, 5)]},
             {"error": "boom"}])).encode()
    else:
        payload = b'{"OUT": [' + rng.randbytes(rng.randint(0, 10)) + b"]}"
    try:
        event = parse_sse_event(payload)
        assert isinstance(event, dict)
    except InferenceServerException:
        pass
