"""Wire-codec property/fuzz tests: random messages round-trip exactly and
random bytes never crash the decoder with anything but ValueError.

Deterministic seeded fuzzing (no ``hypothesis`` dependency — the
previous version failed COLLECTION on machines without it, so tier-1
never ran these at all): every case is a pure function of a fixed seed,
so a failure reproduces exactly by its printed case index. The
generators mirror the original strategies — random-but-valid
ModelInferRequest dicts for the round-trip property, raw byte soup for
the never-crash properties.
"""

import random
import string

import numpy as np
import pytest

from client_tpu.grpc import _messages as M
from client_tpu.grpc._wire import decode_message, encode_message

_SEED = 0xF022
# codepoints 32..126 — the original strategy's alphabet, space included
_NAME_ALPHABET = (string.digits + string.ascii_letters
                  + string.punctuation + " ")


def _name(rng: random.Random, max_size: int = 12) -> str:
    return "".join(
        rng.choice(_NAME_ALPHABET) for _ in range(rng.randint(0, max_size)))


def _param_value(rng: random.Random) -> dict:
    kind = rng.randrange(4)
    if kind == 0:
        return {"bool_param": rng.random() < 0.5}
    if kind == 1:
        return {"int64_param": rng.randint(-(1 << 62), 1 << 62)}
    if kind == 2:
        return {"string_param": _name(rng)}
    # finite doubles only (NaN would fail == in the round-trip assert)
    return {"double_param": rng.uniform(-1e300, 1e300)}


def _infer_request(rng: random.Random) -> dict:
    """One random-but-valid ModelInferRequest dict (mirrors the original
    hypothesis strategy, including negative/huge shape dims)."""
    request = {"model_name": _name(rng), "id": _name(rng)}
    inputs = []
    for _ in range(rng.randint(0, 3)):
        tensor = {
            "name": _name(rng),
            "datatype": rng.choice(["INT32", "FP32", "BYTES", "BF16"]),
            "shape": [rng.randint(-1, 1 << 40)
                      for _ in range(rng.randint(0, 4))],
        }
        params = {}
        for _ in range(rng.randint(0, 2)):
            key = _name(rng)
            if key:
                params[key] = _param_value(rng)
        if params:
            tensor["parameters"] = params
        inputs.append(tensor)
    if inputs:
        request["inputs"] = inputs
    raws = [rng.randbytes(rng.randint(0, 64))
            for _ in range(rng.randint(0, 3))]
    if raws:
        request["raw_input_contents"] = raws
    return request


@pytest.mark.parametrize("case", range(150))
def test_infer_request_roundtrip_property(case):
    rng = random.Random((_SEED << 16) | case)
    request = _infer_request(rng)
    decoded = decode_message(
        M.MODEL_INFER_REQUEST, encode_message(M.MODEL_INFER_REQUEST, request)
    )
    # proto3 semantics: default-valued non-oneof fields vanish on the wire
    for key, value in request.items():
        if key in ("model_name", "id"):
            if value:
                assert decoded[key] == value, f"case {case}"
            else:
                assert key not in decoded, f"case {case}"
        elif key == "raw_input_contents":
            assert decoded[key] == value, f"case {case}"
        elif key == "inputs":
            assert len(decoded[key]) == len(value), f"case {case}"
            for got, want in zip(decoded[key], value):
                assert got.get("name", "") == want.get("name", "")
                assert got.get("datatype", "") == want.get("datatype", "")
                assert got.get("shape", []) == [
                    int(d) for d in want.get("shape", [])]
                if want.get("parameters"):
                    assert "parameters" in got, \
                        f"case {case}: parameters dropped by codec"
                    for pk, pv in want["parameters"].items():
                        assert got["parameters"][pk] == pv, f"case {case}"


def _garbage(rng: random.Random, max_size: int) -> bytes:
    """Byte soup biased toward protobuf-shaped prefixes: purely random
    bytes usually die on the first tag, so half the cases splice valid
    field tags in front of random payloads to reach deeper decoder
    paths (the same depth hypothesis found by shrinking)."""
    raw = rng.randbytes(rng.randint(0, max_size))
    if rng.random() < 0.5:
        field = rng.randint(1, 15)
        wire_type = rng.choice([0, 1, 2, 5])
        raw = bytes([(field << 3) | wire_type]) + raw
    return raw


@pytest.mark.parametrize("case", range(300))
def test_decoder_never_crashes_on_garbage(case):
    """Arbitrary bytes: decode either succeeds or raises ValueError — never
    IndexError/struct.error/KeyError/segfault."""
    rng = random.Random((_SEED << 17) | case)
    data = _garbage(rng, 200)
    for spec in (M.MODEL_INFER_REQUEST, M.MODEL_INFER_RESPONSE,
                 M.MODEL_CONFIG):
        try:
            decode_message(spec, data)
        except ValueError:
            pass


@pytest.mark.parametrize("case", range(200))
def test_bytes_deserializer_never_crashes(case):
    from client_tpu.utils import InferenceServerException, deserialize_bytes_tensor

    rng = random.Random((_SEED << 18) | case)
    data = rng.randbytes(rng.randint(0, 100))
    count = rng.randint(0, 100)
    try:
        out = deserialize_bytes_tensor(data, count=count)
        assert out.dtype == np.object_
    except InferenceServerException:
        pass
