"""Slot-based sequence batcher (decoder_lm_batched).

The reference's sequence batcher (direct mode) pins: per-sequence state in
batch slots, one execution advancing every live slot, per-CORRID
serialization, slot exhaustion as a request error. Here the batched model
must additionally be bit-comparable with the unbatched decoder_lm (the
vmapped step is the same math) — the strongest regression net available.
"""

import random
import threading
import time

import numpy as np
import pytest

from client_tpu.models.decoder import TinyDecoderModel
from client_tpu.models.decoder_batched import BatchedDecoderModel


def _drive(model, seq, prompt, n=6, jitter=None):
    p = {"sequence_id": seq, "sequence_start": True, "sequence_end": False}
    out = model.execute({"TOKENS": np.array([prompt], np.int32)}, p)
    tok = int(out["NEXT_TOKEN"][0, 0])
    toks = [tok]
    for i in range(n - 1):
        if jitter is not None:
            time.sleep(jitter.random() * 0.003)
        p = {"sequence_id": seq, "sequence_start": False,
             "sequence_end": i == n - 2}
        out = model.execute({"TOKENS": np.array([[tok]], np.int32)}, p)
        tok = int(out["NEXT_TOKEN"][0, 0])
        toks.append(tok)
    return toks


def test_concurrent_sequences_match_unbatched():
    ref = TinyDecoderModel(seed=0)
    bat = BatchedDecoderModel(seed=0, slots=4)
    prompts = {101: [1, 2, 3], 102: [9, 8, 7, 6], 103: [42]}
    expected = {s: _drive(ref, s, p) for s, p in prompts.items()}

    results, errors = {}, []

    def worker(s, p):
        try:
            results[s] = _drive(bat, s, p)
        except Exception as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s, p))
               for s, p in prompts.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert results == expected
    assert bat.live_sequences() == 0
    # the point of the component: concurrent steps shared dispatches
    assert any(width > 1 for width in bat.batch_histogram), bat.batch_histogram


def test_stress_window_composition_invariance():
    """Invariant: window composition never changes any sequence's tokens.

    20 seeded iterations of randomly-timed concurrent clients — including
    mid-flight restarts, the round-3 flake's second repro — against one
    batcher; every sequence's greedy tokens must equal the unbatched
    decoder's every time. Guards the round-3 nondeterminism (in-place
    mutation of the host pos buffer racing the async dispatch)."""
    ref = TinyDecoderModel(seed=0)
    bat = BatchedDecoderModel(seed=0, slots=4, max_delay_s=0.004)
    pool = [[1, 2, 3], [9, 8, 7, 6], [42], [5, 6], [77, 1], [3]]
    expected = {}

    def exp(prompt, n):
        key = (tuple(prompt), n)
        if key not in expected:
            expected[key] = _drive(ref, 999, prompt, n=n)
        return expected[key]

    for it in range(20):
        rng = random.Random(1000 + it)
        jobs = []  # (seq_id, prompt, n, restart_mid_flight)
        for s in range(4):
            jobs.append((it * 10 + s + 1, rng.choice(pool),
                         rng.randint(2, 7), rng.random() < 0.3))
        results, errors = {}, []

        def worker(seq, prompt, n, restart, seed):
            r = random.Random(seed)
            try:
                if restart:
                    # open the sequence, then sequence_start again on a
                    # live slot (restart in place) via _drive below
                    bat.execute(
                        {"TOKENS": np.array([prompt], np.int32)},
                        {"sequence_id": seq, "sequence_start": True})
                    time.sleep(r.random() * 0.003)
                results[seq] = _drive(bat, seq, prompt, n=n, jitter=r)
            except Exception as e:
                errors.append((seq, e))

        threads = [threading.Thread(target=worker, args=(s, p, n, re, i))
                   for i, (s, p, n, re) in enumerate(jobs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, (it, errors)
        for seq, prompt, n, _ in jobs:
            assert results[seq] == exp(prompt, n), (it, seq)
    assert bat.live_sequences() == 0
    assert any(width > 1 for width in bat.batch_histogram), bat.batch_histogram


def test_slot_exhaustion_is_a_request_error():
    bat = BatchedDecoderModel(seed=0, slots=2)
    for seq in (1, 2):
        bat.execute({"TOKENS": np.array([[5]], np.int32)},
                    {"sequence_id": seq, "sequence_start": True})
    with pytest.raises(ValueError, match="no free sequence slot"):
        bat.execute({"TOKENS": np.array([[5]], np.int32)},
                    {"sequence_id": 3, "sequence_start": True})
    # ending one frees its slot for a new sequence
    bat.execute({"TOKENS": np.array([[6]], np.int32)},
                {"sequence_id": 1, "sequence_start": False,
                 "sequence_end": True})
    bat.execute({"TOKENS": np.array([[5]], np.int32)},
                {"sequence_id": 3, "sequence_start": True,
                 "sequence_end": True})
    bat.execute({"TOKENS": np.array([[5]], np.int32)},
                {"sequence_id": 2, "sequence_start": False,
                 "sequence_end": True})
    assert bat.live_sequences() == 0


def test_validation_errors():
    bat = BatchedDecoderModel(seed=0, slots=2)
    with pytest.raises(ValueError, match="sequence_id"):
        bat.execute({"TOKENS": np.array([[1]], np.int32)}, {})
    with pytest.raises(ValueError, match="no live state"):
        bat.execute({"TOKENS": np.array([[1]], np.int32)},
                    {"sequence_id": 77})
    with pytest.raises(ValueError, match="exactly one token"):
        bat.execute({"TOKENS": np.array([[1, 2]], np.int32)},
                    {"sequence_id": 77})
    with pytest.raises(ValueError, match="out of range"):
        bat.execute({"TOKENS": np.array([[999]], np.int32)},
                    {"sequence_id": 77, "sequence_start": True})
    with pytest.raises(ValueError, match="empty prompt"):
        bat.execute({"TOKENS": np.zeros((1, 0), np.int32)},
                    {"sequence_id": 77, "sequence_start": True})
    # the model must still serve after rejected requests (worker alive)
    out = bat.execute({"TOKENS": np.array([[3]], np.int32)},
                      {"sequence_id": 78, "sequence_start": True,
                       "sequence_end": True})
    assert out["NEXT_TOKEN"].shape == (1, 1)


def test_overflow_frees_slot():
    bat = BatchedDecoderModel(seed=0, slots=1)
    too_long = list(range(10, 10 + TinyDecoderModel.MAX_LEN + 1))
    with pytest.raises(ValueError, match="max_len"):
        bat.execute({"TOKENS": np.array([too_long], np.int32)},
                    {"sequence_id": 5, "sequence_start": True})
    # the failed start must not leak its slot
    bat.execute({"TOKENS": np.array([[5]], np.int32)},
                {"sequence_id": 6, "sequence_start": True,
                 "sequence_end": True})
    assert bat.live_sequences() == 0


def test_restart_in_place():
    """sequence_start on a live sequence restarts it in its slot."""
    ref = TinyDecoderModel(seed=0)
    bat = BatchedDecoderModel(seed=0, slots=2)
    _drive(bat, 9, [1, 2, 3], n=2)  # leaves seq 9 ended... start fresh:
    bat.execute({"TOKENS": np.array([[4]], np.int32)},
                {"sequence_id": 9, "sequence_start": True})
    # restart mid-flight (_drive opens with sequence_start and ends the
    # sequence on its last request)
    toks_restart = _drive(bat, 9, [1, 2, 3], n=4)
    assert toks_restart == _drive(ref, 9, [1, 2, 3], n=4)
    assert bat.live_sequences() == 0


def test_unload_rejects_and_strands_nothing():
    bat = BatchedDecoderModel(seed=0, slots=2)
    bat.execute({"TOKENS": np.array([[3]], np.int32)},
                {"sequence_id": 1, "sequence_start": True,
                 "sequence_end": True})
    bat.unload()
    with pytest.raises(ValueError, match="shutting down"):
        bat.execute({"TOKENS": np.array([[3]], np.int32)},
                    {"sequence_id": 2, "sequence_start": True})


def test_idle_sequences_are_reaped():
    """Abandoned mid-sequence clients must not hold slots forever.

    Reference semantics: max_sequence_idle_microseconds in tritonserver's
    sequence batcher. Fill every slot with sequences that never end (the
    120 s-timeout abandonment shape: client walked away mid-sequence),
    wait past the TTL, then start `slots` fresh sequences — all must be
    admitted because the reaper freed the abandoned slots at window start.
    """
    slots = 3
    bat = BatchedDecoderModel(seed=0, slots=slots, idle_ttl_s=1.0)
    # warm up (first dispatch jit-compiles, which would eat the TTL and
    # reap earlier starts before the fill loop even finishes)
    bat.execute({"TOKENS": np.array([[1]], np.int32)},
                {"sequence_id": 999, "sequence_start": True,
                 "sequence_end": True})
    for seq in range(1, slots + 1):
        bat.execute({"TOKENS": np.array([[5]], np.int32)},
                    {"sequence_id": seq, "sequence_start": True})
    assert bat.live_sequences() == slots
    # capacity genuinely exhausted before the TTL expires
    with pytest.raises(ValueError, match="no free sequence slot"):
        bat.execute({"TOKENS": np.array([[5]], np.int32)},
                    {"sequence_id": 100, "sequence_start": True})
    time.sleep(1.5)
    for seq in range(201, 201 + slots):
        out = bat.execute({"TOKENS": np.array([[7]], np.int32)},
                          {"sequence_id": seq, "sequence_start": True,
                           "sequence_end": True})
        assert out["NEXT_TOKEN"].shape == (1, 1)
    assert bat.live_sequences() == 0


def test_active_sequences_survive_the_reaper():
    """A sequence making requests is never reaped even when each request
    gap is a large fraction of the TTL and OTHER sequences keep running
    reap-triggering windows — activity must refresh the idle clock."""
    ref = TinyDecoderModel(seed=0)
    bat = BatchedDecoderModel(seed=0, slots=2, idle_ttl_s=0.3)
    # warm up so compile time doesn't count against the TTL
    bat.execute({"TOKENS": np.array([[1]], np.int32)},
                {"sequence_id": 999, "sequence_start": True,
                 "sequence_end": True})

    class _SlowJitter:
        def random(self):
            return 0.15 / 0.003  # _drive sleeps jitter.random()*0.003

    stop = threading.Event()
    churn_errors = []

    def churn():
        # seq 12 churns fast windows; each one runs the reaper, so a
        # missing last_seen refresh on seq 11 would reap it mid-drive
        seq = 500
        while not stop.is_set():
            try:
                _drive(bat, seq, [3], n=2)
            except Exception as e:
                churn_errors.append(e)
                return
            seq += 1

    t = threading.Thread(target=churn)
    t.start()
    try:
        # ~0.45 s of slow-gap activity: total > TTL, every gap < TTL
        toks = _drive(bat, 11, [1, 2, 3], n=4, jitter=_SlowJitter())
    finally:
        stop.set()
        t.join()
    assert not churn_errors, churn_errors
    assert toks == _drive(ref, 11, [1, 2, 3], n=4)
    assert bat.live_sequences() == 0


def test_served_over_grpc_sequence_api():
    """End-to-end over the wire via the genai sequence harness."""
    from client_tpu.genai_perf import GenAiPerfRunner
    from client_tpu.server import GrpcInferenceServer, ServerCore

    bat = BatchedDecoderModel(seed=0, slots=8)
    with GrpcInferenceServer(ServerCore([bat])) as server:
        runner = GenAiPerfRunner(server.url, "decoder_lm_batched", "sequence",
                                 prompt_tokens=6, output_tokens=5)
        out = runner.run(3, 6)
        assert out["errors"] == 0, out["error_sample"]
        assert out["sessions"] == 6
    assert bat.live_sequences() == 0
    assert any(width > 1 for width in bat.batch_histogram), (
        "3 concurrent wire sessions never shared a dispatch")
